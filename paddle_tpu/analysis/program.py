"""Whole-program jaxpr analyzer: dataflow framework + pass families.

This module turns the PR 1 jaxpr lint into a real program analyzer. It
provides a small dataflow framework over ClosedJaxprs — a labeled
sub-jaxpr walk (pjit / cond / while / scan / custom_vjp / pallas_call),
def-use chains, and per-eqn live ranges — and registers three pass
families alongside the shallow PDT20x checks:

- **PDT22x — collective consistency.** :func:`collective_schedule`
  extracts the ordered collective schedule (psum / ppermute /
  all_gather / ... with axes, shape, dtype) from a program. PDT221
  ERRORs on collectives under divergent ``cond`` branches whose
  schedules differ (an SPMD deadlock: ranks taking different branches
  issue different collective sequences). PDT222 WARNs when an
  axis-size-dependent shape (an ``all_gather`` result) feeds another
  collective — the program silently re-specializes per world size.
  PDT223 is the *runtime* side: :func:`verify_schedule` hashes each
  rank's schedule and cross-checks via the TCP store at group setup,
  catching divergence before the PDT-E021 collective timeout.
- **PDT23x — donation & HBM.** PDT231 ERRORs on read-after-donation
  (a donated input with no shape/dtype-compatible output: its buffer
  is re-used by XLA while the caller may still hold the old handle —
  the orphaned-flat-bucket restore bug class). PDT232 WARNs on
  double-donation (more donated inputs than matching outputs). PDT233
  WARNs on missed donation of *large* (>= 1 MiB) step-carry buffers —
  fused-optimizer flat buckets and engine KV pools are the canonical
  wins. :func:`static_peak_bytes` runs a live-range interval sweep to
  estimate peak HBM per program; the jit layer exposes it as the
  ``hbm.static_peak_bytes{fn}`` gauge next to the measured gauges.
- **PDT24x — recompile risk.** PDT241 WARNs on weak-type promotion
  forks (a weak-typed input hitting a ``convert_element_type`` — the
  same call with a committed array traces differently and forks the
  compile cache). PDT242 is runtime-reported by the jit capture cache
  when one function accumulates >= 3 shape-only signature variants
  (shape-as-data: a traced length/table baked as a static dim — the
  engine's no-recompile contract), and feeds the same
  ``compile.retrace`` event vocabulary as the runtime classifier.

Entry points: :func:`audit_jaxpr` (one ClosedJaxpr),
:func:`audit_executable` (a built ``jit._Executable``; also computes
the static peak estimate), :func:`audit_jitted` (trace a callable with
example args and audit — for raw ``jax.jit`` sites), and
:func:`audit_counts` (process-level per-code tally for bench records).
All are mode-gated by ``PDTPU_ANALYSIS`` and never raise except through
the standard ``report`` gate in error mode.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Iterable, Iterator, Optional

from .registry import Severity, register, register_runtime
from . import engine as _engine

# --------------------------------------------------------------------------
# sub-jaxpr walk
# --------------------------------------------------------------------------

# params holding a single sub-jaxpr (ClosedJaxpr or bare Jaxpr)
_SINGLE_JAXPR_PARAMS = ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr",
                        "fun_jaxpr", "fwd_jaxpr_thunk")


def _as_jaxpr(obj):
    """Unwrap to a bare Jaxpr (obj may be a ClosedJaxpr); None if not a
    jaxpr-like object."""
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    if hasattr(obj, "eqns") and hasattr(obj, "invars"):
        return obj
    return None


def subjaxprs(eqn) -> Iterator[tuple[str, object]]:
    """Yield ``(label, jaxpr)`` for every sub-jaxpr of ``eqn``.

    Covers the higher-order primitives the stack actually emits — pjit,
    cond (``branches`` tuple), while (``cond_jaxpr``/``body_jaxpr``),
    scan, custom_vjp/custom_jvp (``call_jaxpr``/``fun_jaxpr``),
    pallas_call — plus a duck-typed fallback so new primitives still get
    walked. Labels are ``"<param>"`` or ``"<param>[i]"`` for tuples
    (e.g. ``"branches[1]"`` = the cond true-branch)."""
    seen: set[int] = set()
    for name, val in eqn.params.items():
        if callable(val) and not hasattr(val, "eqns") \
                and not hasattr(val, "jaxpr"):
            continue  # thunks (fwd_jaxpr_thunk) — don't force them
        j = _as_jaxpr(val)
        if j is not None and id(j) not in seen:
            seen.add(id(j))
            yield name, j
            continue
        if isinstance(val, (list, tuple)):
            for i, item in enumerate(val):
                j = _as_jaxpr(item)
                if j is not None and id(j) not in seen:
                    seen.add(id(j))
                    yield f"{name}[{i}]", j


def all_eqns(jaxpr) -> Iterator[tuple[object, str]]:
    """Every eqn of ``jaxpr`` and its sub-jaxprs with a ``/``-joined
    path label (e.g. ``"body_jaxpr/branches[0]"``)."""
    def walk(j, path):
        for eqn in j.eqns:
            yield eqn, path
            for label, sub in subjaxprs(eqn):
                yield from walk(sub, f"{path}/{label}" if path else label)
    yield from walk(_as_jaxpr(jaxpr) or jaxpr, "")


# --------------------------------------------------------------------------
# def-use chains and live ranges
# --------------------------------------------------------------------------

def _aval_bytes(aval) -> int:
    try:
        n = 1
        for d in aval.shape:
            n *= int(d)
        return n * aval.dtype.itemsize
    except Exception:
        return 0


def _aval_str(aval) -> str:
    try:
        return f"{aval.dtype}[{','.join(str(d) for d in aval.shape)}]"
    except Exception:
        return str(aval)


def def_use(jaxpr) -> dict:
    """Def-use chains for the *top level* of ``jaxpr``: maps each var to
    the list of eqn indices that consume it (outvar uses get index
    ``len(eqns)``). Literals are skipped."""
    j = _as_jaxpr(jaxpr) or jaxpr
    uses: dict = {}
    for i, eqn in enumerate(j.eqns):
        for v in eqn.invars:
            if hasattr(v, "count"):
                uses.setdefault(v, []).append(i)
    n = len(j.eqns)
    for v in j.outvars:
        if hasattr(v, "count"):
            uses.setdefault(v, []).append(n)
    return uses


def live_ranges(jaxpr) -> dict:
    """Live interval ``var -> (birth, death)`` over top-level eqn
    indices. Inputs are born at -1; values used by an outvar die at
    ``len(eqns)`` (they survive the whole program)."""
    j = _as_jaxpr(jaxpr) or jaxpr
    uses = def_use(j)
    birth: dict = {}
    for v in j.invars + getattr(j, "constvars", []):
        birth[v] = -1
    for i, eqn in enumerate(j.eqns):
        for v in eqn.outvars:
            if hasattr(v, "count"):
                birth.setdefault(v, i)
    out: dict = {}
    for v, b in birth.items():
        us = uses.get(v)
        out[v] = (b, max(us) if us else b)
    return out


def static_peak_bytes(closed, *, donated: Iterable[int] = ()) -> int:
    """Static peak-HBM estimate from a live-range interval sweep.

    Sweeps the top-level eqns accumulating live-set bytes; a sub-jaxpr
    (scan body, cond branch, ...) contributes its own inner peak *minus*
    the operand/result bytes already counted live at the call site.
    Donated inputs whose shape/dtype matches an output are assumed
    aliased by XLA (counted once, not twice). This is an estimate — XLA
    fuses, rematerializes, and pads — but tracks ``program_state +
    transient`` well enough for a 25%-band regression gate."""
    j = _as_jaxpr(closed) or closed
    donated = frozenset(donated)
    ranges = live_ranges(j)
    n = len(j.eqns)

    # bytes XLA saves by aliasing donated inputs onto matching outputs
    out_keys: dict[tuple, int] = {}
    for v in j.outvars:
        aval = getattr(v, "aval", None)
        if aval is None:
            continue
        key = (tuple(getattr(aval, "shape", ())),
               str(getattr(aval, "dtype", "")))
        out_keys[key] = out_keys.get(key, 0) + 1
    aliased = 0
    for i in sorted(donated):
        if i >= len(j.invars):
            continue
        aval = j.invars[i].aval
        key = (tuple(getattr(aval, "shape", ())),
               str(getattr(aval, "dtype", "")))
        if out_keys.get(key, 0) > 0:
            out_keys[key] -= 1
            aliased += _aval_bytes(aval)

    # delta sweep: +bytes at birth, -bytes after death
    deltas = [0] * (n + 2)
    for v, (b, d) in ranges.items():
        size = _aval_bytes(getattr(v, "aval", None))
        if not size:
            continue
        deltas[b + 1] += size
        deltas[d + 2 if d + 2 <= n + 1 else n + 1] -= size

    # inner peaks of sub-jaxprs, attributed at their call eqn
    inner_extra = [0] * (n + 1)
    for i, eqn in enumerate(j.eqns):
        for _, sub in subjaxprs(eqn):
            inner = static_peak_bytes(sub)
            boundary = sum(_aval_bytes(getattr(v, "aval", None))
                           for v in list(eqn.invars) + list(eqn.outvars)
                           if hasattr(v, "aval"))
            extra = inner - boundary
            if extra > 0:
                inner_extra[i + 1] = max(inner_extra[i + 1], extra)

    peak = live = 0
    for i in range(n + 1):
        live += deltas[i]
        peak = max(peak, live + inner_extra[i])
    return max(0, peak - aliased)


# --------------------------------------------------------------------------
# collective schedule
# --------------------------------------------------------------------------

COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
    "axis_index",  # not a transfer, but schedule-ordering relevant: no
})
# axis_index carries no payload; exclude it from the schedule proper
_SCHEDULE_PRIMS = COLLECTIVE_PRIMS - {"axis_index"}


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective in a program's ordered schedule."""

    prim: str                 # e.g. "psum"
    axes: tuple               # axis names, e.g. ("pg",)
    shape: tuple              # operand shape
    dtype: str
    path: str = ""            # sub-jaxpr path ("" = top level)

    def key(self) -> tuple:
        return (self.prim, self.axes, self.shape, self.dtype)


def _axes_of(eqn) -> tuple:
    for k in ("axes", "axis_name", "axis"):
        a = eqn.params.get(k)
        if a is not None:
            if isinstance(a, (list, tuple)):
                return tuple(str(x) for x in a)
            return (str(a),)
    return ()


def collective_schedule(closed, *, path: str = "") -> list[CollectiveOp]:
    """Ordered collective schedule of ``closed`` (sub-jaxprs included,
    in program order). Each entry records primitive, axes, operand
    shape/dtype and the sub-jaxpr path for provenance."""
    out: list[CollectiveOp] = []
    for eqn, p in all_eqns(closed):
        if str(eqn.primitive) not in _SCHEDULE_PRIMS:
            continue
        v = eqn.invars[0] if eqn.invars else None
        aval = getattr(v, "aval", None)
        out.append(CollectiveOp(
            prim=str(eqn.primitive), axes=_axes_of(eqn),
            shape=tuple(getattr(aval, "shape", ())),
            dtype=str(getattr(aval, "dtype", "")),
            path=f"{path}/{p}" if path and p else (p or path)))
    return out


def schedule_hash(schedule: list[CollectiveOp]) -> str:
    """Stable hash of a collective schedule (order + op keys; sub-jaxpr
    paths excluded so structurally identical programs agree)."""
    canon = ";".join(
        f"{op.prim}@{','.join(op.axes)}:{op.dtype}"
        f"[{','.join(str(d) for d in op.shape)}]" for op in schedule)
    return hashlib.sha1(canon.encode()).hexdigest()[:16]


# --------------------------------------------------------------------------
# PDT22x — collective consistency
# --------------------------------------------------------------------------

@register(
    "PDT221", "divergent-collective-cond", Severity.ERROR, "ir",
    example="""
import jax
import jax.numpy as jnp
from jax import lax

JAXPR = jax.make_jaxpr(
    lambda p, x: lax.cond(p, lambda v: lax.psum(v, 'i'),
                          lambda v: v * 2.0, x),
    axis_env=[('i', 2)])(True, jnp.ones((4,), jnp.float32))
""",
    near_miss="""
import jax
import jax.numpy as jnp
from jax import lax

JAXPR = jax.make_jaxpr(
    lambda p, x: lax.cond(p, lambda v: lax.psum(v, 'i') * 2.0,
                          lambda v: lax.psum(v, 'i') + 1.0, x),
    axis_env=[('i', 2)])(True, jnp.ones((4,), jnp.float32))
""")
def check_divergent_collective_cond(closed, ctx):
    """``cond`` branches with different collective schedules are an SPMD
    deadlock: when the predicate diverges across ranks (data-dependent
    predicates usually do), one rank enters a psum the other never
    issues, and the program hangs until the collective watchdog's
    PDT-E021 timeout. Either hoist the collective out of the cond or
    make every branch issue the identical schedule."""
    for eqn, path in all_eqns(closed):
        if str(eqn.primitive) != "cond":
            continue
        branches = eqn.params.get("branches") or ()
        scheds = [[op.key() for op in collective_schedule(b)]
                  for b in branches]
        if len(scheds) < 2 or all(s == scheds[0] for s in scheds[1:]):
            continue
        desc = []
        for i, s in enumerate(scheds):
            ops = ", ".join(f"{p}@{','.join(a)}" for p, a, _, _ in s) \
                or "(none)"
            desc.append(f"branch[{i}]: {ops}")
        where = f" (at {path})" if path else ""
        yield (f"cond branches issue divergent collective schedules"
               f"{where} — ranks whose predicate differs will deadlock "
               f"(SPMD): " + "; ".join(desc), eqn)


@register(
    "PDT222", "axis-dependent-shape-collective", Severity.WARN, "ir",
    example="""
import jax
import jax.numpy as jnp
from jax import lax

JAXPR = jax.make_jaxpr(
    lambda x: lax.psum(lax.all_gather(x, 'i'), 'i'),
    axis_env=[('i', 2)])(jnp.ones((4,), jnp.float32))
""",
    near_miss="""
import jax
import jax.numpy as jnp
from jax import lax

JAXPR = jax.make_jaxpr(
    lambda x: lax.psum(x, 'i') + lax.all_gather(x, 'i').sum(),
    axis_env=[('i', 2)])(jnp.ones((4,), jnp.float32))
""")
def check_axis_dependent_shape(closed, ctx):
    """A value whose shape depends on the axis size (an ``all_gather``
    result: one dim is ``axis_size * n``) feeding another collective
    means the program's collective payloads silently re-specialize per
    world size — an elastic resize recompiles *and* reshapes every
    rank's schedule. Reduce before gathering, or keep gathered values
    out of later collectives."""
    j = _as_jaxpr(closed) or closed
    axis_dep: set = set()
    for eqn in j.eqns:
        prim = str(eqn.primitive)
        if prim == "all_gather":
            for v in eqn.outvars:
                if hasattr(v, "count"):
                    axis_dep.add(v)
            continue
        if prim in _SCHEDULE_PRIMS:
            for v in eqn.invars:
                if hasattr(v, "count") and v in axis_dep:
                    yield (f"{prim} consumes an axis-size-dependent "
                           f"shape ({_aval_str(v.aval)} from all_gather)"
                           f": collective payloads re-specialize per "
                           f"world size; reduce before gathering", eqn)
                    break
        # propagate the taint through elementwise/reshape-ish ops
        if any(hasattr(v, "count") and v in axis_dep for v in eqn.invars):
            for v in eqn.outvars:
                if hasattr(v, "count"):
                    axis_dep.add(v)


register_runtime(
    "PDT223", "collective-schedule-divergence", Severity.ERROR,
    """Ranks disagree on the collective schedule for the upcoming
    training session: each rank hashed its program's ordered collective
    schedule at group setup and the store cross-check found a mismatch.
    Without this check the divergence surfaces only as a PDT-E021
    collective timeout mid-step. Usually a rank-dependent branch or a
    config skew (different bucket sizes / sync settings per node).""",
    example="""
from paddle_tpu import analysis
from paddle_tpu.analysis import program as prog


class _Store:
    def __init__(self, kv):
        self.kv = kv

    def set(self, k, v):
        self.kv[k] = v

    def get(self, k, timeout=None):
        from paddle_tpu.core.errors import StoreTimeoutError
        if k not in self.kv:
            raise StoreTimeoutError(f"no key {k}")
        return self.kv[k]


kv = {}
s0, s1 = _Store(kv), _Store(kv)
with analysis.collect() as DIAGS:
    prog.verify_schedule(s0, "setup", "node-0", ["node-0", "node-1"],
                         "aaaa", timeout=0.1, raise_on_divergence=False)
    prog.verify_schedule(s1, "setup", "node-1", ["node-0", "node-1"],
                         "bbbb", timeout=0.1, raise_on_divergence=False)
""",
    near_miss="""
from paddle_tpu import analysis
from paddle_tpu.analysis import program as prog


class _Store:
    def __init__(self, kv):
        self.kv = kv

    def set(self, k, v):
        self.kv[k] = v

    def get(self, k, timeout=None):
        from paddle_tpu.core.errors import StoreTimeoutError
        if k not in self.kv:
            raise StoreTimeoutError(f"no key {k}")
        return self.kv[k]


kv = {}
s0, s1 = _Store(kv), _Store(kv)
with analysis.collect() as DIAGS:
    prog.verify_schedule(s0, "setup", "node-0", ["node-0", "node-1"],
                         "aaaa", timeout=0.1, raise_on_divergence=False)
    prog.verify_schedule(s1, "setup", "node-1", ["node-0", "node-1"],
                         "aaaa", timeout=0.1, raise_on_divergence=False)
""")


def verify_schedule(store, tag: str, node_id: str, members: list,
                    sched_hash: str, *, timeout: float = 5.0,
                    raise_on_divergence: bool = True) -> bool:
    """Cross-check ``sched_hash`` against every peer via the store.

    Each rank publishes its hash under ``sched/{tag}/{node}`` and polls
    the peers'. A missing peer (store timeout) is skipped — membership
    churn is the elastic manager's problem, not ours. On mismatch the
    divergence is reported as PDT223 and, with ``raise_on_divergence``,
    a :class:`~paddle_tpu.core.errors.CollectiveScheduleError`
    (PDT-E023) is raised — failing fast at group setup instead of
    hanging until the PDT-E021 watchdog fires mid-step. Returns True
    when every reachable peer agrees."""
    from ..core.errors import CollectiveScheduleError, StoreTimeoutError

    store.set(f"sched/{tag}/{node_id}", str(sched_hash))
    mismatches: list[str] = []
    for peer in members:
        if str(peer) == str(node_id):
            continue
        try:
            theirs = store.get(f"sched/{tag}/{peer}", timeout=timeout)
        except StoreTimeoutError:
            continue  # peer not up yet; elastic membership handles it
        except Exception:
            continue
        if isinstance(theirs, bytes):
            theirs = theirs.decode("utf-8", "replace")
        if str(theirs) != str(sched_hash):
            mismatches.append(f"{peer}={theirs}")
    if not mismatches:
        return True
    msg = (f"collective schedule divergence at group setup "
           f"[{tag}]: this rank ({node_id}) hashed {sched_hash}, "
           f"peers disagree: {', '.join(mismatches)} — ranks would "
           f"deadlock at the first mismatched collective")
    _engine.report_runtime("PDT223", msg, file=f"<store:{tag}>")
    if raise_on_divergence:
        raise CollectiveScheduleError(msg)
    return False


# --------------------------------------------------------------------------
# PDT23x — donation & HBM
# --------------------------------------------------------------------------

def _shape_key(v) -> tuple:
    aval = getattr(v, "aval", None)
    return (tuple(getattr(aval, "shape", ())),
            str(getattr(aval, "dtype", "")))


@register(
    "PDT231", "read-after-donation", Severity.ERROR, "ir",
    example="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(lambda w: w.sum())(jnp.ones((8,), jnp.float32))
DONATED = frozenset({0})
N_ARGS = 0
""",
    near_miss="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(lambda w: w + 1.0)(jnp.ones((8,), jnp.float32))
DONATED = frozenset({0})
N_ARGS = 0
""")
def check_read_after_donation(closed, ctx):
    """A donated input with NO shape/dtype-compatible output: XLA frees
    or reuses its buffer during the step, but nothing replaces it — any
    caller still holding the handle (a state dict, a flat bucket, a KV
    pool) reads garbage on the next step. This is the orphaned-buffer
    restore bug class; donation must pair each donated input with the
    output that supersedes it."""
    j = _as_jaxpr(closed) or closed
    out_count: dict[tuple, int] = {}
    for v in j.outvars:
        key = _shape_key(v)
        out_count[key] = out_count.get(key, 0) + 1
    uses = def_use(j)
    for i in sorted(ctx.donated):
        if i >= len(j.invars):
            continue
        v = j.invars[i]
        if out_count.get(_shape_key(v), 0) == 0:
            # provenance: anchor to the last eqn consuming the donated
            # buffer — the site whose result outlives the freed input
            sites = [k for k in uses.get(v, ()) if k < len(j.eqns)]
            eqn = j.eqns[sites[-1]] if sites else None
            yield (f"input #{i} ({_aval_str(v.aval)}) is donated but no "
                   f"output matches its shape/dtype: its buffer is "
                   f"consumed with nothing superseding it — a caller "
                   f"re-reading the old handle gets garbage "
                   f"(read-after-donation)", eqn)


@register(
    "PDT232", "double-donation", Severity.WARN, "ir",
    example="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(
    lambda a, b: (a + b,))(jnp.ones((8,), jnp.float32),
                           jnp.ones((8,), jnp.float32))
DONATED = frozenset({0, 1})
N_ARGS = 0
""",
    near_miss="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(
    lambda a, b: (a + b, a - b))(jnp.ones((8,), jnp.float32),
                                 jnp.ones((8,), jnp.float32))
DONATED = frozenset({0, 1})
N_ARGS = 0
""")
def check_double_donation(closed, ctx):
    """More inputs donated for one shape/dtype class than there are
    outputs to alias onto: the surplus donations buy nothing (XLA can
    only alias one input per output buffer) while still invalidating the
    callers' handles. Donate exactly the inputs the outputs supersede."""
    j = _as_jaxpr(closed) or closed
    out_count: dict[tuple, int] = {}
    for v in j.outvars:
        key = _shape_key(v)
        out_count[key] = out_count.get(key, 0) + 1
    don_count: dict[tuple, list] = {}
    for i in sorted(ctx.donated):
        if i >= len(j.invars):
            continue
        don_count.setdefault(_shape_key(j.invars[i]), []).append(i)
    for key, idxs in don_count.items():
        outs = out_count.get(key, 0)
        if outs and len(idxs) > outs:
            v = j.invars[idxs[0]]
            yield (f"{len(idxs)} inputs {idxs} donated for "
                   f"{_aval_str(v.aval)} but only {outs} matching "
                   f"output(s): the surplus donation invalidates a live "
                   f"handle without saving HBM (double-donation)", None)


_BIG = 1 << 20  # 1 MiB — PDT233 only fires on buffers worth donating


@register(
    "PDT233", "missed-donation-step-carry", Severity.WARN, "ir",
    example="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(
    lambda w: w + 1.0)(jnp.ones((1024, 1024), jnp.float32))
DONATED = frozenset()
N_ARGS = 0
""",
    near_miss="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(
    lambda w: w + 1.0)(jnp.ones((1024, 1024), jnp.float32))
DONATED = frozenset({0})
N_ARGS = 0
""")
def check_missed_donation(closed, ctx):
    """A large (>= 1 MiB) step-carry buffer — a state input whose
    shape/dtype matches an output — not donated doubles its HBM
    footprint: XLA must materialize the new value alongside the old.
    Fused-optimizer flat buckets and engine KV pools are the canonical
    wins (a flat bucket is the model size; a KV pool is the HBM
    budget). PDT203 notes the general case; this WARNs when the wasted
    buffer is big enough to matter."""
    j = _as_jaxpr(closed) or closed
    out_count: dict[tuple, int] = {}
    for v in j.outvars:
        key = _shape_key(v)
        out_count[key] = out_count.get(key, 0) + 1
    for i in sorted(ctx.donated):
        if i < len(j.invars):
            key = _shape_key(j.invars[i])
            if out_count.get(key, 0) > 0:
                out_count[key] -= 1
    for i, v in enumerate(j.invars):
        if i < ctx.n_explicit_args or i in ctx.donated:
            continue
        size = _aval_bytes(getattr(v, "aval", None))
        if size < _BIG:
            continue
        key = _shape_key(v)
        if out_count.get(key, 0) > 0:
            out_count[key] -= 1
            yield (f"state input #{i} ({_aval_str(v.aval)}, "
                   f"{size / (1 << 20):.1f} MiB) matches an output but "
                   f"is not donated: a full extra copy of a step-carry "
                   f"buffer held in HBM across the step", None)


# --------------------------------------------------------------------------
# PDT24x — recompile risk
# --------------------------------------------------------------------------

@register(
    "PDT241", "weak-type-promotion-fork", Severity.WARN, "ir",
    example="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(
    lambda x, s: x * s)(jnp.ones((4,), jnp.bfloat16), 3.0)
""",
    near_miss="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(
    lambda x, s: x * s)(jnp.ones((4,), jnp.bfloat16),
                        jnp.float32(3.0))
""")
def check_weak_type_promotion_fork(closed, ctx):
    """A weak-typed program input flowing into a dtype conversion: the
    promotion the compiler picked depends on the input being weak, so
    the same call with a committed array traces to a DIFFERENT program
    — a signature fork that doubles the compile cache and can flip
    numerics (bf16 vs f32 accumulation). PDT205 notes weak inputs
    exist; this flags the fork actually happening (eqn-level site).
    Commit the scalar's dtype at the boundary."""
    j = _as_jaxpr(closed) or closed
    weak_invars = {v for v in j.invars
                   if getattr(getattr(v, "aval", None), "weak_type", False)}
    if not weak_invars:
        return
    flagged = 0
    for eqn in j.eqns:
        if str(eqn.primitive) != "convert_element_type":
            continue
        for v in eqn.invars:
            if hasattr(v, "count") and v in weak_invars:
                new = eqn.params.get("new_dtype")
                yield (f"weak-typed input ({_aval_str(v.aval)}) is "
                       f"promoted to {new} inside the program: the same "
                       f"call with a committed array traces differently "
                       f"and forks the compile cache; commit the dtype "
                       f"at the boundary", eqn)
                flagged += 1
                if flagged >= 5:
                    return


register_runtime(
    "PDT242", "shape-as-data-recompile", Severity.WARN,
    """One function accumulated >= 3 compiled variants that differ ONLY
    in input shapes: a traced length/batch/table is being baked into the
    program as a static dim, so every new size recompiles (the engine's
    no-recompile contract is void). Pad to a bucketed shape or pass the
    length as data. Cross-referenced with the runtime
    ``compile.retrace`` cause classifier — both report the same
    vocabulary.""",
    example="""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import analysis


@paddle.jit.to_static
def fn(x):
    return x * 2.0


with analysis.collect() as DIAGS:
    for n in (4, 5, 6):
        fn(paddle.to_tensor(np.ones((n,), np.float32)))
""",
    near_miss="""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import analysis


@paddle.jit.to_static
def fn(x):
    return x * 2.0


with analysis.collect() as DIAGS:
    for n in (4, 5):
        fn(paddle.to_tensor(np.ones((n,), np.float32)))
""")


SHAPE_FORK_LIMIT = 3  # distinct shape-only variants before PDT242 fires


def strip_shapes(sig):
    """Recursively erase shape tuples from a jit cache signature, so
    signatures differing only in shapes collapse to one class. Tensor
    leaves are ``("T", shape, dtype)`` / ``("A", shape, dtype)`` tuples
    (see ``jit._tree_signature``)."""
    if isinstance(sig, tuple):
        if len(sig) == 3 and sig[0] in ("T", "A"):
            return (sig[0], None) + tuple(
                strip_shapes(s) for s in sig[2:])
        return tuple(strip_shapes(s) for s in sig)
    if isinstance(sig, (list, frozenset)):
        return type(sig)(strip_shapes(s) for s in sig)
    return sig


# --------------------------------------------------------------------------
# audit entry points
# --------------------------------------------------------------------------

@dataclasses.dataclass
class AuditResult:
    """What one whole-program audit produced."""

    diags: list
    peak_bytes: int
    schedule: list
    schedule_hash: str
    where: str = "<jaxpr>"


def flat_eqn_count(jaxpr) -> int:
    """Total equation count of a jaxpr INCLUDING every call-like
    sub-jaxpr (pjit, remat/checkpoint, scan, custom_vjp, ...) — the
    denominator-independent size measure ``calibrate.
    measure_remat_fraction`` uses: a remat region's recomputed forward
    lives in a ``remat``-primitive sub-jaxpr, invisible to a top-level
    count."""
    from jax import core as _jcore  # noqa: F401  (import parity)
    total = 0
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    for eqn in jaxpr.eqns:
        total += 1
        for v in eqn.params.values():
            if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
                total += flat_eqn_count(v)
            elif isinstance(v, (tuple, list)):
                for item in v:
                    if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                        total += flat_eqn_count(item)
    return total


# process-level per-code tally (bench round records; regression sentinel)
_audit_counts: dict[str, int] = {}


def audit_counts(reset: bool = False) -> dict[str, int]:
    """Per-code finding counts accumulated by every audit since the last
    reset — bench.py snapshots these into the round record so the
    regression sentinel treats new findings like a perf regression."""
    out = dict(sorted(_audit_counts.items()))
    if reset:
        _audit_counts.clear()
    return out


def _tally(diags) -> None:
    for d in diags:
        _audit_counts[d.code] = _audit_counts.get(d.code, 0) + 1


def audit_jaxpr(closed, *, donated: Iterable[int] = (),
                n_explicit_args: int = 0, where: str = "<jaxpr>",
                extra_suppress: frozenset = frozenset(),
                do_report: bool = True) -> AuditResult:
    """Run the full IR pass suite over one ClosedJaxpr and compute the
    program's static peak-HBM estimate and collective schedule.

    The diagnostics go through the standard ``report`` gate (mode flag,
    suppression, session dedup) unless ``do_report=False`` (the CLI
    collects its own)."""
    diags = _engine.check_jaxpr(
        closed, donated=donated, n_explicit_args=n_explicit_args,
        where=where, extra_suppress=extra_suppress)
    try:
        peak = static_peak_bytes(closed, donated=donated)
    except Exception:
        peak = 0
    try:
        sched = collective_schedule(closed)
        shash = schedule_hash(sched)
    except Exception:
        sched, shash = [], ""
    _tally(diags)
    if do_report:
        _engine.report(diags, where=where)
    return AuditResult(diags=diags, peak_bytes=peak, schedule=sched,
                       schedule_hash=shash, where=where)


def audit_executable(exe, *, where: str = "", fn=None
                     ) -> Optional[AuditResult]:
    """Whole-program audit of a built ``jit._Executable`` — the
    post-capture hook ``StaticFunction._capture`` calls once per trace.

    Stashes ``static_peak_bytes`` and ``schedule_hash`` on the
    executable (the jit layer's ``hbm.static_peak_bytes{fn}`` gauge and
    the elastic schedule verifier read them) *before* the capture
    releases the jaxpr. Mode-gated; returns None when the lint is off
    or the jaxpr is already released."""
    if _engine.mode() == "off":
        return None
    closed = getattr(exe, "jaxpr", None)
    if closed is None:
        return None
    extra = frozenset()
    if fn is not None:
        extra = frozenset(getattr(_engine._unwrap_callable(fn),
                                  "__pdtpu_suppress__", frozenset()))
    try:
        res = audit_jaxpr(
            closed, donated=getattr(exe, "donate_idx", ()),
            n_explicit_args=getattr(exe, "n_explicit_args", 0),
            where=where or "<to_static>", extra_suppress=extra,
            do_report=False)
    except Exception:
        _engine.logger.debug("audit_executable failed", exc_info=True)
        return None
    exe.static_peak_bytes = res.peak_bytes
    exe.schedule_hash = res.schedule_hash
    # flattened program size, stashed before the jaxpr is released:
    # remat A/Bs read it off cached executables (the recompute fraction
    # is extra eqns / baseline eqns — see calibrate.py)
    try:
        exe.jaxpr_eqn_count = flat_eqn_count(closed)
    except Exception:
        exe.jaxpr_eqn_count = 0
    _engine.report(res.diags, where=where)
    return res


def audit_jitted(fn, args=(), kwargs=None, *, where: str = "",
                 donated: Iterable[int] = ()) -> Optional[AuditResult]:
    """Trace ``fn`` with example args and audit the jaxpr — the hook for
    raw ``jax.jit`` sites (engine COW/window programs, pipeline bodies,
    psum_mean) that never pass through ``to_static`` capture.

    Mode-gated and best-effort: tracing failures are swallowed (a
    broken audit must never break a build). When ``donated`` is empty
    the donation passes are disabled by marking every input explicit."""
    if _engine.mode() == "off":
        return None
    try:
        import jax
        closed = jax.make_jaxpr(fn)(*args, **(kwargs or {}))
    except Exception:
        _engine.logger.debug("audit_jitted trace failed (%s)", where,
                             exc_info=True)
        return None
    donated = tuple(donated)
    n_explicit = 0 if donated else len(closed.jaxpr.invars)
    try:
        return audit_jaxpr(closed, donated=donated,
                           n_explicit_args=n_explicit,
                           where=where or getattr(fn, "__name__", "<fn>"))
    except Exception:
        _engine.logger.debug("audit_jitted failed (%s)", where,
                             exc_info=True)
        return None

"""CLI: ``python -m paddle_tpu.analysis <paths...>`` — repo-wide graph
lint (AST front-end) and whole-program audit (IR front-end).

Walks ``.py`` files, lints every ``to_static``-decorated function (every
function under ``--assume-jit``), prints findings as
``file:line:col: CODE [severity] message``, and exits with a stable
code: **0** no gating findings, **1** findings at or above the gate
severity (``error`` by default, ``warn`` under ``--strict``), **2**
usage or import error. ``--format json`` emits machine-readable
findings for CI/editors. ``--programs mod:callable`` imports and runs
an entry point, collecting the compile-time whole-program audit
findings (PDT2xx) from every program it compiles. ``--list-codes``
prints the registry catalog (``--format markdown`` renders the README
code table).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .engine import analyze_file
from .registry import REGISTRY, Diagnostic, Severity

EXIT_CLEAN = 0     # no gating findings
EXIT_FINDINGS = 1  # findings at/above the gate severity
EXIT_USAGE = 2     # bad invocation / unreadable input / import failure


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        else:
            print(f"warning: no such path: {p}", file=sys.stderr)


def _one_line(doc: str) -> str:
    return " ".join(doc.split())


def code_table_markdown() -> str:
    """The registry rendered as a markdown table — the single source
    for the README "Static analysis" code table (a doc test keeps the
    README block in sync with this output)."""
    rows = ["| code | name | severity | front-end | flags |",
            "|------|------|----------|-----------|-------|"]
    for code in sorted(REGISTRY):
        s = REGISTRY[code]
        summary = _one_line(s.doc).split(". ")[0].rstrip(".")
        summary = summary.replace("|", "\\|")
        rows.append(f"| {code} | {s.name} | {s.severity} | "
                    f"{s.frontend} | {summary}. |")
    return "\n".join(rows)


def _list_codes(fmt: str) -> int:
    if fmt == "markdown":
        print(code_table_markdown())
        return EXIT_CLEAN
    if fmt == "json":
        print(json.dumps({
            code: {"name": s.name, "severity": str(s.severity),
                   "frontend": s.frontend, "doc": _one_line(s.doc)}
            for code, s in sorted(REGISTRY.items())}, indent=2))
        return EXIT_CLEAN
    for code in sorted(REGISTRY):
        s = REGISTRY[code]
        print(f"{code}  {s.name:<32} {str(s.severity):<5} [{s.frontend}]")
        print(f"        {_one_line(s.doc)}")
    return EXIT_CLEAN


def _run_programs(entries) -> tuple[list[Diagnostic], int]:
    """Import and call each ``module:callable`` entry under a collect
    sink; the compile-time whole-program audits (jit capture, engine
    program caches, pipeline dispatch) report into it. Returns the
    findings and an exit code (EXIT_USAGE on import/call failure)."""
    import importlib

    from . import collect

    diags: list[Diagnostic] = []
    for entry in entries:
        mod_name, _, attr = entry.partition(":")
        try:
            mod = importlib.import_module(mod_name)
            fn = getattr(mod, attr) if attr else None
        except (ImportError, AttributeError) as e:
            print(f"error: cannot load --programs entry {entry!r}: {e}",
                  file=sys.stderr)
            return diags, EXIT_USAGE
        try:
            with collect() as sink:
                if fn is not None:
                    fn()
            diags.extend(sink)
        except Exception as e:
            print(f"error: --programs entry {entry!r} raised "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return diags, EXIT_USAGE
    return diags, EXIT_CLEAN


def _emit_json(diags, n_files, counts, gating) -> None:
    print(json.dumps({
        "findings": [
            {"path": d.file, "line": d.line, "col": d.col,
             "code": d.code, "severity": str(d.severity),
             "message": d.message} for d in diags],
        "summary": {"files": n_files,
                    "error": counts[Severity.ERROR],
                    "warn": counts[Severity.WARN],
                    "note": counts[Severity.NOTE],
                    "gating": gating}}, indent=2))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="paddle_tpu graph lint & whole-program audit")
    ap.add_argument("paths", nargs="*", help=".py files or directories")
    ap.add_argument("--assume-jit", action="store_true",
                    help="lint every function, not only @to_static ones")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warn-severity findings too")
    ap.add_argument("--select", default="",
                    help="comma-separated codes to restrict to")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "markdown"),
                    help="output format (markdown: --list-codes only)")
    ap.add_argument("--programs", action="append", default=[],
                    metavar="MODULE:CALLABLE",
                    help="import and run an entry point, auditing every "
                         "program it compiles (repeatable)")
    ap.add_argument("--list-codes", action="store_true",
                    help="print the diagnostic catalog and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the per-finding lines (summary only)")
    args = ap.parse_args(argv)

    if args.list_codes:
        return _list_codes(args.format)
    if not args.paths and not args.programs:
        ap.error("no paths given (or use --programs / --list-codes)")

    select = {c.strip().upper() for c in args.select.split(",") if c.strip()}
    gate = Severity.WARN if args.strict else Severity.ERROR
    n_files = 0
    counts = {Severity.NOTE: 0, Severity.WARN: 0, Severity.ERROR: 0}
    gating = 0
    kept: list[Diagnostic] = []

    all_diags: list[tuple[str, list[Diagnostic]]] = []
    for path in _iter_py_files(args.paths):
        n_files += 1
        try:
            all_diags.append((path, analyze_file(
                path, force_jit=args.assume_jit)))
        except OSError as e:
            print(f"warning: cannot read {path}: {e}", file=sys.stderr)
            continue

    rc_programs = EXIT_CLEAN
    if args.programs:
        prog_diags, rc_programs = _run_programs(args.programs)
        all_diags.append(("<programs>", prog_diags))

    for _, diags in all_diags:
        for d in diags:
            if select and d.code not in select:
                continue
            counts[d.severity] += 1
            if d.severity >= gate:
                gating += 1
            kept.append(d)
            if not args.quiet and args.format == "text":
                print(d.format())

    if args.format == "json":
        _emit_json(kept, n_files, counts, gating)
    else:
        total = sum(counts.values())
        print(f"{total} finding(s) ({counts[Severity.ERROR]} error, "
              f"{counts[Severity.WARN]} warn, {counts[Severity.NOTE]} "
              f"note) in {n_files} file(s)")
    if rc_programs != EXIT_CLEAN:
        return rc_programs
    return EXIT_FINDINGS if gating else EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())

"""CLI: ``python -m paddle_tpu.analysis <paths...>`` — repo-wide graph
lint over the AST front-end.

Walks ``.py`` files, lints every ``to_static``-decorated function (every
function under ``--assume-jit``), prints findings as
``file:line:col: CODE [severity] message``, and exits non-zero when any
finding reaches the gate severity (``error`` by default, ``warn`` under
``--strict``). ``--list-codes`` prints the registry catalog.
"""
from __future__ import annotations

import argparse
import os
import sys

from .engine import analyze_file
from .registry import REGISTRY, Severity


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        else:
            print(f"warning: no such path: {p}", file=sys.stderr)


def _list_codes() -> int:
    for code in sorted(REGISTRY):
        s = REGISTRY[code]
        print(f"{code}  {s.name:<32} {str(s.severity):<5} [{s.frontend}]")
        doc = " ".join(s.doc.split())
        print(f"        {doc}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="paddle_tpu graph lint (AST front-end)")
    ap.add_argument("paths", nargs="*", help=".py files or directories")
    ap.add_argument("--assume-jit", action="store_true",
                    help="lint every function, not only @to_static ones")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warn-severity findings too")
    ap.add_argument("--select", default="",
                    help="comma-separated codes to restrict to")
    ap.add_argument("--list-codes", action="store_true",
                    help="print the diagnostic catalog and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the per-finding lines (summary only)")
    args = ap.parse_args(argv)

    if args.list_codes:
        return _list_codes()
    if not args.paths:
        ap.error("no paths given (or use --list-codes)")

    select = {c.strip().upper() for c in args.select.split(",") if c.strip()}
    gate = Severity.WARN if args.strict else Severity.ERROR
    n_files = 0
    counts = {Severity.NOTE: 0, Severity.WARN: 0, Severity.ERROR: 0}
    gating = 0
    for path in _iter_py_files(args.paths):
        n_files += 1
        try:
            diags = analyze_file(path, force_jit=args.assume_jit)
        except OSError as e:
            print(f"warning: cannot read {path}: {e}", file=sys.stderr)
            continue
        for d in diags:
            if select and d.code not in select:
                continue
            counts[d.severity] += 1
            if d.severity >= gate:
                gating += 1
            if not args.quiet:
                print(d.format())
    total = sum(counts.values())
    print(f"{total} finding(s) ({counts[Severity.ERROR]} error, "
          f"{counts[Severity.WARN]} warn, {counts[Severity.NOTE]} note) "
          f"in {n_files} file(s)")
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())

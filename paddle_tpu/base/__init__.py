"""Compat namespace: ``paddle.base`` (reference ``python/paddle/base/``).

The reference keeps framework internals here (Program/Executor/core
bindings). On this framework those live in ``paddle_tpu.static`` (program &
executor), ``paddle_tpu.core`` (dispatch/state), and ``paddle_tpu.framework``
(IO); this module aliases them for call sites written against the
reference's layout.
"""
from .. import framework  # noqa: F401
from ..core import dtype as core  # noqa: F401  (dtype/Place table ~ base.core)
from ..core import state  # noqa: F401
from ..framework import save, load  # noqa: F401
from ..static import Executor, Program, program_guard  # noqa: F401


def default_main_program():
    from .. import static
    return static.default_main_program()


def default_startup_program():
    from .. import static
    return static.default_startup_program()

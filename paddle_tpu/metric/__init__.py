"""``paddle.metric`` parity (reference ``python/paddle/metric/metrics.py``:
Metric base :46, Accuracy :184, Precision :310, Recall :407, Auc :499).

Metrics are host-side accumulators: the compiled train/eval step returns
predictions, and ``update`` runs on numpy values — keeping metric state out
of the XLA program (the reference likewise updates them in Python between
``_C_ops`` calls).
"""
from __future__ import annotations

import abc

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(x._read())
    return np.asarray(x)


class Metric(abc.ABC):
    """Base class (reference ``metrics.py:46``)."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def name(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def reset(self):
        ...

    def compute(self, *args):
        """Optional pre-processing of (pred, label) — runs inside the
        compiled step when used through hapi; defaults to identity."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (reference ``metrics.py:184``)."""

    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        # top-maxk indices, descending
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            if label.shape[-1] == pred.shape[-1] and pred.shape[-1] > 1:
                label = np.argmax(label, axis=-1)  # one-hot / soft labels
            else:
                label = label[..., 0]              # [N, 1] index labels
        correct = idx == label[..., None]
        return correct.astype("float32")

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        num = int(np.prod(correct.shape[:-1]))
        for i, k in enumerate(self.topk):
            c = correct[..., :k].sum()
            accs.append(float(c) / max(num, 1))
            self.total[i] += float(c)
            self.count[i] += num
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (reference ``metrics.py:310``)."""

    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype("int32").reshape(-1)
        labels = _np(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (reference ``metrics.py:407``)."""

    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype("int32").reshape(-1)
        labels = _np(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via thresholded confusion bins (reference ``metrics.py:499``,
    same bucketed algorithm)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2:          # [N, 2] class probs -> P(class 1)
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = _np(labels).reshape(-1)
        bins = np.clip((preds * self.num_thresholds).astype("int64"),
                       0, self.num_thresholds)
        pos = labels > 0.5
        np.add.at(self._stat_pos, bins[pos], 1)
        np.add.at(self._stat_neg, bins[~pos], 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, "int64")
        self._stat_neg = np.zeros(self.num_thresholds + 1, "int64")

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        # walk thresholds from high to low, trapezoid over (fp, tp)
        for i in range(self.num_thresholds, -1, -1):
            p = float(self._stat_pos[i])
            n = float(self._stat_neg[i])
            auc += n * (tot_pos + p / 2.0)
            tot_pos += p
            tot_neg += n
        denom = tot_pos * tot_neg
        return auc / denom if denom else 0.0

    def name(self):
        return self._name


__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Reference ``paddle.metric.accuracy`` functional: top-k accuracy of
    ``input`` [N, C] probabilities/logits against ``label`` [N] or [N, 1]."""
    import jax.numpy as jnp

    from ..core.dispatch import apply

    def impl(x, y):
        topk = jnp.argsort(-x, axis=-1)[:, :k]
        yy = y.reshape(-1, 1)
        hit = jnp.any(topk == yy, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply("accuracy", impl, input, label)

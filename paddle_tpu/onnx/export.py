"""ONNX export surface (reference ``python/paddle/onnx/export.py``:22)."""
from __future__ import annotations

__all__ = []


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer`` to ``path``.onnx — not implemented on this
    backend; ``paddle.jit.save`` (StableHLO export) is the portable
    serialized-program path here."""
    raise NotImplementedError(
        "ONNX export is not implemented for this backend (the reference "
        "delegates to the external paddle2onnx package); use "
        "paddle.jit.save (StableHLO) for portable serialized inference "
        "programs.")

"""ONNX export surface (reference ``python/paddle/onnx/export.py``:22).

The reference delegates to the external ``paddle2onnx`` package, which has
no analog for this backend; ``export`` raises with a pointer to
``paddle.jit.save`` (StableHLO), the portable serialized-program path here.
"""
from . import export as _export_mod
from .export import export

__all__ = ['export']

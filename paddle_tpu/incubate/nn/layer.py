"""Fused transformer layers (reference ``python/paddle/incubate/nn/layer/
fused_transformer.py``: FusedMultiHeadAttention :278, FusedFeedForward
:564; ``fused_dropout_add.py``, ``fused_linear.py``).

TPU-native: "fused" means routed through the Pallas/fused-functional tier
(flash attention, fused norms) and left to XLA to fuse the rest — the
layer classes keep the reference's signatures so incubate call sites work.
"""
from __future__ import annotations

from ...nn import functional as F
from ...nn.layer import Layer
from ...nn.layers import Dropout, LayerNorm, Linear

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward", "FusedLinear",
           "FusedDropoutAdd"]


class FusedLinear(Layer):
    """Reference ``fused_linear.py`` FusedLinear (gemm+bias in one op —
    XLA fuses these natively)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        if transpose_weight:
            raise NotImplementedError(
                "FusedLinear(transpose_weight=True) stores [out, in] "
                "weights; use the default layout on this backend")
        self.linear = Linear(in_features, out_features,
                             weight_attr=weight_attr, bias_attr=bias_attr)

    def forward(self, x):
        return self.linear(x)


class FusedDropoutAdd(Layer):
    """Reference ``fused_dropout_add.py``: dropout(x) + y in one pass."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.drop = Dropout(p, mode=mode)

    def forward(self, x, y):
        return self.drop(x) + y


class FusedMultiHeadAttention(Layer):
    """Reference ``fused_transformer.py:278``: pre/post-LN multi-head
    self-attention block with fused qkv, flash-attention core, residual."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        if need_weights:
            raise NotImplementedError("need_weights=True is unsupported "
                                      "(flash attention never forms the "
                                      "probability matrix)")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv = Linear(embed_dim, 3 * embed_dim,
                          weight_attr=qkv_weight_attr,
                          bias_attr=qkv_bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim,
                               weight_attr=linear_weight_attr,
                               bias_attr=linear_bias_attr)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.drop = Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ... import ops
        residual = query
        x = self.ln(query) if self.normalize_before else query
        b, s, _ = x.shape
        qkv = ops.reshape(self.qkv(x),
                          [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0)
        out = self.out_proj(ops.reshape(out, [b, s, self.embed_dim]))
        out = residual + self.drop(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    """Reference ``fused_transformer.py:564``: pre/post-LN FFN block with
    residual (linear→act→dropout→linear→dropout + add)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.fc1 = Linear(d_model, dim_feedforward,
                          weight_attr=linear1_weight_attr,
                          bias_attr=linear1_bias_attr)
        self.fc2 = Linear(dim_feedforward, d_model,
                          weight_attr=linear2_weight_attr,
                          bias_attr=linear2_bias_attr)
        self.ln = LayerNorm(d_model, epsilon=epsilon)
        self.act = getattr(F, activation)
        self.drop_act = Dropout(act_dropout_rate if act_dropout_rate
                                is not None else dropout_rate)
        self.drop_out = Dropout(dropout_rate)

    def forward(self, src, cache=None):
        residual = src
        x = self.ln(src) if self.normalize_before else src
        x = self.fc2(self.drop_act(self.act(self.fc1(x))))
        out = residual + self.drop_out(x)
        if not self.normalize_before:
            out = self.ln(out)
        return out

"""``paddle.incubate.nn.functional`` parity — fused-op surface.

Reference: ``python/paddle/incubate/nn/functional/`` (fused_rms_norm.py:21,
fused_layer_norm.py:21, fused_rotary_position_embedding.py:21, swiglu.py:20,
fused_dropout_add.py:22, fused_matmul_bias.py:24). On TPU these lower to the
Pallas fused kernels in ``paddle_tpu.ops.pallas``; elsewhere to XLA
compositions (which XLA fuses anyway — the capability, not the CUDA
mechanism, is what's matched).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.dispatch import apply
from ....nn import functional as F


def _on_tpu():
    return jax.default_backend() == "tpu"


def fused_rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis,
                   bias=None, residual=None, quant_scale=-1,
                   quant_round_type=0, quant_max_bound=0, quant_min_bound=0):
    """RMSNorm(bias + residual + x) fused pattern (reference
    ``fused_rms_norm.py:21``). Returns (out, residual_out) like the
    reference's two-output kernel."""

    def impl(v, w, *rest):
        i = 0
        b = rest[i] if bias is not None else None
        if bias is not None:
            i += 1
        r = rest[i] if residual is not None else None
        if residual is not None:
            i += 1
        nb = rest[i] if norm_bias is not None else None
        if b is not None:
            v = v + b
        if r is not None:
            v = v + r
        res_out = v
        if _on_tpu() and begin_norm_axis in (-1, v.ndim - 1) and nb is None:
            from ....ops.pallas import norms
            out = norms.rms_norm(v, w, eps=epsilon)
        else:
            axes = tuple(range(begin_norm_axis if begin_norm_axis >= 0
                               else v.ndim + begin_norm_axis, v.ndim))
            v32 = v.astype(jnp.float32)
            ms = jnp.mean(jnp.square(v32), axis=axes, keepdims=True)
            out = (v32 * jax.lax.rsqrt(ms + epsilon)).astype(v.dtype) * w
            if nb is not None:
                out = out + nb
        return out, res_out

    args = [x, norm_weight] + [t for t in (bias, residual, norm_bias)
                               if t is not None]
    return apply("fused_rms_norm", impl, *args)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon, residual_alpha=1.0,
                     begin_norm_axis=1, bias=None, residual=None,
                     quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                     quant_min_bound=0):
    """LayerNorm(bias + residual_alpha*residual + x) fused pattern
    (reference ``fused_layer_norm.py:21``). Returns (out, residual_out)."""

    def impl(v, *rest):
        i = 0
        w = rest[i] if norm_weight is not None else None
        if norm_weight is not None:
            i += 1
        nb = rest[i] if norm_bias is not None else None
        if norm_bias is not None:
            i += 1
        b = rest[i] if bias is not None else None
        if bias is not None:
            i += 1
        r = rest[i] if residual is not None else None
        if b is not None:
            v = v + b
        if r is not None:
            v = v + residual_alpha * r
        res_out = v
        if w is None and nb is None:
            return v, res_out
        last = begin_norm_axis in (-1, v.ndim - 1)
        if _on_tpu() and last and w is not None and nb is not None:
            from ....ops.pallas import norms
            return norms.layer_norm(v, w, nb, eps=epsilon), res_out
        axes = tuple(range(begin_norm_axis if begin_norm_axis >= 0
                           else v.ndim + begin_norm_axis, v.ndim))
        v32 = v.astype(jnp.float32)
        mean = jnp.mean(v32, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v32 - mean), axis=axes, keepdims=True)
        out = ((v32 - mean) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        if w is not None:
            out = out * w
        if nb is not None:
            out = out + nb
        return out, res_out

    args = [x] + [t for t in (norm_weight, norm_bias, bias, residual)
                  if t is not None]
    return apply("fused_layer_norm", impl, *args)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    time_major=False):
    """Reference ``fused_rotary_position_embedding.py:21``. Layout
    [batch, seq, num_heads, head_dim]; sin/cos [seq, head_dim] or
    [1, seq, 1, head_dim]. Paddle's ``use_neox_rotary_style=True`` pairs
    adjacent elements (2i, 2i+1); False pairs front/back halves — note this
    maps to the *opposite* convention of our kernel's ``use_neox`` flag."""
    import math

    def default_angles(positions, d):
        """positions: [S] or [B, S] -> tiled angle table [.., S, D]."""
        inv = 1.0 / 10000.0 ** (jnp.arange(0, d // 2) * 2.0 / d)
        ang = positions[..., None].astype(jnp.float32) * inv
        if use_neox_rotary_style:
            return jnp.repeat(ang, 2, axis=-1)        # interleaved tiling
        return jnp.concatenate([ang, ang], -1)        # half tiling

    def prep_tables(s_val, c_val, d):
        s_val = s_val.reshape(s_val.shape[-3] if s_val.ndim == 4
                              else s_val.shape[0], d)
        c_val = c_val.reshape(c_val.shape[-3] if c_val.ndim == 4
                              else c_val.shape[0], d)
        return c_val, s_val

    def impl(*tensors):
        ts = list(tensors)
        xq = ts.pop(0)
        if time_major:
            xq = jnp.swapaxes(xq, 0, 1)
        sq, d = xq.shape[1], xq.shape[-1]
        tab = [None, None]
        if sin is not None:
            tab = [ts[-2], ts[-1]]  # [sin, cos] — appended in that order
            ts = ts[:-2]
        pid = ts.pop(-1) if position_ids is not None else None
        if tab[0] is None:
            # computed tables: evaluate angles at the requested positions
            # directly (arbitrary position values, e.g. KV-cache decode)
            pos = jnp.arange(sq) if pid is None else pid  # [S] or [B, S]
            ang = default_angles(pos, d)
            c_tab, s_tab = jnp.cos(ang), jnp.sin(ang)
        else:
            c_tab, s_tab = prep_tables(tab[0], tab[1], d)
            if pid is not None:
                # per-example gather [B, S, D]; positions must lie within
                # the provided tables (clip matches the reference's
                # in-bounds contract without UB)
                c_tab = jnp.take(c_tab, pid, axis=0, mode="clip")
                s_tab = jnp.take(s_tab, pid, axis=0, mode="clip")
        from ....ops.pallas import rope
        outs = []
        kernel_neox = not use_neox_rotary_style  # see docstring
        for xx in [xq] + ts:
            if time_major and xx is not xq:
                xx = jnp.swapaxes(xx, 0, 1)
            o = rope.apply_rope(xx, c_tab, s_tab, use_neox=kernel_neox)
            if time_major:
                o = jnp.swapaxes(o, 0, 1)
            outs.append(o)
        return tuple(outs) if len(outs) > 1 else outs[0]

    args = [q] + [t for t in (k, v) if t is not None]
    if position_ids is not None:
        args.append(position_ids)
    if sin is not None:
        args += [sin, cos]
    out = apply("fused_rotary_position_embedding", impl, *args)
    n = 1 + (k is not None) + (v is not None)
    if n == 1:
        return out, None, None
    outs = list(out) + [None] * (3 - n)
    return tuple(outs)


def swiglu(x, y=None, name=None):
    """Reference ``swiglu.py:20``: silu(x) * y (y defaults to chunk)."""

    def impl(v, *rest):
        if rest:
            return jax.nn.silu(v) * rest[0]
        a, b = jnp.split(v, 2, axis=-1)
        return jax.nn.silu(a) * b

    args = [x] + ([y] if y is not None else [])
    return apply("swiglu", impl, *args)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """Reference ``fused_dropout_add.py:22``: dropout(x) + y."""
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """Reference ``fused_matmul_bias.py:24`` — XLA fuses the epilogue."""

    def impl(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a, b)
        if rest:
            out = out + rest[0]
        return out

    args = [x, y] + ([bias] if bias is not None else [])
    return apply("fused_matmul_bias", impl, *args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, False, transpose_weight)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation=None, name=None):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    if activation in (None, "none", ""):
        return out
    if activation == "relu":
        return F.relu(out)
    if activation in ("gelu", "gelu_approx"):
        return F.gelu(out, approximate=True)
    raise ValueError(f"unsupported activation {activation!r}")


__all__ = [
    "fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding",
    "swiglu", "fused_dropout_add", "fused_matmul_bias", "fused_linear",
    "fused_linear_activation",
]

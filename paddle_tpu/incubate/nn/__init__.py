"""``paddle.incubate.nn`` parity (reference ``python/paddle/incubate/nn``)."""
from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedDropoutAdd, FusedFeedForward, FusedLinear,
    FusedMultiHeadAttention)

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedLinear", "FusedDropoutAdd"]

"""``paddle.incubate.nn`` parity (reference ``python/paddle/incubate/nn``)."""
from . import functional  # noqa: F401

__all__ = ["functional"]

"""``paddle.incubate.autotune`` parity (reference
``python/paddle/incubate/autotune.py:25`` set_config) — fronting the
Pallas kernel autotuner (``ops/pallas/autotune.py``, SURVEY C14)."""
from ..ops.pallas.autotune import enabled, set_config  # noqa: F401

__all__ = ["set_config", "enabled"]

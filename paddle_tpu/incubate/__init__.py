"""``paddle.incubate`` parity namespace (reference ``python/paddle/incubate``)."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import autotune  # noqa: F401

__all__ = ["nn", "distributed", "autotune"]

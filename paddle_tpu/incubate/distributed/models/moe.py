"""Mixture-of-Experts with expert parallelism (the ``ep`` mesh axis).

Capability analog of the reference MoE stack (SURVEY D18):
``python/paddle/incubate/distributed/models/moe/moe_layer.py`` (MoELayer),
``gate/{naive,switch,gshard}_gate.py``, and the
``global_scatter/global_gather`` dispatch collectives
(``paddle/distributed/utils/moe_utils.py``). The reference routes tokens
with explicit NCCL all-to-alls; here dispatch/combine are capacity-bucketed
einsums (the GShard formulation) over expert-stacked ``[E, ...]`` weights
sharded ``Shard(0)`` over the ``ep`` axis — XLA's partitioner emits the
all-to-alls when token shardings (dp) and expert shardings (ep) meet in
the dispatch einsum, and they ride ICI.

Top-k routing with renormalized combine weights, per-expert capacity
``C = ceil(k * N / E * capacity_factor)``, overflow tokens dropped
(GShard/Switch semantics), and the switch-style load-balance auxiliary
loss ``E * sum(importance * load)``.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....core.dispatch import apply
from ....core.tensor import Parameter, Tensor
from ....nn.layer import Layer
from ....nn.layers import Linear


def _one_hot(idx, n, dtype=jnp.float32):
    return jax.nn.one_hot(idx, n, dtype=dtype)


def moe_dispatch_combine(gates, k, capacity):
    """Build dispatch/combine tensors from gate probabilities.

    gates: [N, E] softmax probabilities. Returns (dispatch [N, E, C] 0/1,
    combine [N, E, C] weights, aux_loss scalar). Slot 0 (top-1 choices)
    fills capacity first, then slot 1, matching the reference gshard gate's
    priority order."""
    n, e = gates.shape
    gval, gidx = jax.lax.top_k(gates, k)          # [N, k]
    gval = gval / jnp.maximum(gval.sum(-1, keepdims=True), 1e-9)

    counts = jnp.zeros((e,), jnp.float32)
    dispatch = jnp.zeros((n, e, capacity), jnp.float32)
    combine = jnp.zeros((n, e, capacity), jnp.float32)
    for slot in range(k):
        oh = _one_hot(gidx[:, slot], e)           # [N, E]
        pos = jnp.cumsum(oh, axis=0) - 1 + counts[None, :]
        keep = (pos < capacity).astype(jnp.float32) * oh
        counts = counts + keep.sum(axis=0)
        pos_kept = (pos * keep).sum(-1).astype(jnp.int32)  # [N]
        slot_disp = keep[:, :, None] * _one_hot(pos_kept, capacity)[:, None]
        dispatch = dispatch + slot_disp
        combine = combine + gval[:, slot, None, None] * slot_disp

    # switch-style load balancing on the top-1 assignment
    importance = gates.mean(axis=0)               # [E]
    load = _one_hot(gidx[:, 0], e).mean(axis=0)   # [E]
    aux = e * jnp.sum(importance * load)
    return dispatch, combine, aux


class MoEMLP(Layer):
    """Expert-parallel feed-forward mixture — drop-in for a dense FFN.

    Expert weights are stacked ``[E, ...]``; ``shard(mesh, ep_axis)`` pins
    ``Shard(0)`` so each ep rank owns ``E/ep`` experts (the reference's
    per-rank expert placement, ``moe_layer.py`` MoELayer). After forward,
    ``self.aux_loss`` holds the load-balance loss of the last call."""

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 top_k=2, capacity_factor=1.25, mesh=None, ep_axis="ep",
                 weight_attr=None):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gate = Linear(hidden_size, num_experts, bias_attr=False,
                           weight_attr=weight_attr)
        e, h, i = num_experts, hidden_size, intermediate_size
        from ....nn import initializer as I
        init = (weight_attr if weight_attr is not None
                else I.Normal(std=0.02))

        def mk(shape):
            return Parameter(init(shape, jnp.float32), trainable=True)

        self.w1 = mk((e, h, i))
        self.b1 = Parameter(jnp.zeros((e, i), jnp.float32), trainable=True)
        self.w2 = mk((e, i, h))
        self.b2 = Parameter(jnp.zeros((e, h), jnp.float32), trainable=True)
        self.aux_loss = None
        if mesh is not None:
            self.shard(mesh, ep_axis)

    def shard(self, mesh, ep_axis="ep"):
        from ....distributed.auto_parallel.api import (Replicate, Shard,
                                                       shard_parameter)
        dim = mesh.dim_names.index(ep_axis)
        pl = [Replicate()] * mesh.ndim
        pl[dim] = Shard(0)
        for p in (self.w1, self.b1, self.w2, self.b2):
            shard_parameter(p, mesh, pl)
        return self

    def forward(self, x):
        e, k, cf = self.num_experts, self.top_k, self.capacity_factor
        shape = tuple(x.shape)
        n_tokens = int(shape[0] if len(shape) == 2
                       else math.prod(shape[:-1]))
        capacity = max(int(math.ceil(k * n_tokens / e * cf)), 1)

        def impl(xv, wg, w1, b1, w2, b2):
            flat = xv.reshape(n_tokens, xv.shape[-1])
            logits = flat @ wg
            gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            dispatch, combine, aux = moe_dispatch_combine(gates, k, capacity)
            # [N,E,C] x [N,H] -> [E,C,H]: the all-to-all point (XLA emits
            # it when flat is dp-sharded and w1 is ep-sharded)
            expert_in = jnp.einsum("nec,nh->ech", dispatch,
                                   flat.astype(jnp.float32))
            hdn = jax.nn.gelu(
                jnp.einsum("ech,ehi->eci", expert_in, w1) + b1[:, None])
            y = jnp.einsum("eci,eih->ech", hdn, w2) + b2[:, None]
            out = jnp.einsum("nec,ech->nh", combine, y)
            return out.astype(xv.dtype).reshape(shape), aux

        out, aux = apply("moe_mlp", impl, x, self.gate.weight, self.w1,
                         self.b1, self.w2, self.b2)
        self.aux_loss = aux
        return out


class MoELayer(Layer):
    """Reference ``MoELayer`` parity surface: wraps a gate spec + expert
    shape into the einsum-dispatch ``MoEMLP``. ``gate`` may be "switch"
    (top-1) or "gshard" (top-2), matching the reference gate classes."""

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 capacity_factor=1.25, mesh=None, ep_axis="ep",
                 recompute_interval=0, **kwargs):
        super().__init__()
        if isinstance(gate, str):
            if gate not in ("switch", "gshard", "naive"):
                raise ValueError(f"unknown gate {gate!r}")
            top_k = 1 if gate == "switch" else 2
        else:
            top_k = int(getattr(gate, "top_k", 2))
        self.moe = MoEMLP(d_model, d_hidden, num_experts, top_k=top_k,
                          capacity_factor=capacity_factor, mesh=mesh,
                          ep_axis=ep_axis)

    @property
    def aux_loss(self):
        return self.moe.aux_loss

    def forward(self, x):
        return self.moe(x)

"""Cost model (reference ``python/paddle/cost_model/cost_model.py``:25).

The reference profiles a static program per-op and ships a benchmark JSON
of measured op times. Here the cost source is XLA itself: ``profile_measure``
compiles the jittable function and reads the compiled cost analysis
(FLOPs / bytes accessed — what the reference approximates by measurement),
plus an optional wall-clock measurement on the current device.
"""
from __future__ import annotations

import time

__all__ = ["CostModel"]


class CostModel:
    """Per-program cost estimates from the XLA compiler + measurement."""

    def __init__(self):
        self._static = {}

    def profile_measure(self, fn, example_args=(), device_count=1,
                        measure=True, iters=10):
        """Compile ``fn(*example_args)`` and return its cost dict:
        ``flops``, ``bytes accessed``, optimal-seconds estimate, and (with
        ``measure=True``) measured wall seconds per call."""
        import jax

        jitted = jax.jit(fn)
        lowered = jitted.lower(*example_args)
        compiled = lowered.compile()
        try:
            analysis = compiled.cost_analysis()
        except Exception:
            analysis = {}
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        cost = {
            "flops": float(analysis.get("flops", 0.0)),
            "bytes accessed": float(analysis.get("bytes accessed", 0.0)),
            "optimal_seconds": float(
                analysis.get("optimal_seconds", 0.0)),
        }
        if measure:
            out = jitted(*example_args)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = jitted(*example_args)
            jax.block_until_ready(out)
            cost["measured_seconds"] = (time.perf_counter() - t0) / iters
        return cost

    def static_cost_data(self):
        """Reference ``static_cost_data``: the measured op-time table. Ours
        accumulates from ``get_static_op_time`` probes instead of a
        shipped JSON (costs are device-dependent)."""
        return self._static

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        """Measure (once, cached) a representative run of a framework op
        on a canonical shape, mirroring the reference's per-op benchmark
        table entries {op, time}."""
        import numpy as np

        import paddle_tpu as paddle

        key = (op_name, forward, dtype)
        if key in self._static:
            return self._static[key]
        import paddle_tpu.nn.functional as F

        op = getattr(paddle, op_name, None) or getattr(F, op_name, None)
        if op is None:
            raise ValueError(f"unknown op {op_name!r}")
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(256, 256)).astype(dtype))
        x.stop_gradient = forward  # grads only for the backward probe

        def run():
            y = op(x)
            if forward:
                return y
            s = y.sum()
            s.backward()
            return s

        run()  # warm
        t0 = time.perf_counter()
        for _ in range(5):
            out = run()
        _ = float(out.sum().numpy()) if hasattr(out, "numpy") else out
        entry = {"op": op_name, "time": (time.perf_counter() - t0) / 5}
        self._static[key] = entry
        return entry

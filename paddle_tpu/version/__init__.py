"""``paddle.version`` parity (reference ``python/paddle/version/``)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
commit = "tpu-native"
cuda_version = "False"
cudnn_version = "False"
istaged = False
with_pip_cuda_libraries = "OFF"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("backend: XLA/TPU")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def xpu():
    return "False"

"""Non-finite step guard: in-graph skip of NaN/Inf steps.

One NaN step poisons every parameter it touches and, through Adam's
moments, every later step — on a TPU fleet the blow-up typically lands
long after its cause. ``StepGuard`` makes the bad step a bitwise no-op
INSIDE the compiled step: it computes a finite-ness predicate over the
loss and every gradient, lets the optimizer update run, then
where-blends every written slot (params, master weights, accumulators)
back to its pre-step snapshot when the predicate is false. No host
sync, no recompile, no control flow the tracer can't see — the skip is
a handful of selects fused into the step program.

A device-side consecutive-bad-step counter threads through the compiled
step as ordinary captured state; the host consults it lazily (only when
it already observed a non-finite loss) and raises a coded
``NonFiniteStepError`` once the budget is exceeded. With an
``amp.GradScaler`` attached, each observed bad step also backs the loss
scale off, the reference's dynamic-loss-scaling response.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.errors import NonFiniteStepError
from ..core.tensor import Tensor

__all__ = ["StepGuard"]


class StepGuard:
    """Guard a train step against non-finite loss/grads.

    hapi wiring: ``Model.prepare(..., step_guard=StepGuard())`` (or
    ``step_guard=True``). Custom loops::

        guard = StepGuard(max_bad_steps=3, scaler=scaler)
        loss = loss_fn(...)
        loss.backward()
        guard.guarded_step(opt, loss)   # skips the update when bad
        opt.clear_grad()
        guard.observe(float(loss))      # host: backoff + budget raise

    ``max_bad_steps`` consecutive bad steps are skipped silently; the
    next one raises ``NonFiniteStepError`` (PDT-E013).

    A step can be bad with a FINITE loss (bf16/fp16 overflow in the
    backward pass only) — the host never sees that in the loss scalar,
    so ``observe`` additionally syncs the device streak counter every
    ``grad_sync_every`` good-looking steps; without it a run could
    skip every step bitwise forever while reporting healthy losses.
    """

    def __init__(self, max_bad_steps=3, scaler=None, grad_sync_every=32):
        self.max_bad_steps = int(max_bad_steps)
        self._scaler = scaler
        self.grad_sync_every = max(1, int(grad_sync_every))
        # created HERE so jit capture classifies it as persistent state
        # (input + output of the compiled step), not a step temporary
        self._streak_var = Tensor(jnp.zeros((), jnp.int32))
        self._host_streak = 0
        self._observed = 0
        self.last_skipped = False

    # ------------------------------------------------------------ traced --
    def check(self, loss, optimizer=None):
        """Finite-ness predicate (0-d bool) over the loss and, when an
        optimizer is given, every gradient it would consume."""
        vals = [loss._read() if isinstance(loss, Tensor) else loss]
        if optimizer is not None:
            for _p, g in optimizer._collect():
                vals.append(g._read())
        ok = jnp.asarray(True)
        for v in vals:
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(v)))
        return ok

    def guarded_step(self, optimizer, loss):
        """``optimizer.step()`` that is a bitwise no-op when the step is
        bad. Returns the predicate (traced)."""
        ok = self.check(loss, optimizer)

        # snapshot every slot the update may write: params with grads,
        # their master weights, and all existing accumulators. On the
        # fused multi-tensor path the flat bucket STORAGES are the
        # written slots (optimizer/flat.py) — under jit capture the
        # per-param views are skipped (the compiled program threads
        # only the storages; blending them is a handful of selects
        # instead of O(params)). EAGERLY the views are snapshotted too:
        # a FlatMismatch can defuse the buckets mid-step, and the
        # per-param fallback's writes must still roll back.
        from ..core import tensor as _tm
        snaps = []
        capturing = _tm._tracker is not None

        def _skip(t):
            fv = t._flat_view
            return capturing and fv is not None and fv[1] >= 0
        fused_slots = getattr(optimizer, "_fused_guard_slots", None)
        if fused_slots is not None:
            for t in fused_slots():
                snaps.append((t, t._read()))
        for p, _g in optimizer._collect():
            if not _skip(p):
                snaps.append((p, p._read()))
            mw = optimizer._master_weights.get(id(p))
            if mw is not None and not _skip(mw):
                snaps.append((mw, mw._read()))
        for store in optimizer._accumulators.values():
            for t in store.values():
                if not _skip(t):
                    snaps.append((t, t._read()))

        # accumulators/master weights born DURING this step (only the
        # first-ever optimizer step) blend back to their creation value
        created = []
        orig_acc = optimizer._acc
        orig_master = optimizer._get_master

        def patched_acc(name, p, init=None, dtype=None):
            store = optimizer._accumulators.setdefault(name, {})
            fresh = id(p) not in store
            val = orig_acc(name, p, init=init, dtype=dtype)
            if fresh:
                created.append((store[id(p)], val))
            return val

        def patched_master(p):
            fresh = id(p) not in optimizer._master_weights
            val = orig_master(p)
            if fresh:
                created.append((optimizer._master_weights[id(p)], val))
            return val

        optimizer._acc = patched_acc
        optimizer._get_master = patched_master
        # flat bucket storages born during THIS step (the first fused
        # step builds them) blend back to their creation values, the
        # same first-step contract as patched_acc above
        optimizer._flat_created_log = created
        try:
            optimizer.step()
        finally:
            del optimizer._acc
            del optimizer._get_master
            optimizer._flat_created_log = None

        for t, snap in snaps + created:
            fv = t._flat_view
            if fv is not None and fv[1] >= 0:
                # still a bound flat view at blend time: its bucket
                # storage is itself in the blend set (snapshotted via
                # _fused_guard_slots, or in the created log when born
                # this step) and the view reads through it lazily — a
                # direct write would mark a local override and force a
                # full per-member re-sync of the bucket next step. The
                # view snapshots matter only when a mid-step defuse
                # unbound them, in which case fv is cleared and the
                # write below runs.
                continue
            cur = t._read()
            t._write(jnp.where(ok, cur, snap))

        streak = self._streak_var._read()
        self._streak_var._write(
            jnp.where(ok, jnp.zeros((), jnp.int32), streak + 1))
        return ok

    # -------------------------------------------------------------- host --
    @property
    def bad_streak(self) -> int:
        """Device-side consecutive-bad-step count (host sync; don't call
        from traced code)."""
        return int(np.asarray(self._streak_var._read()))

    def observe(self, loss_value) -> bool:
        """Host-side bookkeeping with the already-fetched loss scalar.
        Returns True when the step was bad. Backs off the attached
        ``GradScaler`` and raises ``NonFiniteStepError`` once MORE than
        ``max_bad_steps`` consecutive steps were bad."""
        self._observed += 1
        bad = not math.isfinite(float(loss_value))
        if not bad and self._observed % self.grad_sync_every == 0:
            # periodic device sync catches grad-only non-finite steps
            # (finite loss, overflowed grads) the loss scalar hides
            bad = self.bad_streak > 0
        if not bad:
            self._host_streak = 0
            self.last_skipped = False
            return False
        self._host_streak += 1
        self.last_skipped = True
        # observability breadcrumbs: the skip streak in the event ring
        # (flight records show the NaN steps preceding a blow-up) and a
        # process-global counter a dashboard can alert on
        from ..observability import events as _events
        from ..observability import metrics as _metrics
        _events.emit("guard.step_skip", streak=self._host_streak)
        _metrics.registry().counter(
            "train.guard_skips",
            "non-finite train steps skipped in-graph by StepGuard").inc()
        if self._scaler is not None and self._scaler.is_enable():
            # the reference GradScaler response: shrink the loss scale
            self._scaler._found_inf = True
            self._scaler._update_scale()
            self._scaler._found_inf = False
        # the device streak also counts bad-grads/finite-loss steps the
        # host never saw; consult it only now that a sync is warranted
        streak = max(self._host_streak, self.bad_streak)
        if streak > self.max_bad_steps:
            raise NonFiniteStepError(
                f"{streak} consecutive non-finite training steps "
                f"(budget {self.max_bad_steps}); every one was skipped, "
                "parameters are still finite. Lower the learning rate, "
                "check the input pipeline for bad records, or enable "
                "loss scaling (amp.GradScaler) if training in fp16. "
                f"[{NonFiniteStepError.error_code}]")
        return True

"""Atomic file writes: temp file + fsync + ``os.replace`` + dir fsync.

A crash (or injected ``torn_write`` fault) at ANY point leaves either
the previous file intact or a stray ``.<name>.tmp.<pid>`` — never a
half-written file under the real name that would later load as garbage.
``framework.save``, the distributed checkpoint writer and the
``COMPLETE`` markers of ``resilience.CheckpointManager`` all commit
through here.
"""
from __future__ import annotations

import contextlib
import os

__all__ = ["atomic_write", "fsync_dir"]


def fsync_dir(path):
    """fsync a directory so a rename within it is durable (best effort —
    some filesystems refuse directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path, mode="wb"):
    """Context manager yielding a file object; on clean exit the bytes
    are fsynced and renamed over ``path`` in one atomic step.

    On a handled ``Exception`` the temp file is removed and ``path`` is
    untouched. On a crash (including the injected ``torn_write`` fault,
    which truncates the temp file to half its bytes and raises
    ``InjectedCrash``) the temp file is left behind — exactly what a
    real power loss leaves — and ``path`` is still untouched.
    """
    from . import faults

    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    f = open(tmp, mode)
    try:
        yield f
        f.flush()
        if faults.check("torn_write", path):
            f.truncate(max(1, f.tell() // 2))
            f.close()
            raise faults.InjectedCrash(f"torn write: {path}")
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
        fsync_dir(d)
    except Exception:
        if not f.closed:
            f.close()
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise

"""Preemption handling: SIGTERM/SIGINT -> checkpoint-and-exit flag.

TPU fleets are preemptible by design; the eviction notice is a signal.
``install()`` swaps in a handler that only sets a flag — the training
loop polls ``requested()`` at step boundaries, writes a final
checkpoint through its ``CheckpointManager`` and exits cleanly, after
which ``Model.fit(resume=True)`` picks the run back up. A second
signal while the first is still being honored restores the previous
disposition and re-raises it, so a stuck checkpoint can still be killed
the ordinary way.

``hapi.Model.fit`` installs/uninstalls this automatically whenever it
has a ``save_dir`` to checkpoint into; custom loops call it directly.
The synthetic ``preempt`` fault (``resilience.faults``) goes through
``signal.raise_signal``, i.e. through this exact path.

The SERVING consumer (ISSUE 20): ``inference.router.FleetRouter``
polls ``requested()`` once per ``step()`` when live migration is on
(``serving_migration``) and answers a planned preemption by putting
its elastically scaled-out replicas (else the last live one, never
the last replica standing) into LAME-DUCK: placements stop and
resident requests migrate warm to the survivors — the eviction notice
loses zero prefill work. The same handler serves both stacks: one
flag, training checkpoints, serving drains.
"""
from __future__ import annotations

import signal
import threading

__all__ = ["install", "uninstall", "requested", "last_signal", "clear",
           "DEFAULT_SIGNALS"]

DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)

_lock = threading.Lock()
_requested = False
_last_signal = None
_prev: dict = {}


def _handler(signum, frame):
    global _requested, _last_signal
    if _requested:
        # second notice: restore the old disposition and re-deliver
        prev = _prev.get(signum, signal.SIG_DFL)
        signal.signal(signum, prev)
        signal.raise_signal(signum)
        return
    _last_signal = signum
    _requested = True
    # flight record at the eviction notice (ISSUE 8): the checkpoint
    # this flag triggers is the run's last act, so the postmortem for
    # "what was the job doing when it was preempted" starts from the
    # last N structured events, not from grepping logs. The handler
    # must never die on observability IO — best effort only.
    try:
        from ..observability import events as _events
        _events.emit("preempt.signal", signum=int(signum))
        _events.dump("preempt_signal", extra={"signum": int(signum)})
    except Exception:
        pass


def install(signals=DEFAULT_SIGNALS) -> bool:
    """Install the flag-setting handler. Returns True when THIS call
    installed it — callers must only uninstall/clear state they own
    (``Model.fit`` inside a user's own install leaves the user's
    handler and any pending request untouched). No-op (False) when
    already installed or off the main thread, where CPython forbids
    ``signal.signal``."""
    with _lock:
        if _prev:
            return False
        for s in signals:
            try:
                _prev[s] = signal.signal(s, _handler)
            except ValueError:  # not the main thread
                _prev.clear()
                return False
        return True


def uninstall():
    """Restore the previous signal dispositions."""
    with _lock:
        for s, h in _prev.items():
            try:
                signal.signal(s, h)
            except ValueError:
                pass
        _prev.clear()


def requested() -> bool:
    """True once a preemption signal arrived (sticky until ``clear``)."""
    return _requested


def last_signal():
    """The signal number that set ``requested`` (None until one did) —
    lets a loop distinguish an eviction (SIGTERM: exit cleanly) from a
    user abort (SIGINT: checkpoint, then re-raise the interrupt)."""
    return _last_signal


def clear():
    global _requested, _last_signal
    _requested = False
    _last_signal = None

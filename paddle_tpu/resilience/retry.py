"""Bounded retry with exponential backoff + jitter.

``retry`` is the decorator form, ``retry_call`` the one-shot form. Only
exception types in ``retry_on`` are retried — anything else (including
``faults.InjectedCrash``, a ``BaseException``) propagates immediately.
Jitter comes from a module-seeded PRNG so backoff sequences are
reproducible within a process; tests that want zero wall time pass
``sleep=lambda s: None``.

Wired into the TCPStore client ops (``distributed/store.py``), the rpc
connect phase (``distributed/rpc/rpc.py``) and ``hapi.hub.download`` —
the paths a flaky network or a restarting peer makes transiently fail.
"""
from __future__ import annotations

import functools
import os
import random
import time

__all__ = ["retry", "retry_call"]

# pid-seeded: jitter MUST differ across the ranks of a job — correlated
# failures (the store host restarting under every worker at once) are
# exactly when the herd needs desynchronizing — while staying
# reproducible within one process
_rng = random.Random(0x7E57ab1e ^ os.getpid())


def retry_call(fn, *, max_attempts=4, base_delay=0.05, max_delay=2.0,
               backoff=2.0, jitter=0.25, retry_on=(ConnectionError,),
               giveup=None, sleep=None, on_retry=None):
    """Call ``fn()`` with up to ``max_attempts`` tries.

    Delay before retry ``k`` (1-based) is
    ``min(max_delay, base_delay * backoff**(k-1)) * (1 + jitter*u)``
    with ``u`` uniform in [0, 1).

    ``giveup(exc) -> bool`` short-circuits retrying for errors that are
    formally in ``retry_on`` but known permanent. ``on_retry(exc, k)``
    runs before the sleep — the hook reconnect-style recovery lives in
    (it must not raise; failures should surface on the next attempt).
    """
    if max_attempts < 1:
        raise ValueError("max_attempts must be >= 1")
    slp = time.sleep if sleep is None else sleep
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            if attempt >= max_attempts or (giveup is not None
                                           and giveup(e)):
                raise
            # structured-event breadcrumb: retries are rare, and a
            # flight record that shows the transient(s) preceding a
            # failure is the whole point of the event ring
            from ..observability import events as _events
            _events.emit("retry.attempt", attempt=attempt,
                         error=f"{type(e).__name__}: {e}"[:200])
            delay = min(max_delay, base_delay * backoff ** (attempt - 1))
            if jitter:
                delay *= 1.0 + jitter * _rng.random()
            if on_retry is not None:
                on_retry(e, attempt)
            slp(delay)


def retry(**cfg):
    """Decorator form of ``retry_call``::

        @retry(max_attempts=5, retry_on=(ConnectionError, TimeoutError))
        def fetch(): ...
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(functools.partial(fn, *args, **kwargs),
                              **cfg)
        return wrapper
    return deco

"""Deterministic fault injection — ``PDTPU_FAULTS`` or programmatic.

The durability-critical paths consult this harness so tests (and chaos
drills) can prove every recovery path with a single env var and zero
sleeps or randomness:

* ``torn_write``         — ``resilience.atomic.atomic_write`` commit:
  the temp file is truncated to half its bytes and ``InjectedCrash``
  (a ``BaseException``) propagates, simulating a process dying
  mid-checkpoint. The destination file is never touched. Key = the
  destination file's path.
* ``store_transient``    — TCPStore client ops (``distributed/
  store.py``) raise ``InjectedConnectionError`` before sending.
  Key = op name (``set``/``get``/``add``/``delete``).
* ``rpc_transient``      — rpc connect phase (``distributed/rpc``).
  Key = target worker name.
* ``download_transient`` — ``hapi.hub.download`` fetch. Key = the
  destination basename.
* ``nan_step``           — the hapi fit loop poisons the step's first
  floating batch input with NaN. Key = 1-based GLOBAL step number.
* ``preempt``            — the hapi fit loop raises a synthetic
  SIGTERM through the real signal path. Key = global step number.
* ``engine_dispatch``    — a serving engine dispatch raises
  ``InjectedConnectionError`` before touching the device; absorbed by
  the bounded retry every dispatch runs under. Key = dispatch kind
  (``mixed``/``decode``/``window``).
* ``engine_nan_decode``  — ONE serving slot's logits are poisoned with
  NaN for one dispatch, drilling the decode guard (that request fails
  with ``finish_reason='failed'``; co-residents are untouched). Key =
  the request id.
* ``engine_page_pressure`` — the engine's page allocator treats the
  free list as empty for one growth attempt, forcing the
  preempt-and-requeue path without shrinking the pool. Key = the
  request id of the slot being grown.
* ``engine_cache_evict``  — the serving prefix cache evicts its LRU
  cached page on one allocation even while free pages remain, forcing
  the eviction path (an evicted prefix transparently re-prefills with
  bitwise-identical output). Key = the request id the allocation
  serves.
* ``engine_draft_nan``    — ONE slot's speculative verify rows are
  poisoned with NaN for one dispatch, drilling the per-draft decode
  guard (that request fails with ``finish_reason='failed'``
  PDT-E018; co-resident slots keep decoding bitwise). Key = the
  request id.
* ``engine_draft_mismatch`` — one slot's draft proposal is corrupted
  (tokens shifted mod vocab) before the verify dispatch, forcing the
  rejection path: outputs stay bitwise, accepted-draft counters drop.
  Key = the request id.
* ``engine_handoff_transient`` — one disaggregated-serving KV-page
  handoff (``inference.distserve.KVPageTransport.ship``) raises
  ``InjectedConnectionError`` before the transfer; absorbed by the
  bounded retry every handoff runs under. Key = the request id.
* ``engine_decode_worker_lost`` — the decode worker is treated as
  dead at handoff: the payload is discarded and the coordinator
  requeues the request to the prefill group for a from-scratch
  re-prefill (outputs bitwise; the ``requeues`` counter moves). Key =
  the request id.
* ``engine_stall``       — one serving dispatch hangs (bounded Python
  spin) to drill the stall watchdog
  (``observability/watchdog.py``): stacks + flight record + Chrome
  trace are captured and a coded ``EngineStallError`` (PDT-E020) is
  injected into the stalled dispatch; co-residents complete bitwise
  on the re-dispatch. Key = dispatch kind (``mixed``/``decode``/
  ``window``/``verify``).
* ``router_replica_lost`` — one fleet-serving replica
  (``inference.router.FleetRouter``) is declared dead mid-decode:
  the router requeues its queued AND in-flight requests to the
  surviving replicas (from-scratch re-prefill, restoring from the
  survivors' prefix caches where pages match) — outputs stay
  bitwise, the ``deaths``/``requeues`` counters move, and exactly
  one coded flight record (``ReplicaLostError`` PDT-E024) is
  written. Key = the replica name.
* ``router_dispatch_transient`` — one router->replica placement
  raises ``InjectedConnectionError``; absorbed by the bounded retry
  every placement runs under (``serving_fleet_dispatch_retries``;
  the router ``retries`` counter moves). Key = the request id.
* ``router_scaleout_stall`` — one standby-replica admission
  (SLO-breach scale-out) hangs; past the scale-out watchdog deadline
  it surfaces ``EngineStallError`` (PDT-E020) + a flight record and
  the fleet degrades gracefully (standby stays parked, live replicas
  keep serving). Key = the standby replica name.
* ``router_migration_transient`` — one live-migration snapshot
  transfer (``KVPageTransport.ship_snapshot``, ISSUE 20) raises
  ``InjectedConnectionError``; absorbed by the bounded retry every
  transfer runs under (``serving_migration_retries``); past the
  budget the router writes ONE ``MigrationError`` (PDT-E025) flight
  record and falls back to the PR17 cold requeue (bitwise, demand
  counted once). Key = the request id.
* ``engine_snapshot_torn`` — one migration payload arrives torn (a
  KV byte flipped in flight): ``restore_request`` rejects it on CRC
  validation (``MigrationError`` PDT-E025) and the SOURCE keeps the
  request resident, decoding on bitwise. Key = the request id.
* ``rank_dead``          — an elastic-training rank
  (``resilience/elastic_train.py`` ``FleetSupervisor``) dies at a
  step boundary: heartbeats stop, its collective contribution never
  arrives — survivors get ``CollectiveTimeoutError`` (PDT-E021),
  the membership generation bumps, and recovery restores the dead
  rank's state from its buddy's in-memory replica. Key = the rank.
* ``slow_rank``          — one elastic rank stalls ``slow_rank_s``
  before contributing (a straggler, NOT a death): heartbeats keep
  flowing and peers absorb the wait inside ``collective_timeout_ms``
  — NO recovery triggers (detector vs straggler separation). Key =
  the rank.
* ``store_partition``    — one supervisor-level store operation
  (snapshot replication push) raises ``InjectedConnectionError`` per
  firing; absorbed by the supervisor's bounded retry; past the
  budget that snapshot generation is skipped (training continues,
  ``elastic.snapshot_push_failures`` moves). Key = the node id.
* ``snapshot_torn``      — a buddy-snapshot replica transfer writes
  half of one chunk's bytes while the manifest records the full
  size/CRC: the receiving buddy's validation rejects the generation
  and keeps the previous COMPLETE one, which recovery then restores.
  Key = the source rank.

Spec grammar (``;``-separated rules)::

    PDTPU_FAULTS="site[:match][*times][@at][;...]"

    site   injection point (table above)
    match  fnmatch glob the site key must match (default ``*``); for
           step-indexed sites the key is the step number, so
           ``nan_step:6`` means "global step 6"
    times  how many matching occurrences fire (default 1; 0 = every)
    at     1-based matching-occurrence index of the first firing
           (default 1)

Examples::

    PDTPU_FAULTS="store_transient:get*2"    # first two gets fail
    PDTPU_FAULTS="torn_write:*step_8*"      # kill that ckpt mid-file
    PDTPU_FAULTS="nan_step:6;preempt:10"    # NaN step 6, SIGTERM @10

Counting is per-rule and purely occurrence-based, so a given spec
replays identically on every run — the property the recovery tests
(``tests/test_resilience.py``) rely on.
"""
from __future__ import annotations

import fnmatch
import os
import threading

__all__ = [
    "InjectedCrash", "InjectedConnectionError", "Rule", "inject",
    "check", "maybe_raise", "clear", "reset", "active", "parse",
]


class InjectedCrash(BaseException):
    """A simulated process death (torn write). Deliberately NOT an
    ``Exception``: cleanup handlers that swallow ``Exception`` must not
    'survive' a crash the harness asked for."""


class InjectedConnectionError(ConnectionError):
    """A simulated transient network failure — a real ``ConnectionError``
    (so retry/backoff treats it exactly like one) that tests can also
    match on specifically."""


class Rule:
    """One injection rule: fire ``times`` times starting at the
    ``at``-th occurrence whose key matches ``match``."""

    def __init__(self, site, match="*", times=1, at=1):
        self.site = str(site)
        self.match = match or "*"
        self.times = int(times)
        self.at = max(1, int(at))
        self.seen = 0   # matching occurrences observed
        self.fired = 0  # occurrences that fired

    def __repr__(self):
        return (f"Rule({self.site}:{self.match}*{self.times}"
                f"@{self.at} seen={self.seen} fired={self.fired})")


_lock = threading.Lock()
_rules: list[Rule] = []
_env_loaded = False


def parse(spec: str) -> list[Rule]:
    """Parse a ``PDTPU_FAULTS`` spec string into rules."""
    rules = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        at = 1
        if "@" in part:
            part, at_s = part.rsplit("@", 1)
            at = int(at_s)
        times = 1
        # trailing *N is a count; a bare * inside match stays a glob
        head, star, tail = part.rpartition("*")
        if star and tail.isdigit():
            part, times = head, int(tail)
        site, sep, match = part.partition(":")
        rules.append(Rule(site.strip(), match.strip() if sep else "*",
                          times, at))
    return rules


def _load_env(force=False):
    global _env_loaded
    if _env_loaded and not force:
        return
    _env_loaded = True
    spec = os.environ.get("PDTPU_FAULTS", "")
    if spec:
        _rules.extend(parse(spec))


def inject(site, match="*", times=1, at=1) -> Rule:
    """Programmatically arm a rule; returns it (inspect ``.fired``)."""
    rule = Rule(site, match, times, at)
    with _lock:
        _rules.append(rule)
    return rule


def clear():
    """Drop every rule (env rules included; they do NOT re-arm until
    ``reset``)."""
    global _env_loaded
    with _lock:
        _rules.clear()
        _env_loaded = True


def reset():
    """Drop every rule and re-parse ``PDTPU_FAULTS`` from scratch."""
    global _env_loaded
    with _lock:
        _rules.clear()
        _env_loaded = False
        _load_env()


def active() -> list[Rule]:
    with _lock:
        _load_env()
        return list(_rules)


def check(site: str, key: str = "") -> bool:
    """True when an armed rule matches this occurrence (consumes one
    firing). Sites call this at their injection point and raise/act
    themselves — the harness only decides."""
    with _lock:
        _load_env()
        for rule in _rules:
            if rule.site != site:
                continue
            if not fnmatch.fnmatch(str(key), rule.match):
                continue
            rule.seen += 1
            if rule.seen >= rule.at and (rule.times == 0
                                         or rule.fired < rule.times):
                rule.fired += 1
                fired_site = rule.site
                break
        else:
            return False
    # outside the lock: a firing is an event the flight recorder wants
    # in its ring (drills should read like the real failures they
    # simulate), and emit takes the ring's own lock
    try:
        from ..observability import events as _events
        _events.emit("fault.fired", site=fired_site, key=str(key))
    except Exception:
        pass
    return True


def maybe_raise(site: str, key: str, exc_type=InjectedConnectionError):
    """Raise ``exc_type`` when a rule fires — the one-liner for
    transient-failure sites."""
    if check(site, key):
        raise exc_type(f"injected {site} fault (key={key!r})")

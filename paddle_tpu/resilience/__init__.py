"""paddle_tpu.resilience — the fault-tolerance layer.

At production scale preemptions, torn writes, flaky stores and loss
blow-ups are routine; the framework, not the user, owns surviving them
(SURVEY D23; the reference's elastic manager + comm watchdog +
checkpoint manifests). Five pieces, each wired into the rest of the
stack:

* ``faults``            — deterministic fault injection
  (``PDTPU_FAULTS=<spec>`` or programmatic) that the recovery tests
  drive: torn checkpoint writes, transient store/rpc/download errors,
  NaN steps, synthetic preemption.
* ``atomic_write``      — temp + fsync + ``os.replace`` commit used by
  ``framework.save``, the distributed checkpoint writer and the
  COMPLETE markers.
* ``CheckpointManager`` — ``step_<N>`` versioned checkpoints with
  COMPLETE markers, keep-last-K GC and newest-complete fallback on
  load; ``hapi.Model.fit(save_dir=..., resume=True)`` rides it.
* ``retry``/``retry_call`` — bounded exponential backoff + jitter,
  wired into TCPStore ops, rpc connects and hub downloads.
* ``StepGuard``         — in-graph skip of non-finite steps with a
  consecutive-bad-step budget (``NonFiniteStepError`` PDT-E013) and
  GradScaler backoff; ``preempt`` — SIGTERM/SIGINT ->
  checkpoint-on-preempt + clean exit.
* ``serving``           — the serving-side analogs (ISSUE 5): the
  per-request decode guard (``DecodeGuard`` + in-graph flag; a bad
  request fails alone with ``NonFiniteLogitsError`` PDT-E018), the
  bounded-retry dispatch wrapper, and the ``engine_dispatch`` /
  ``engine_nan_decode`` / ``engine_page_pressure`` fault sites the
  serving drills fire.
* ``elastic_train``     — elastic training recovery (ISSUE 15):
  ``FleetSupervisor`` arms a fit loop with buddy in-memory snapshots
  (replicated to rank ``(r+1) % W`` off the step path), a collective
  watchdog (a dead peer surfaces as ``CollectiveTimeoutError``
  PDT-E021 with a flight dump instead of an infinite hang), and
  failure-detector-driven resume: quiesce survivors, reshard the DP
  group, restore the dead rank's state from its buddy (disk
  ``CheckpointManager`` fallback only when the buddy is also gone),
  fast-forward the data position, continue ``fit``.
"""
from . import elastic_train  # noqa: F401
from . import faults  # noqa: F401
from . import preempt  # noqa: F401
from . import serving  # noqa: F401
from .atomic import atomic_write, fsync_dir  # noqa: F401
from .checkpoint import CheckpointManager  # noqa: F401
from .elastic_train import FleetSupervisor  # noqa: F401
from .guard import StepGuard  # noqa: F401
from .retry import retry, retry_call  # noqa: F401
from .serving import DecodeGuard  # noqa: F401

__all__ = [
    "faults", "preempt", "serving", "elastic_train", "atomic_write",
    "fsync_dir", "CheckpointManager", "FleetSupervisor", "StepGuard",
    "DecodeGuard", "retry", "retry_call",
]

"""Serving-side resilience: the decode guard and the serving fault
sites (ISSUE 5).

The training stack guards a step with :class:`resilience.StepGuard` —
an in-graph finite-ness predicate that makes a bad step a bitwise
no-op. Serving needs the per-REQUEST analog: one request whose logits
go non-finite (bad weights region, poisoned KV, an injected drill)
must fail alone, never the engine or its co-resident requests. The
pieces here are model-agnostic and host-side; the in-graph half
(:func:`models.generation.guarded_argmax`) rides inside the engine's
compiled mixed/decode programs as a device-side flag, so detection
costs no extra host sync.

Serving fault sites (``resilience.faults`` spec grammar):

* ``engine_dispatch``      — raises ``InjectedConnectionError`` at the
  top of an engine dispatch; absorbed by the bounded retry every
  dispatch runs under. Key = dispatch kind (``mixed``/``decode``/
  ``window``).
* ``engine_nan_decode``    — poisons ONE slot's logits with NaN for
  one dispatch (host-built poison vector, added in-graph), drilling
  the decode guard. Key = the request id.
* ``engine_page_pressure`` — makes the page allocator behave as if
  the free list were empty for one growth attempt, drilling
  preempt-and-requeue without shrinking the pool. Key = the request
  id of the slot being grown.
* ``engine_cache_evict`` — forces the prefix cache
  (``inference/prefix_cache.py``) to evict its LRU cached page on one
  allocation even while free pages remain, drilling eviction-then-
  transparent-re-prefill without filling the pool. Key = the request
  id the allocation serves.
* ``engine_draft_nan`` — poisons ONE slot's speculative VERIFY rows
  with NaN for one dispatch (ISSUE 9): the per-draft guard
  (``models.generation.verify_argmax``) fails exactly that request
  with PDT-E018 while co-resident slots keep decoding. Key = the
  request id.
* ``engine_draft_mismatch`` — corrupts one slot's draft proposal
  (tokens shifted mod vocab) so the verify step rejects it, forcing
  the 0-accept path: outputs stay bitwise (the acceptance rule is
  correct for ANY drafts), only the accept rate moves. Key = the
  request id.
* ``engine_handoff_transient`` — one KV-page handoff transfer
  (``inference.distserve.KVPageTransport.ship``) raises
  ``InjectedConnectionError``; absorbed by the bounded
  ``resilience.retry`` every transfer runs under
  (``serving_disagg_handoff_retries``). Key = the request id.
* ``engine_decode_worker_lost`` — the decode worker is treated as
  dead at handoff time: the shipped payload is DISCARDED and the
  coordinator requeues the request to the prefill group, which
  re-prefills it from token zero — outputs stay bitwise (greedy
  prefill+decode is deterministic), only ``requeues`` moves. Key =
  the request id.
* ``engine_stall`` — one engine dispatch HANGS (a bounded Python
  spin standing in for a wedged device tunnel), drilling the stall
  watchdog (``observability/watchdog.py``): past ``watchdog_ms`` the
  watchdog captures thread stacks, dumps the flight record + Chrome
  trace and injects ``EngineStallError`` (PDT-E020) into the spinning
  dispatch, which surfaces coded from ``step()`` — co-resident
  requests then complete bitwise on the re-dispatched plan. Key =
  dispatch kind (``mixed``/``decode``/``window``/``verify``).
* ``router_replica_lost`` — one fleet replica
  (``inference.router.FleetRouter``) is declared dead mid-decode:
  its queued AND in-flight requests requeue to the surviving
  replicas, which re-prefill them from token zero (restoring from
  their own prefix caches where pages match) — outputs stay bitwise
  (greedy decode is deterministic and batch-invariant), only
  ``requeues``/``deaths`` move and exactly one coded flight record
  (``ReplicaLostError`` PDT-E024) is written. Key = the replica
  name.
* ``router_dispatch_transient`` — one router->replica placement
  dispatch raises ``InjectedConnectionError``; absorbed by the
  bounded ``resilience.retry`` every placement runs under
  (``serving_fleet_dispatch_retries``), only the router ``retries``
  counter moves. Exhausting the retry budget is treated as a dead
  replica (the request requeues, the replica is killed). Key = the
  request id.
* ``router_scaleout_stall`` — one standby-replica admission
  (SLO-breach scale-out) HANGS, drilling the scale-out watchdog:
  past ``serving_fleet_scaleout_timeout_ms`` the admission surfaces
  ``EngineStallError`` (PDT-E020) with a flight record and the fleet
  DEGRADES GRACEFULLY — the standby stays parked and the live
  replicas keep serving. Key = the standby replica name.
* ``router_migration_transient`` — one live-migration snapshot
  transfer (``inference.distserve.KVPageTransport.ship_snapshot``,
  ISSUE 20) raises ``InjectedConnectionError``; absorbed by the
  bounded ``resilience.retry`` every transfer runs under
  (``serving_migration_retries``), only ``migration_retries`` moves.
  Exhausting the budget writes exactly one ``MigrationError``
  (PDT-E025) flight record and falls back to the PR17 COLD requeue:
  the source discards the resident silently, the request re-prefills
  front-of-line on a survivor — outputs stay bitwise (greedy decode
  is deterministic), demand is counted once. Key = the request id.
* ``engine_snapshot_torn`` — one migration payload arrives TORN at
  the destination (a byte of its KV pool bytes flipped in flight):
  ``restore_request`` rejects it on CRC validation with
  ``MigrationError`` (PDT-E025) and the SOURCE keeps the request —
  it stays resident and keeps decoding there, bitwise; only
  ``migration_failures`` moves. Key = the request id.
"""
from __future__ import annotations

import numpy as np

from ..core.errors import NonFiniteLogitsError
from . import faults

__all__ = [
    "FINISH_REASONS", "DecodeGuard", "dispatch_retry",
    "simulated_stall",
    "SITE_DISPATCH", "SITE_NAN_DECODE", "SITE_PAGE_PRESSURE",
    "SITE_CACHE_EVICT", "SITE_DRAFT_NAN", "SITE_DRAFT_MISMATCH",
    "SITE_HANDOFF_TRANSIENT", "SITE_DECODE_WORKER_LOST",
    "SITE_STALL", "SITE_ROUTER_REPLICA_LOST",
    "SITE_ROUTER_DISPATCH_TRANSIENT", "SITE_ROUTER_SCALEOUT_STALL",
    "SITE_MIGRATION_TRANSIENT", "SITE_SNAPSHOT_TORN",
]

#: Every value ``CompletedRequest.finish_reason`` can take.
FINISH_REASONS = ("stop", "length", "timeout", "cancelled", "failed")

SITE_DISPATCH = "engine_dispatch"
SITE_NAN_DECODE = "engine_nan_decode"
SITE_PAGE_PRESSURE = "engine_page_pressure"
SITE_CACHE_EVICT = "engine_cache_evict"
SITE_DRAFT_NAN = "engine_draft_nan"
SITE_DRAFT_MISMATCH = "engine_draft_mismatch"
SITE_HANDOFF_TRANSIENT = "engine_handoff_transient"
SITE_DECODE_WORKER_LOST = "engine_decode_worker_lost"
SITE_STALL = "engine_stall"
SITE_ROUTER_REPLICA_LOST = "router_replica_lost"
SITE_ROUTER_DISPATCH_TRANSIENT = "router_dispatch_transient"
SITE_ROUTER_SCALEOUT_STALL = "router_scaleout_stall"
SITE_MIGRATION_TRANSIENT = "router_migration_transient"
SITE_SNAPSHOT_TORN = "engine_snapshot_torn"


def simulated_stall(key: str, max_s: float = 30.0, site: str = SITE_STALL):
    """The ``engine_stall`` drill body: when the site fires, spin in
    Python (interpreter-visible, so the watchdog's injected
    ``EngineStallError`` lands at the next bytecode boundary — a real
    wedged C call could only be stack-dumped).  The spin is BOUNDED:
    with no watchdog armed the drill raises after ``max_s`` instead of
    hanging tier-1, which is the exact failure mode the watchdog
    exists to prevent.  ``site`` lets the other stall drills
    (``router_scaleout_stall``) reuse the same body."""
    import time as _time
    if not faults.check(site, key=str(key)):
        return
    t0 = _time.monotonic()
    while _time.monotonic() - t0 < max_s:
        _time.sleep(0.002)
    raise RuntimeError(
        f"{site} drill (key={key!r}): no watchdog interrupted "
        f"the stalled dispatch within {max_s}s — arm watchdog_ms / "
        "the watchdog_stall_ms flag when drilling this site")


class DecodeGuard:
    """Host half of the serving decode guard.

    Builds the per-slot poison vector each dispatch (NaN where the
    ``engine_nan_decode`` drill fires, else 0.0 — adding 0.0f to finite
    logits is argmax-invariant, so the guard is free when idle) and
    turns a device-reported bad flag into the coded error the engine
    records on the failed request.
    """

    def __init__(self, max_slots: int):
        self.max_slots = int(max_slots)

    def poison(self, slot_rids, sites=(SITE_NAN_DECODE,)) -> np.ndarray:
        """[max_slots] float32: NaN for slots whose request id fires
        one of the ``sites`` this dispatch, 0.0 elsewhere.
        ``slot_rids`` maps slot index -> request id (None = idle); the
        speculative verify dispatch adds ``engine_draft_nan`` so a
        NaN'd draft drills the per-draft guard."""
        vec = np.zeros(self.max_slots, np.float32)
        for b, rid in enumerate(slot_rids):
            if rid is None:
                continue
            for site in sites:
                if faults.check(site, key=str(rid)):
                    vec[b] = np.nan
                    # flight-recorder breadcrumb: the poison lands one
                    # dispatch before the guard reports it, so the
                    # drilled timeline reads cause -> effect like a
                    # real NaN would
                    from ..observability import events as _events
                    _events.emit("serving.nan_poison", rid=rid, slot=b,
                                 site=site)
                    break
        return vec

    @staticmethod
    def failure(rid, position) -> NonFiniteLogitsError:
        """The coded error recorded on a guard-failed request (never
        raised through the engine loop)."""
        return NonFiniteLogitsError(
            f"request {rid!r}: non-finite logits at position "
            f"{position} — decode guard failed this request only "
            f"[{NonFiniteLogitsError.error_code}]")


def dispatch_retry(kind: str, fn, *, max_attempts=3, on_retry=None):
    """Run one engine dispatch under bounded retry.

    The ``engine_dispatch`` fault check sits INSIDE the retried
    closure, so an injected transient is consumed per attempt and a
    ``*N``-spec drill is absorbed by ``N`` retries exactly like a real
    transient ConnectionError from a network-attached device. Delays
    are kept tiny: a serving step retried at human backoff scales
    would blow the latency budget before the second attempt.
    """
    from .retry import retry_call

    def call():
        faults.maybe_raise(SITE_DISPATCH, kind)
        simulated_stall(kind)
        return fn()

    return retry_call(call, max_attempts=max(1, int(max_attempts)),
                      base_delay=0.005, max_delay=0.05,
                      retry_on=(ConnectionError,), on_retry=on_retry)

"""Elastic training recovery (ISSUE 15): failure-detector-driven
resume with buddy in-memory snapshots and a collective watchdog.

At fleet scale the dominant availability cost is not the crash but the
recovery: a single dead rank hangs every survivor inside a psum, and
the classic way back is a full restart from on-disk checkpoints.  The
:class:`FleetSupervisor` arms a training loop against rank failure end
to end:

* **Buddy in-memory snapshots** — every ``snapshot_every`` optimizer
  steps each rank snapshots model/optimizer/RNG state to host memory
  (with the PR4 fused optimizer the state it reads is views over a
  handful of contiguous flat dtype buckets, not thousands of tensors)
  and replicates it to its buddy rank ``(r + elastic_buddy) % W``
  asynchronously off the step path: the capture happens at the step
  boundary, the chunked transfer rides a dedicated TCPStore connection
  under bounded :func:`resilience.retry` in a background thread.  The
  store is the *transport*, not the home: the buddy's receiver thread
  pulls each generation into its own process memory (validating
  per-chunk sizes + CRCs — a half-written replica is discarded and the
  previous generation kept, the ``snapshot_torn`` drill) and the
  writer deletes transfer keys beyond the last two generations, so
  store footprint stays bounded and replicas die with their holder —
  which is exactly what makes the buddy-also-dead disk fallback real.

* **Collective watchdog** — the supervisor's store-backed allreduce
  (and, via ``observability.watchdog.arm_collective``, the device
  collectives ``Group.psum_mean`` / ``DataParallel.
  apply_collective_grads`` / the pipeline ppermute dispatches) runs
  under a ``collective_timeout_ms`` deadline: a dead peer surfaces as
  a coded :class:`~paddle_tpu.core.errors.CollectiveTimeoutError`
  (PDT-E021) with every thread's stack in a flight record, instead of
  an infinite hang.  Metrics-off keeps a supervisor-side hard deadline
  (no dump — observability off is observability off) so recovery still
  functions.

* **Elastic resume** — on a detected membership change (an
  :class:`~paddle_tpu.distributed.elastic.ElasticManager` generation
  bump at a step boundary, or PDT-E021 out of a blocked collective)
  every survivor unwinds its ``Model.fit`` at the step boundary,
  meets the others at a quiesce barrier, reshards the data-parallel
  group to the new world size (rank/world re-derived from the new
  membership; the batch-granular data shard re-strides), restores the
  dead rank's state from its buddy's in-memory replica (falling back
  to the newest COMPLETE ``CheckpointManager`` version only when the
  buddy is also gone), fast-forwards the data position to the
  snapshot's consumed-batch count, and re-enters ``fit`` — the
  post-recovery loss trajectory equals an unfaulted run restarted at
  that step on the same data order.

Why survivors restore the snapshot instead of continuing their live
state: the death is detected mid-step, after each survivor already
applied its LOCAL update for the step whose sync never completed —
survivor states have diverged from each other by exactly that unsynced
step.  The snapshot is the newest provably-consistent point; rolling
back to it is what makes the resumed trajectory well-defined.

CPU-testable like ``tests/test_elastic.py`` / ``tests/test_rpc_store.py``:
each "rank" is a thread with its own model, optimizer, data shard and
TCPStore connections; the data-parallel sync is the supervisor's
store-backed parameter allreduce (``sync_each_step=True``, the
single-process stand-in for the cross-host psum — on a real pod the
in-graph GSPMD psum owns gradient sync and ``sync_each_step`` stays
off; the supervisor then adds only detection/snapshot/recovery around
the compiled step).

Fault sites (``resilience.faults`` grammar; key = the RANK for the
first three, the source rank for ``snapshot_torn``):

* ``rank_dead``       — the rank's worker dies at a step boundary:
  heartbeats stop, its collective contribution never arrives.
* ``slow_rank``       — the rank stalls ``slow_rank_s`` before its
  contribution: a straggler, NOT a death — peers wait it out inside
  the collective deadline and no recovery triggers (detector vs
  straggler separation).
* ``store_partition`` — one supervisor-level store operation (snapshot
  push) raises ``InjectedConnectionError`` per firing; absorbed by the
  bounded retry; past the budget that snapshot generation is skipped
  (counter ``elastic.snapshot_push_failures``) and training continues.
* ``snapshot_torn``   — the replica transfer writes half of one
  chunk's bytes while the manifest records the full size/CRC (the
  reordered-delivery / partial-receive failure a real transport can
  produce): the buddy's validation rejects the generation and keeps
  the previous one.

Metrics (PR8 registry, ``render_prometheus()``-visible):
``elastic.snapshots`` / ``elastic.snapshot_ms`` (capture->replicated
wall) / ``elastic.snapshots_torn`` / ``elastic.snapshot_push_failures``
/ ``elastic.recoveries`` / ``elastic.recovery_ms`` /
``elastic.generation``.
"""
from __future__ import annotations

import pickle
import threading
import time
import zlib

import numpy as np

from ..core.errors import (CheckpointNotFoundError,
                           CollectiveTimeoutError, StoreTimeoutError)
from . import faults
from .retry import retry_call

__all__ = ["FleetSupervisor", "MembershipChanged"]

_P = "elastic_train"  # store-key namespace


class MembershipChanged(Exception):
    """Raised out of the fit loop at a step boundary when the
    ElasticManager published a generation with different members —
    the supervisor catches it and runs recovery."""

    def __init__(self, gen, members):
        super().__init__(f"generation {gen}: members {members}")
        self.gen = gen
        self.members = members


class _RankDead(Exception):
    """Internal: the ``rank_dead`` drill killed this worker."""


class _TornReplica(Exception):
    """Internal: a fetched replica failed size/CRC validation."""


def _to_np(obj):
    """Recursively convert a state-dict-shaped object to plain numpy /
    scalars so it pickles without framework types."""
    from ..core.tensor import Tensor
    if isinstance(obj, Tensor):
        return np.asarray(obj.numpy())
    if isinstance(obj, dict):
        return {k: _to_np(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_np(v) for v in obj]
    return obj


def _shard_view(data, batch_size, rank, world, offset_batches):
    """Batch-granular shard of ``data``: local batch ``b`` of this rank
    is the contiguous global batch ``offset + b*world + rank``, so a
    resumed run at a NEW world size reconstructs the exact remaining
    batch stream by carrying ``offset`` (consumed global batches)
    forward — the property the loss-parity acceptance drill pins."""
    from ..io import Dataset

    bs = int(batch_size)
    total_batches = len(data) // bs
    avail = max(0, total_batches - int(offset_batches))
    nbatches = avail // max(1, world)

    class _Shard(Dataset):
        def __len__(self):
            return nbatches * bs

        def __getitem__(self, j):
            b, r = divmod(int(j), bs)
            g = int(offset_batches) + b * world + rank
            return data[g * bs + r]

    return _Shard()


def _supervisor_callback(sup, model):
    from ..hapi.callbacks import Callback

    class _Cb(Callback):
        def on_train_batch_end(self, step, logs=None):
            sup._on_step(model, logs)

    return _Cb()


class FleetSupervisor:
    """Arms one rank's training for elastic recovery (module docstring).

    One supervisor per rank.  ``host``/``port`` address the rendezvous
    TCPStore (hosted by the launcher or externally — the supervisor
    only connects; it opens separate connections for membership
    heartbeats, blocking collectives and bulk snapshot transfer so a
    blocked barrier can never starve the heartbeat).  ``node_id`` must
    be unique per rank; the designated initial master (conventionally
    rank 0) passes ``is_master=True`` — on its death the standby
    election in ``distributed/elastic.py`` takes over scanning.

    ``fit(model, data, ...)`` wraps ``hapi.Model.fit`` in the
    join -> train -> (recover -> train)* loop and returns True when
    training completed, False when this rank died (the ``rank_dead``
    drill).  ``checkpoint_manager`` is the disk fallback used only
    when no buddy replica survives.
    """

    def __init__(self, host, port, node_id, world_size, *,
                 is_master=False, snapshot_every=None, buddy=None,
                 collective_timeout_ms=None, sync_each_step=True,
                 checkpoint_manager=None, heartbeat_interval=0.5,
                 heartbeat_timeout=2.5, recovery_timeout_s=60.0,
                 store_retries=3, chunk_bytes=1 << 20,
                 slow_rank_s=0.25, keep_snapshots=2,
                 recv_poll_s=0.05):
        from ..core import state as _state
        self.host, self.port = host, int(port)
        self.node_id = str(node_id)
        self.world_size = int(world_size)
        self.is_master = bool(is_master)
        if snapshot_every is None:
            snapshot_every = _state.get_flag("elastic_snapshot_every")
        self.snapshot_every = max(0, int(snapshot_every))
        if buddy is None:
            buddy = _state.get_flag("elastic_buddy")
        self.buddy = max(1, int(buddy))
        if collective_timeout_ms is None:
            collective_timeout_ms = _state.get_flag(
                "collective_timeout_ms")
        # the supervisor NEEDS a deadline — the blocked collective IS
        # its failure detector — so flag 0 means "default", not "off"
        self.collective_timeout_ms = float(collective_timeout_ms) \
            or 30000.0
        self.sync_each_step = bool(sync_each_step)
        self.mgr = checkpoint_manager
        self.hb_interval = float(heartbeat_interval)
        self.hb_timeout = float(heartbeat_timeout)
        self.recovery_timeout_s = float(recovery_timeout_s)
        self.store_retries = max(1, int(store_retries))
        self.chunk_bytes = max(1024, int(chunk_bytes))
        self.slow_rank_s = float(slow_rank_s)
        self.keep_snapshots = max(1, int(keep_snapshots))
        self.recv_poll_s = float(recv_poll_s)

        # membership (set at _join / _recover)
        self.rank = -1
        self.world = 0
        self._gen = 0
        self._members: list[str] = []
        # data position
        self._gstep = 0       # optimizer steps completed (global)
        self._consumed = 0    # global batches consumed by the fleet
        self._epoch = 0
        # collective-key epoch: bumped in lockstep at every completed
        # recovery so rolled-back steps never reuse pre-crash ar tags
        self._epoch_ar = 0
        # snapshots: mine + the replicas I hold for the rank I buddy
        self._replicas: dict[str, list] = {}   # node -> [(step, meta, payload)]
        self._local: list = []                 # [(step, meta, payload)]
        self._pushed: list[tuple[int, int, int]] = []  # [(epoch, step, nchunks)]
        self._pending = None                   # latest-wins push queue
        self._restored_gen = -1                # last gen whose restore applied
        self._restored_info = None             # (meta, plan, dead) of it
        self._gens_touched: set = set()        # recovery gens with our keys
        self._restore_pushed: dict = {}        # gen -> nchunks we pushed
        self._qlock = threading.Lock()
        self._qev = threading.Event()
        self._stop = threading.Event()
        self._mgr_elastic = None
        self._stores = None
        self._threads = []
        self._sync_cache = None                # (params, shapes, sizes)
        self._last_ar_tags: list[str] = []
        self.last_recovery = None
        self.dead = False

    # ------------------------------------------------------------ wiring --
    def _connect(self):
        from ..distributed.store import TCPStore
        # three connections: heartbeats/membership must never queue
        # behind a blocked barrier or a megabyte chunk transfer
        self._store = TCPStore(self.host, self.port)    # membership
        self._bstore = TCPStore(self.host, self.port)   # collectives
        self._xstore = TCPStore(self.host, self.port)   # snapshots
        self._stores = (self._store, self._bstore, self._xstore)

    def _join(self):
        from ..distributed.elastic import ElasticManager
        if self._stores is None:
            self._connect()
        self._mgr_elastic = ElasticManager(
            self._store, self.node_id, self.is_master,
            heartbeat_interval=self.hb_interval,
            heartbeat_timeout=self.hb_timeout,
            min_nodes=self.world_size)
        gen, members = self._mgr_elastic.start()
        self._adopt(gen, members)
        t1 = threading.Thread(target=self._replicator_loop,
                              name=f"et-push-{self.node_id}",
                              daemon=True)
        t2 = threading.Thread(target=self._receiver_loop,
                              name=f"et-recv-{self.node_id}",
                              daemon=True)
        self._threads = [t1, t2]
        t1.start()
        t2.start()

    def _adopt(self, gen, members):
        self._gen = int(gen)
        # canonical (sorted) member order: the ElasticManager publishes
        # members in REGISTRATION order, which is a race between
        # concurrently joining ranks — every supervisor sorts the same
        # list, so rank assignment, the buddy ring and the leader
        # choice are deterministic functions of the node ids alone
        self._members = sorted(members)
        self.rank = self._members.index(self.node_id)
        self.world = len(self._members)
        # prune replica holdings to the node we now buddy for: the
        # restore plan only ever consults the CURRENT buddy mapping, so
        # holdings for former sources (dead ranks, reshard-shifted
        # rings) are dead weight — full model+opt payloads that would
        # otherwise stay resident for the life of the job
        src = self._replica_source()
        for k in [k for k in self._replicas if k != src]:
            del self._replicas[k]
        self._registry().gauge(
            "elastic.generation",
            "current elastic membership generation").set(self._gen)

    def close(self):
        """Stop heartbeats and background threads (idempotent)."""
        self._stop.set()
        self._qev.set()
        if self._mgr_elastic is not None:
            self._mgr_elastic.stop()
        for t in self._threads:
            t.join(timeout=2.0)
        for s in (self._stores or ()):
            try:
                s.close()
            except OSError:
                pass
        self._stores = None

    def _registry(self):
        from ..observability import metrics as om
        return om.registry()

    def _emit(self, kind, **fields):
        try:
            from ..observability import events
            events.emit(kind, node=self.node_id, **fields)
        except Exception:
            pass

    def _sop(self, fn):
        """One supervisor-level store op under the ``store_partition``
        fault site + bounded retry (the TCPStore client has its own
        transport-level retry underneath; this budget is the
        supervisor's give-up point for a real partition)."""
        def attempt():
            faults.maybe_raise("store_partition", self.node_id)
            return fn()

        return retry_call(attempt, max_attempts=self.store_retries,
                          base_delay=0.02, max_delay=0.2,
                          retry_on=(ConnectionError,))

    # --------------------------------------------------------------- fit --
    def fit(self, model, train_data, batch_size=1, num_iters=None,
            callbacks=None, verbose=0, **fit_kw):
        """Supervised ``Model.fit`` over this rank's shard of
        ``train_data`` (deterministic order — the supervisor forces
        ``shuffle=False``; sharding is batch-granular, see
        ``_shard_view``).  Single-epoch stream semantics: the remaining
        data after a recovery is treated as the current epoch.
        Returns True on completion, False when this rank died."""
        fit_kw.pop("epochs", None)
        fit_kw.pop("shuffle", None)
        if not self._members:
            self._join()
        self._verify_schedule(model)
        try:
            while True:
                shard = _shard_view(train_data, batch_size, self.rank,
                                    self.world, self._consumed)
                cb = _supervisor_callback(self, model)
                try:
                    # single-epoch stream semantics: the shard already
                    # excludes consumed batches, so the resumed run
                    # ALWAYS starts at epoch 0 of the remaining data —
                    # feeding a checkpoint's epoch >= 1 through resume
                    # with epochs=1 would make fit's epoch range empty
                    # and "complete" without training a step
                    model.fit(shard, batch_size=batch_size, epochs=1,
                              shuffle=False, verbose=verbose,
                              num_iters=num_iters,
                              callbacks=list(callbacks or []) + [cb],
                              resume=((0, 0, self._gstep)
                                      if self._gstep else False),
                              **fit_kw)
                    return True
                except _RankDead:
                    # the drill's simulated death: stop heartbeating
                    # and vanish without cleanup — peers must detect us
                    self.dead = True
                    self._emit("elastic.rank_dead", rank=self.rank)
                    self.close()
                    return False
                except (CollectiveTimeoutError, MembershipChanged) as e:
                    try:
                        self._recover(model, e)
                    except _RankDead:
                        # partitioned out during recovery: the fleet
                        # moved on without us — same exit as the drill
                        return False
        except BaseException:
            # terminal exit (recovery gave up, user train-step error):
            # stop heartbeating before unwinding — a raised-but-still-
            # beating rank is an undetectable zombie whose peers would
            # burn the full collective deadline with its buddy replica
            # unused, because the detector's premise (death stops
            # heartbeats) is violated
            self.close()
            raise

    def _on_step(self, model, logs):
        """Step-boundary supervision hook (fires from the fit callback
        after each optimizer update)."""
        gs = self._gstep + 1
        if faults.check("rank_dead", str(self.rank)):
            raise _RankDead()
        if faults.check("slow_rank", str(self.rank)):
            # a straggler, not a death: heartbeats keep flowing (their
            # thread is independent) and the stall stays well inside
            # the collective deadline — peers wait, nobody recovers
            time.sleep(self.slow_rank_s)
        if self.sync_each_step and self.world > 1:
            self._sync_state(model, gs)
        self._gstep = gs
        self._consumed += self.world
        if self.snapshot_every and gs % self.snapshot_every == 0:
            self._enqueue_snapshot(model, gs)
        self._poll_membership()

    def _poll_membership(self):
        # timeout=0 -> one nonblocking gen probe (sub-ms on loopback);
        # the step path must not absorb a sleep quantum per step
        gen, members = self._mgr_elastic.wait_generation(self._gen,
                                                         timeout=0.0)
        if gen > self._gen:
            if any(m not in members for m in self._members):
                raise MembershipChanged(gen, sorted(members))
            # flap re-publish or pure ADDITION: adopt the generation
            # but keep training on the current member set — a joiner
            # registers at gstep 0 and cannot partake in the lockstep
            # allreduce mid-stream; integrating late joiners (catch-up
            # from a snapshot) is a scale-up feature this supervisor
            # does not provide, and wedging recovery on one would
            # abort perfectly healthy training
            self._gen = gen

    # -------------------------------------------------- state collective --
    def _verify_schedule(self, model):
        """PDT223 guard at group setup: hash this rank's collective
        schedule for the upcoming session — the store-backed psum-mean
        over the flat parameter vector, i.e. the concatenated param
        shapes/sizes that determine every ``_allreduce_mean`` payload —
        and cross-check the hash against every peer via the store
        (``analysis.verify_schedule``). A rank with skewed config
        (different model shapes, a divergent branch) fails fast and
        coded (``CollectiveScheduleError``, PDT-E023) here instead of
        hanging to the PDT-E021 watchdog timeout mid-step. Peers that
        have not published yet are skipped — late joiners are the
        elastic manager's business, not a divergence."""
        from .. import analysis as _analysis
        if _analysis.mode() == "off":
            return
        try:
            params, shapes, sizes = self._sync_params(model)
            sched = [_analysis.CollectiveOp(
                prim="psum_mean", axes=("store",),
                shape=(int(sum(sizes)),), dtype="float32")]
            h = _analysis.schedule_hash(sched)
        except Exception:
            return
        try:
            self._emit("elastic.schedule_hash", hash=h, gen=self._gen)
            _analysis.verify_schedule(
                self._bstore, f"{_P}/g{self._gen}", self.node_id,
                self._members, h, timeout=0.5)
        except (ConnectionError, OSError):
            pass  # store hiccup: the verifier is best-effort

    def _sync_params(self, model):
        cache = self._sync_cache
        params = [p for p in model.network.parameters()]
        if cache is None or len(cache[0]) != len(params):
            shapes = [tuple(int(s) for s in p.shape) for p in params]
            sizes = [int(np.prod(s)) if s else 1 for s in shapes]
            cache = self._sync_cache = (params, shapes, sizes)
        return cache

    def _sync_state(self, model, gs):
        """The CPU-mesh DP stand-in: average the parameter state over
        the fleet through the store (all ranks iterate members in the
        same order, so the reduction is bitwise-identical everywhere).
        On a real pod the in-graph psum owns gradient sync and this is
        off."""
        import jax.numpy as jnp
        params, shapes, sizes = self._sync_params(model)
        vec = np.concatenate(
            [np.asarray(p.numpy(), np.float32).ravel() for p in params]) \
            if params else np.zeros(0, np.float32)
        mean = self._allreduce_mean(f"s{gs}", vec)
        off = 0
        for p, shp, n in zip(params, shapes, sizes):
            p._write(jnp.asarray(mean[off:off + n].reshape(shp)))
            off += n

    def _allreduce_mean(self, tag, vec):
        """Store-backed psum-mean over the current members, armed on
        the collective watchdog: a peer that never contributes raises
        ``CollectiveTimeoutError`` (PDT-E021) with stacks in a flight
        record within ``collective_timeout_ms`` (+ one poll interval)
        — with metrics off, a supervisor-side hard deadline raises the
        same coded error without the dump."""
        from ..observability import watchdog as _watchdog
        # keyed by (recovery epoch, step tag), NOT the generation: the
        # step sequence is globally unique under lockstep sync, while a
        # transient generation disagreement (one rank adopted a flap
        # re-publish one step before its peer) would partition the key
        # namespace and deadlock ranks that are both alive.  The epoch
        # exists because a recovery ROLLS BACK the step counter: with
        # >1 survivor, re-run steps would otherwise reuse pre-crash
        # tags and a fast reader could consume a peer's STALE pre-crash
        # contribution before the peer re-sets it.  Unlike the
        # generation, the epoch cannot transiently disagree — every
        # survivor increments it at the same recovery barrier, and
        # ranks that missed the recovery are no longer members.
        base = f"{_P}/ar/e{self._epoch_ar}/{tag}"
        self._bstore.set(f"{base}/{self.node_id}",
                         vec.astype(np.float32, copy=False).tobytes())
        ms = self.collective_timeout_ms
        bufs = {}
        with _watchdog.arm_collective(
                "elastic.allreduce", key=str(tag),
                deadline_ms=ms,
                extra={"members": list(self._members)}):
            hard = time.monotonic() + 2.0 * ms / 1e3
            pending = list(self._members)
            while pending:
                node = pending[0]
                try:
                    # short server-side waits keep this loop at Python
                    # bytecode boundaries, where the watchdog's
                    # injected exception can land.  The catch below is
                    # the STORE's "not there yet" answer specifically —
                    # CollectiveTimeoutError is a TimeoutError too, and
                    # swallowing the injection here would un-detect the
                    # dead peer until the hard backstop
                    bufs[node] = self._bstore.get(f"{base}/{node}",
                                                  timeout=0.05)
                    pending.pop(0)
                except StoreTimeoutError:
                    if time.monotonic() > hard:
                        raise CollectiveTimeoutError(
                            f"collective {tag!r} gen {self._gen}: no "
                            f"contribution from {node!r} within "
                            f"{ms:.0f}ms "
                            f"[{CollectiveTimeoutError.error_code}]")
        arrs = [np.frombuffer(bufs[n], np.float32)
                for n in self._members]
        out = arrs[0].astype(np.float32, copy=True)
        for a in arrs[1:]:
            out += a
        out /= np.float32(len(arrs))
        self._gc_ar(base)
        return out

    def _gc_ar(self, base):
        """Bounded collective-key footprint: each rank deletes its own
        contribution for the tag before last (the previous tag may
        still be mid-read by a straggler)."""
        self._last_ar_tags.append(base)
        while len(self._last_ar_tags) > 2:
            base = self._last_ar_tags.pop(0)
            try:
                self._bstore.delete_key(f"{base}/{self.node_id}")
            except (ConnectionError, OSError):
                pass

    # ---------------------------------------------------------- snapshots --
    def _capture(self, model, gs):
        from ..core import state as core_state
        net = model.network
        msd = {k: np.asarray(v.numpy())
               for k, v in net.state_dict().items()}
        opt = getattr(model, "_optimizer", None)
        osd = _to_np(opt.state_dict()) \
            if opt is not None and hasattr(opt, "state_dict") else None
        rng = core_state.default_rng
        rng_arr = np.asarray(rng._key_var._read()) \
            if rng._key_var is not None else None
        meta = {"step": int(gs), "consumed": int(self._consumed),
                "epoch": int(self._epoch), "node": self.node_id,
                "world": self.world}
        payload = pickle.dumps(
            {"model": msd, "opt": osd, "rng": rng_arr, "meta": meta},
            protocol=4)
        return meta, payload

    def _enqueue_snapshot(self, model, gs):
        t0 = time.perf_counter()
        meta, payload = self._capture(model, gs)
        self._hold(self._local, gs, meta, payload)
        self._registry().counter(
            "elastic.snapshots",
            "buddy in-memory snapshots captured").inc()
        with self._qlock:
            # latest-wins: a slow push never queues unbounded work.
            # The recovery EPOCH is captured here, with the payload: a
            # push that drains after a recovery bumped the epoch must
            # land in the OLD epoch's (dead) keyspace, not mislabel
            # pre-crash state as post-recovery
            self._pending = (gs, meta, payload, t0, self._epoch_ar)
        self._qev.set()

    def _hold(self, store_list, step, meta, payload):
        store_list[:] = [e for e in store_list if e[0] != step]
        store_list.append((step, meta, payload))
        store_list.sort(key=lambda e: e[0])
        del store_list[:-self.keep_snapshots]

    def _replicator_loop(self):
        while not self._stop.is_set():
            self._qev.wait(timeout=0.2)
            with self._qlock:
                item, self._pending = self._pending, None
                self._qev.clear()
            if item is None:
                continue
            gs, meta, payload, t0, epoch = item
            try:
                self._push_snapshot(gs, meta, payload, epoch)
                self._registry().histogram(
                    "elastic.snapshot_ms",
                    "snapshot capture -> buddy-replicated wall time"
                ).observe((time.perf_counter() - t0) * 1e3)
            except Exception as e:
                self._registry().counter(
                    "elastic.snapshot_push_failures",
                    "snapshot replications abandoned after retry"
                ).inc()
                self._emit("elastic.snapshot_push_failed", step=gs,
                           error=f"{type(e).__name__}: {e}"[:200])

    def _xfer_base(self, node, epoch):
        # transfer keys are RECOVERY-EPOCH-namespaced: after a rollback
        # the fleet re-runs step numbers it already snapshotted, and a
        # buddy that kept pre-crash keys/holdings at the same bare step
        # would silently serve state from the divergent pre-recovery
        # trajectory on a second death.  The epoch bumps in lockstep at
        # the recovery barrier, so writer and receiver agree on the
        # keyspace whenever training (and thus snapshotting) runs
        return f"{_P}/xfer/e{int(epoch)}/{node}"

    def _push_snapshot(self, gs, meta, payload, epoch):
        base = self._xfer_base(self.node_id, epoch)
        # writer-side transfer-key GC: keep the last keep_snapshots
        # generations in flight; the receiver pulls within a poll tick
        while len(self._pushed) >= self.keep_snapshots:
            self._drop_pushed(self._pushed.pop(0))
        nchunks = self._push_payload(self._xstore, f"{base}/{gs}",
                                     payload, meta,
                                     torn_key=str(self.rank))
        self._pushed.append((epoch, gs, nchunks))
        self._sop(lambda: self._xstore.set(
            f"{base}/latest",
            pickle.dumps([s for e, s, _n in self._pushed
                          if e == epoch])))
        self._sop(lambda: self._xstore.add(f"{base}/seq", 1))

    def _drop_pushed(self, entry):
        epoch, old, nchunks = entry
        base = self._xfer_base(self.node_id, epoch)
        for i in range(nchunks):
            try:
                self._xstore.delete_key(f"{base}/{old}/c{i}")
            except (ConnectionError, OSError):
                pass
        try:
            self._xstore.delete_key(f"{base}/{old}/meta")
        except (ConnectionError, OSError):
            pass

    def _push_payload(self, store, keybase, payload, meta_extra,
                      torn_key=None):
        cb = self.chunk_bytes
        chunks = [payload[i:i + cb] for i in range(0, len(payload), cb)] \
            or [b""]
        torn = torn_key is not None and faults.check("snapshot_torn",
                                                     torn_key)
        for i, c in enumerate(chunks):
            data = c[:max(1, len(c) // 2)] if torn and i == 0 else c
            self._sop(lambda k=f"{keybase}/c{i}", d=data: store.set(k, d))
        meta = dict(meta_extra)
        meta.update({"nchunks": len(chunks),
                     "sizes": [len(c) for c in chunks],
                     "crcs": [zlib.crc32(c) for c in chunks],
                     "bytes": len(payload)})
        self._sop(lambda: store.set(f"{keybase}/meta",
                                    pickle.dumps(meta)))
        return len(chunks)

    def _fetch_payload(self, store, keybase, timeout):
        meta = pickle.loads(store.get(f"{keybase}/meta", timeout))
        parts = []
        for i in range(meta["nchunks"]):
            c = store.get(f"{keybase}/c{i}", timeout)
            if len(c) != meta["sizes"][i] \
                    or zlib.crc32(c) != meta["crcs"][i]:
                raise _TornReplica(f"{keybase} chunk {i}")
            parts.append(c)
        return meta, b"".join(parts)

    def _replica_source(self):
        """The node whose buddy I currently am (whose snapshots I
        receive): ``members[(my_rank - buddy) % world]``."""
        if self.world <= 1 or self.rank < 0:
            return None
        src = self._members[(self.rank - self.buddy) % self.world]
        return None if src == self.node_id else src

    def _receiver_loop(self):
        # seen-seq is keyed by the epoch-namespaced base: each recovery
        # epoch starts a fresh pull stream (seq counts from zero there)
        seen: dict[str, int] = {}
        while not self._stop.is_set():
            src = self._replica_source()
            if src is not None:
                try:
                    self._pull_from(src, seen)
                except Exception:
                    pass  # transient — next poll retries
            self._stop.wait(self.recv_poll_s)

    def _pull_from(self, src, seen):
        epoch = self._epoch_ar
        base = self._xfer_base(src, epoch)
        seq = self._xstore.add(f"{base}/seq", 0)
        if seq <= seen.get(base, 0):
            return
        steps = pickle.loads(self._xstore.get(f"{base}/latest",
                                              timeout=1.0))
        held = {s for s, _m, _p in self._replicas.get(src, [])}
        for s in sorted(steps):
            if s in held:
                continue
            try:
                meta, payload = self._fetch_payload(
                    self._xstore, f"{base}/{s}", timeout=1.0)
            except _TornReplica:
                # half-written replica: discard, keep the previous
                # generation — the snapshot_torn acceptance drill
                self._registry().counter(
                    "elastic.snapshots_torn",
                    "received replicas rejected by validation").inc()
                self._emit("elastic.snapshot_torn", src=src, step=s)
                continue
            with self._qlock:
                if self._epoch_ar != epoch:
                    # a recovery bumped the epoch while this pull was
                    # in flight: the payload belongs to the abandoned
                    # trajectory — holding it would undo the rollback
                    # prune (the prune runs post-bump under this lock)
                    return
                self._hold(self._replicas.setdefault(src, []), s,
                           meta, payload)
        seen[base] = seq

    # ----------------------------------------------------------- recovery --
    def _recover(self, model, cause):
        """Quiesce -> reshard -> restore -> fast-forward (module
        docstring).  Raises the original ``cause`` when membership
        never changes within ``recovery_timeout_s`` (a genuine hang
        with no detected death must stay a coded failure).

        Cascade-safe: a SECOND death mid-recovery (the quiesce barrier
        or the plan exchange waits on a rank that just died) surfaces
        as ``StoreTimeoutError`` from the short-deadline store ops —
        the attempt is abandoned and retried, preferring a newer
        generation (where the new corpse is out of the member list)
        but falling back to the SAME one: two survivors whose staggered
        observation of near-simultaneous deaths made them miss each
        other's barrier window must converge without any further
        membership event.  Re-entry is safe because the recovery
        barriers are idempotent per-node arrival keys (not counters)
        and the dead set derives from ``old_members``, the membership
        at the START of the episode — stable across attempts even when
        an earlier attempt already adopted the new generation."""
        deadline = time.monotonic() + self.recovery_timeout_s
        old_members = list(self._members)
        gen_floor = self._gen
        retry = None            # (gen, members) of the abandoned attempt
        while True:
            if retry is None:
                gen, members = self._wait_membership_change(
                    gen_floor, deadline)
                if members is None:
                    raise cause
            else:
                gen, members = retry
                # brief probe for an even newer generation (a cascade
                # death publishes one); keep the current target when
                # the change is a flap or a pure addition
                g2, m2 = self._mgr_elastic.wait_generation(
                    gen, timeout=0.5)
                if g2 > gen:
                    m2s = sorted(m2)
                    if any(m not in m2s for m in members):
                        gen, members = g2, m2s
            if self.node_id not in members:
                # partitioned out: our heartbeat lapsed and the fleet
                # moved on — this rank must not keep training on stale
                # membership
                self.dead = True
                self.close()
                raise _RankDead()
            try:
                self._recover_at(model, gen, members, old_members,
                                 cause)
                return
            except StoreTimeoutError:
                retry = (gen, members)
                if time.monotonic() > deadline:
                    raise cause

    def _arrive_barrier(self, name, nodes, tmo):
        """Idempotent store barrier: arrival is a per-node key, and the
        wait is for every named node's key.  Unlike a counting barrier,
        re-entry after an abandoned attempt just re-sets the arrival —
        a retry can never double-count and release peers early — which
        is what lets ``_recover`` retry the SAME generation.  A node
        that never arrives surfaces as ``StoreTimeoutError`` from the
        short-deadline get (the cascade signal)."""
        self._bstore.set(f"{name}/{self.node_id}", b"1")
        deadline = time.monotonic() + tmo
        for n in nodes:
            left = max(0.05, deadline - time.monotonic())
            self._bstore.get(f"{name}/{n}", timeout=left)

    def _recover_at(self, model, gen, members, old_members, cause):
        """One recovery attempt against generation ``gen``.  Every
        blocking store op uses a deadline short enough that a cascade
        (second death) bounces us back to the membership poll instead
        of eating the whole recovery budget.  ``members`` may include
        JOINERS (a respawned replacement registering concurrently with
        the death) — recovery runs over the SURVIVORS of
        ``old_members``, the training membership when the episode
        started (stable across retry attempts); joiners cannot reach
        the quiesce barrier (they have no recovery to run) and cannot
        partake in the lockstep stream mid-run (see
        ``_poll_membership``)."""
        t0 = time.perf_counter()
        dead = [n for n in old_members if n not in members]
        survivors = [n for n in old_members if n in members]
        tmo = max(4.0 * self.hb_timeout, 5.0)
        self._gens_touched.add(gen)
        if self._restored_gen == gen:
            # retry of an attempt that already restored (it timed out
            # at the release barrier): do NOT re-run the restore — the
            # holder GCs the restore keys the moment its own release
            # barrier passes, so a re-fetch could find nothing — just
            # re-join the release handshake below
            meta, plan, dead = self._restored_info
        else:
            self._emit("elastic.recovering", gen=gen, dead=dead,
                       survivors=survivors)
            # 1. quiesce: every survivor reaches a step boundary
            self._arrive_barrier(f"{_P}/q/{gen}", survivors, tmo)
            # 2. inventory: what buddy replicas do I hold for the dead?
            inv = {}
            for d in dead:
                i = old_members.index(d)
                holder = old_members[(i + self.buddy)
                                     % len(old_members)]
                if holder == self.node_id:
                    inv[d] = [s for s, _m, _p
                              in self._replicas.get(d, [])]
            self._bstore.set(f"{_P}/inv/{gen}/{self.node_id}",
                             pickle.dumps(inv))
            # 3. leader (first survivor) picks the restore source
            if survivors[0] == self.node_id:
                plan = self._make_plan(gen, old_members, survivors,
                                       dead, tmo)
                self._bstore.set(f"{_P}/plan/{gen}",
                                 pickle.dumps(plan))
            plan = pickle.loads(self._bstore.get(f"{_P}/plan/{gen}",
                                                 timeout=tmo))
            # 4. restore the dead rank's state (buddy replica / disk)
            obj, meta = self._execute_plan(plan, gen, tmo)
            self._apply_payload(model, obj)
            self._gstep = int(meta["step"])
            self._consumed = int(meta["consumed"])
            self._epoch = int(meta.get("epoch", 0))
            self._sync_cache = None
            self._restored_gen = gen
            self._restored_info = (dict(meta), dict(plan), list(dead))
        self._adopt(gen, survivors)
        with self._qlock:
            # a queued pre-crash push dies here (a push already in
            # flight lands in the old epoch's dead keyspace — the
            # epoch rides the queue entry)
            self._pending = None
        # 5. release: the holder may GC its restore keys once everyone
        # is done reading them, and the collective-key epoch bumps in
        # lockstep — rolled-back steps must not reuse pre-crash ar tags
        self._arrive_barrier(f"{_P}/qd/{gen}", survivors, tmo)
        self._epoch_ar += 1
        with self._qlock:
            # AFTER the epoch bump (the receiver re-checks the epoch
            # under this lock before holding a pulled replica, so an
            # in-flight old-epoch pull can't repopulate post-prune):
            # snapshots beyond the restored step came from the
            # abandoned (divergent) trajectory and must never serve a
            # later restore
            cut = int(meta["step"])
            self._local[:] = [e for e in self._local if e[0] <= cut]
            for lst in self._replicas.values():
                lst[:] = [e for e in lst if e[0] <= cut]
        for entry in self._pushed:
            self._drop_pushed(entry)
        self._pushed = []
        if plan.get("holder") == self.node_id:
            base = f"{_P}/restore/{gen}"
            for i in range(plan.get("nchunks", 0)):
                try:
                    self._bstore.delete_key(f"{base}/c{i}")
                except (ConnectionError, OSError):
                    pass
            try:
                self._bstore.delete_key(f"{base}/meta")
            except (ConnectionError, OSError):
                pass
        self._gc_recovery_keys(gen)
        ms = (time.perf_counter() - t0) * 1e3
        reg = self._registry()
        reg.counter("elastic.recoveries",
                    "elastic recoveries completed").inc()
        reg.histogram("elastic.recovery_ms",
                      "membership-change -> training-resumable wall "
                      "time").observe(ms)
        self.last_recovery = {
            "source": plan["source"], "step": int(meta["step"]),
            "consumed": int(meta["consumed"]), "dead": dead,
            "gen": gen, "ms": ms,
            "cause": type(cause).__name__,
        }
        self._emit("elastic.recovered", **{
            k: v for k, v in self.last_recovery.items() if k != "ms"})

    def _gc_recovery_keys(self, done_gen):
        """Deferred coordination-key GC (the ``_gc_ar`` pattern): once
        the recovery at ``done_gen`` completed, no rank can revisit an
        EARLIER generation's episode (retry targets only move forward,
        and completion required every survivor to pass this
        generation's barriers), so each rank deletes its own
        arrival/inventory keys — and the shared plan plus any restore
        payload it pushed — for every older generation it touched.
        The just-completed generation's keys stay until the NEXT
        completed recovery: a slower peer may still be reading them."""
        for g in sorted(self._gens_touched):
            if g >= done_gen:
                continue
            for k in (f"{_P}/q/{g}/{self.node_id}",
                      f"{_P}/qd/{g}/{self.node_id}",
                      f"{_P}/inv/{g}/{self.node_id}",
                      f"{_P}/plan/{g}"):
                try:
                    self._bstore.delete_key(k)
                except (ConnectionError, OSError):
                    pass
            n = self._restore_pushed.pop(g, 0)
            base = f"{_P}/restore/{g}"
            for i in range(n):
                try:
                    self._bstore.delete_key(f"{base}/c{i}")
                except (ConnectionError, OSError):
                    pass
            if n:
                try:
                    self._bstore.delete_key(f"{base}/meta")
                except (ConnectionError, OSError):
                    pass
            self._gens_touched.discard(g)

    def _wait_membership_change(self, gen_floor, deadline):
        g = max(gen_floor, self._gen)
        while time.monotonic() < deadline:
            gen, members = self._mgr_elastic.wait_generation(
                g, timeout=1.0)
            if gen > g:
                if any(m not in members for m in self._members):
                    return gen, sorted(members)
                g = gen  # flap or pure addition: not a death, keep waiting
        return None, None

    def _make_plan(self, gen, old_members, members, dead, tmo):
        """Leader: walk dead ranks ascending; the first whose buddy
        survives AND holds a COMPLETE replica wins.  Only when no buddy
        replica exists anywhere does the plan fall to the newest
        COMPLETE on-disk CheckpointManager version."""
        for d in sorted(dead, key=old_members.index):
            i = old_members.index(d)
            holder = old_members[(i + self.buddy) % len(old_members)]
            if holder not in members:
                continue  # the buddy died with its ward
            raw = self._bstore.get(f"{_P}/inv/{gen}/{holder}",
                                   timeout=tmo)
            steps = pickle.loads(raw).get(d) or []
            if steps:
                return {"source": "buddy", "holder": holder,
                        "dead": d, "step": max(steps)}
        if self.mgr is not None:
            lc = self.mgr.latest_complete()
            if lc is not None:
                return {"source": "disk", "step": int(lc[0])}
        return {"source": "none"}

    def _execute_plan(self, plan, gen, tmo):
        """Returns ``(payload_obj, position_meta)``; zero disk reads on
        the buddy path."""
        if plan["source"] == "buddy":
            base = f"{_P}/restore/{gen}"
            held = [e for e in self._replicas.get(plan.get("dead"), [])
                    if e[0] == plan["step"]] \
                if plan.get("holder") == self.node_id else []
            if held:
                _s, meta, payload = held[0]
                plan["nchunks"] = self._push_payload(
                    self._bstore, base, payload, meta)
                self._restore_pushed[gen] = plan["nchunks"]
                # publish the chunk count so non-holders' plan copy
                # matches ours is unnecessary — only the holder GCs
            else:
                # non-holder, or a holder whose holding was pruned by
                # an earlier attempt of this episode: the pushed copy
                # in the store is the source of truth
                meta, payload = self._fetch_payload(self._bstore, base,
                                                    tmo)
            obj = pickle.loads(payload)
            return obj, obj["meta"]
        if plan["source"] == "disk":
            if self.mgr is None:
                raise CheckpointNotFoundError(
                    "elastic recovery: no buddy replica and no "
                    "CheckpointManager for disk fallback "
                    f"[{CheckpointNotFoundError.error_code}]")
            step, objs, meta = self.mgr.load(step=plan["step"])
            rng_v = objs.get("rng")
            if rng_v is not None and hasattr(rng_v, "numpy"):
                rng_v = rng_v.numpy()  # Tensor-shaped; _resilient_save
                # writes a plain ndarray, which needs no conversion
            obj = {"model": _to_np(objs.get("model", {})),
                   "opt": _to_np(objs["opt"]) if "opt" in objs else None,
                   "rng": (np.asarray(rng_v)
                           if rng_v is not None else None)}
            pos = {"step": int(meta.get("global_step", step)),
                   "consumed": int(meta.get(
                       "consumed",
                       int(meta.get("global_step", step))
                       * max(1, len(self._members)))),
                   "epoch": int(meta.get("epoch", 0))}
            return obj, pos
        raise CheckpointNotFoundError(
            "elastic recovery: no buddy replica survives and no "
            "COMPLETE disk checkpoint exists "
            f"[{CheckpointNotFoundError.error_code}]")

    def _apply_payload(self, model, obj):
        from ..core import state as core_state
        from ..core.tensor import Tensor
        model.network.set_state_dict(
            {k: Tensor(np.asarray(v)) for k, v in obj["model"].items()})
        opt = getattr(model, "_optimizer", None)
        if obj.get("opt") is not None and opt is not None \
                and hasattr(opt, "set_state_dict"):
            opt.set_state_dict(obj["opt"])
        if obj.get("rng") is not None:
            import jax.numpy as jnp
            rng = core_state.default_rng
            if rng._key_var is None:
                rng.seed(0)
            rng._key_var._write(jnp.asarray(obj["rng"]))
        # a captured train step holds its state by IDENTITY — the
        # restore above may have replaced accumulator tensors and
        # dissolved fused-optimizer flat buckets (set_state_dict
        # defuses; buckets rebuild at the next EAGER step, which a
        # cached program never runs).  Replaying a stale program would
        # keep training the orphaned bucket storage while the restored
        # tensors sit frozen: drop the compiled-step caches so the
        # first post-recovery batch re-discovers over restored state.
        if hasattr(model, "_reset_compiled_steps"):
            model._reset_compiled_steps()

"""Versioned checkpoints: ``step_<N>`` dirs, COMPLETE markers, keep-K.

The write path is crash-safe at two levels: every file inside a version
commits through ``atomic_write`` (so no file is ever half-written under
its real name), and the version itself only counts once its
``COMPLETE`` marker — written LAST, after every data file is durably on
disk — validates (file list + sizes). The load path walks versions
newest-first and silently falls back past torn/invalid ones, so a run
killed mid-checkpoint resumes from the previous complete version with
no manual cleanup. Garbage collection keeps the newest ``keep_last_k``
complete versions and sweeps older/incomplete debris.

Capability analog of the reference checkpoint manifests (SURVEY D23)
plus the save-then-commit discipline its elastic manager assumes.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import time
import warnings

from .atomic import atomic_write

__all__ = ["CheckpointManager"]

_MARKER = "COMPLETE"
_VERSION_RE = re.compile(r"^step_(\d+)$")
# atomic.py temp-file shape: a crash mid-commit strands
# ".<name>.tmp.<pid>" next to the destination (by design — that is
# what a power loss leaves); the keep-K GC sweeps them once aged
_TMP_RE = re.compile(r"^\..*\.tmp\.\d+$")


class CheckpointManager:
    """Atomic, versioned checkpoint store under one root directory.

    ``save({'model': sd, 'opt': osd}, step=120, meta={...})`` writes
    ``root/step_120/{model,opt}`` (``framework.save`` format) and then
    the COMPLETE marker; ``load()`` returns the newest version that
    validates. ``objs`` values are anything ``framework.save`` accepts.
    """

    def __init__(self, root, keep_last_k=3, tmp_ttl_s=3600.0):
        self.root = os.fspath(root)
        self.keep_last_k = max(1, int(keep_last_k))
        # age gate for sweeping orphaned atomic_write temps: a LIVE
        # writer's temp is seconds old, a crash's orphan only gets
        # older — the gate is what makes the sweep safe to run while
        # another process is mid-save into the same root
        self.tmp_ttl_s = float(tmp_ttl_s)

    # ------------------------------------------------------------ paths --
    def version_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step)}")

    def _scan(self):
        """[(step, dir, marker|None)] sorted by step ascending — one
        validation pass shared by load/latest_complete/gc (re-stating
        every version's files per caller would multiply metadata I/O on
        the networked filesystems checkpoints actually live on)."""
        out = []
        if not os.path.isdir(self.root):
            return out
        for name in os.listdir(self.root):
            m = _VERSION_RE.match(name)
            if not m:
                continue
            d = os.path.join(self.root, name)
            if os.path.isdir(d):
                out.append((int(m.group(1)), d, self._validate(d)))
        out.sort(key=lambda t: t[0])
        return out

    def versions(self):
        """[(step, dir, complete?)] sorted by step ascending. Complete
        means the marker VALIDATES, not merely exists."""
        return [(s, d, marker is not None) for s, d, marker
                in self._scan()]

    # ------------------------------------------------------------- write --
    def save(self, objs: dict, step: int, meta: dict | None = None):
        """Write one version. Returns its directory. Any crash before
        the final marker commit leaves the version incomplete and
        invisible to ``load``."""
        from .. import framework as fw

        d = self.version_dir(step)
        if os.path.isdir(d):
            # leftover torn attempt at the same step (we resumed and
            # re-reached it): start the version over
            shutil.rmtree(d)
        os.makedirs(d)
        files = {}
        for name, obj in objs.items():
            path = os.path.join(d, name)
            fw.save(obj, path)
            files[name] = os.path.getsize(path)
        marker = {"step": int(step), "files": files, "meta": meta or {},
                  "wall_time": time.time()}
        with atomic_write(os.path.join(d, _MARKER), "w") as f:
            json.dump(marker, f)
        self.gc()
        return d

    # -------------------------------------------------------------- read --
    def _validate(self, d):
        """Marker dict when the version is complete and consistent
        (marker parses, every listed file exists with the recorded
        size), else None."""
        try:
            with open(os.path.join(d, _MARKER)) as f:
                marker = json.load(f)
            files = marker["files"]
        except (OSError, ValueError, KeyError, TypeError):
            return None
        for name, size in files.items():
            p = os.path.join(d, name)
            if not os.path.isfile(p) or os.path.getsize(p) != int(size):
                return None
        return marker

    def latest_complete(self):
        """(step, marker) of the newest valid version, or None."""
        for step, _d, marker in reversed(self._scan()):
            if marker is not None:
                return step, marker
        return None

    def load(self, step=None, return_numpy=False):
        """Load a version: ``(step, objs, meta)``.

        With ``step=None``, walks newest-first and falls back past any
        version that fails validation (warning once per skip) — the
        auto-recovery path after a death mid-checkpoint. With an
        explicit ``step``, a validation failure is an error instead.
        Raises ``CheckpointNotFoundError`` when nothing loadable
        exists.
        """
        from ..core.errors import (CheckpointCorruptError,
                                   CheckpointNotFoundError)
        from .. import framework as fw

        vs = self._scan()
        if step is not None:
            vs = [(s, d, m) for s, d, m in vs if s == int(step)]
            if not vs:
                raise CheckpointNotFoundError(
                    f"no checkpoint version step_{step} under "
                    f"{self.root} [{CheckpointNotFoundError.error_code}]")
        for s, d, marker in reversed(vs):
            if marker is None:
                if step is not None:
                    raise CheckpointCorruptError(
                        f"checkpoint {d} is incomplete or torn (no "
                        f"valid {_MARKER} marker) "
                        f"[{CheckpointCorruptError.error_code}]")
                warnings.warn(
                    f"checkpoint {d} incomplete/torn; falling back to "
                    "the previous complete version", RuntimeWarning)
                continue
            objs = {name: fw.load(os.path.join(d, name),
                                  return_numpy=return_numpy)
                    for name in marker["files"]}
            return s, objs, marker.get("meta", {})
        raise CheckpointNotFoundError(
            f"no complete checkpoint under {self.root} "
            f"[{CheckpointNotFoundError.error_code}]")

    # ---------------------------------------------------------------- gc --
    def gc(self):
        """Keep the newest ``keep_last_k`` complete versions; delete
        older complete ones and any incomplete version at or below the
        newest complete step (torn attempts a resumed run has already
        moved past). An incomplete version NEWER than every complete
        one is left alone — it may be another process mid-write; it
        gets swept once a newer complete version lands.  Orphaned
        ``atomic_write`` temp files (a crash mid-commit — the injected
        ``torn_write`` fault included — strands ``.<name>.tmp.<pid>``
        next to the destination) are swept too, age-gated by
        ``tmp_ttl_s``, so repeated crash/resume cycles don't
        accumulate garbage that the version-level GC can't see."""
        vs = self._scan()
        self._sweep_tmp([self.root] + [d for _s, d, _m in vs])
        complete = [s for s, _d, m in vs if m is not None]
        if not complete:
            return
        keep = set(complete[-self.keep_last_k:])
        newest = complete[-1]
        for s, d, m in vs:
            if (m is not None and s not in keep) or (m is None
                                                     and s <= newest):
                shutil.rmtree(d, ignore_errors=True)

    def _sweep_tmp(self, dirs):
        """Remove atomic_write orphans older than ``tmp_ttl_s`` from
        the given directories (best effort — a temp that vanishes
        mid-sweep was someone else's commit finishing)."""
        cutoff = time.time() - self.tmp_ttl_s
        for d in dirs:
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if not _TMP_RE.match(name):
                    continue
                p = os.path.join(d, name)
                try:
                    if os.path.getmtime(p) <= cutoff:
                        os.remove(p)
                except OSError:
                    pass

"""Common functionals: linear, embedding, dropout, padding, interpolate.

Analog of ``python/paddle/nn/functional/common.py`` and ``input.py``
(reference). Linear keeps paddle's [in, out] weight layout so state dicts
round-trip; XLA maps it onto the MXU either way.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import state
from ...core.dispatch import apply, primitive
from ...core.tensor import Tensor


@primitive
def linear(x, weight, bias=None):
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


@primitive(name="embedding")
def _embedding_impl(weight, x, padding_idx=None, sparse=False):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return _embedding_impl(weight, x, padding_idx=padding_idx)


def one_hot(x, num_classes, name=None):
    return apply("one_hot",
                 lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32),
                 x)


def _new_key_tensor():
    return Tensor(jax.random.key_data(state.default_rng.next_key()))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """Reference ``common.py`` dropout: two modes — upscale_in_train
    (inverted dropout, default) and downscale_in_infer."""
    if isinstance(p, Tensor):
        p = float(p.item())
    if not training:
        if mode == "downscale_in_infer":
            return apply("dropout_infer", lambda v: v * (1.0 - p), x)
        return x
    if p == 0.0:
        return x
    if p == 1.0:
        return apply("dropout", lambda v: jnp.zeros_like(v), x)
    key = _new_key_tensor()
    return apply("dropout", _dropout_impl, x, key, p=p, axis=axis, mode=mode)


def _dropout_impl(x, key, p, axis, mode):
    k = jax.random.wrap_key_data(key.astype(jnp.uint32))
    if axis is None:
        mask_shape = x.shape
    else:
        axes = [axis] if isinstance(axis, int) else list(axis)
        mask_shape = tuple(
            s if i in axes else 1 for i, s in enumerate(x.shape))
    keep = jax.random.bernoulli(k, 1.0 - p, mask_shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))
    return jnp.where(keep, x, jnp.zeros((), x.dtype))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = _new_key_tensor()
    return apply("alpha_dropout", _alpha_dropout_impl, x, key, p=p)


def _alpha_dropout_impl(x, key, p):
    k = jax.random.wrap_key_data(key.astype(jnp.uint32))
    alpha = 1.6732632423543772 * 1.0507009873554805
    alpha_p = -alpha
    keep = jax.random.bernoulli(k, 1.0 - p, x.shape)
    a = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p
    y = jnp.where(keep, x, jnp.full((), alpha_p, x.dtype))
    return a * y + b


@primitive(name="pad")
def _pad_impl(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    nd = x.ndim
    pad = list(pad)
    if len(pad) == 2 * nd:
        # paddle "full" form: [[before,after] per dim] flattened, low-dim first
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial form applies to the spatial dims (reversed, like torch)
        n_spatial = len(pad) // 2
        cfg = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial = list(range(2, nd))
        else:
            spatial = list(range(1, nd - 1))
        spatial = spatial[-n_spatial:]
        for i, d in enumerate(reversed(spatial)):
            cfg[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None,
        pad_from_left_axis=True):
    return _pad_impl(x, pad=tuple(int(p) for p in np.asarray(pad).ravel()),
                     mode=mode, value=value, data_format=data_format)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


@primitive(name="interpolate")
def _interpolate_impl(x, size, mode, align_corners, data_format):
    chan_first = data_format.startswith("NC")
    if chan_first:
        spatial_axes = list(range(2, x.ndim))
    else:
        spatial_axes = list(range(1, x.ndim - 1))
    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic",
              "area": "linear"}[mode]
    new_shape = list(x.shape)
    for ax, s in zip(spatial_axes, size):
        new_shape[ax] = int(s)
    if align_corners and method != "nearest":
        # jax.image.resize has no align_corners; emulate with explicit
        # coordinate map via scale_and_translate.
        scales, translations = [], []
        for ax, s in zip(spatial_axes, size):
            in_s = x.shape[ax]
            if s == 1 or in_s == 1:
                scales.append(1.0)
                translations.append(0.0)
            else:
                sc = (s - 1) / (in_s - 1)
                scales.append(sc)
                translations.append(0.5 * (1 - sc))
        return jax.image.scale_and_translate(
            x, new_shape, spatial_axes,
            jnp.asarray(scales, jnp.float32),
            jnp.asarray(translations, jnp.float32),
            {"linear": "linear", "cubic": "cubic"}[method],
            antialias=False).astype(x.dtype)
    return jax.image.resize(x, new_shape, method=method).astype(x.dtype)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format=None,
                name=None):
    nd = x.ndim - 2
    if data_format is None:
        data_format = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
    chan_first = data_format.startswith("NC")
    spatial = x.shape[2:] if chan_first else x.shape[1:-1]
    if size is None:
        if scale_factor is None:
            raise ValueError("one of size / scale_factor must be set")
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * nd
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    else:
        if isinstance(size, Tensor):
            size = size.tolist()
        if isinstance(size, (int,)):
            size = [size] * nd
        size = [int(s.item()) if isinstance(s, Tensor) else int(s)
                for s in size]
    return _interpolate_impl(x, size=tuple(size), mode=mode,
                             align_corners=bool(align_corners),
                             data_format=data_format)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format=None, name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


@primitive(name="pixel_shuffle")
def _pixel_shuffle_impl(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h * r, w * r, c // (r * r))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return _pixel_shuffle_impl(x, upscale_factor=int(upscale_factor),
                               data_format=data_format)


@primitive(name="pixel_unshuffle")
def _pixel_unshuffle_impl(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = x.transpose(0, 1, 3, 5, 2, 4)
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(n, h // r, w // r, c * r * r)
    return x


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return _pixel_unshuffle_impl(x, downscale_factor=int(downscale_factor),
                                 data_format=data_format)


@primitive(name="unfold")
def _unfold_impl(x, kernel_sizes, strides, paddings, dilations):
    n, c, h, w = x.shape
    kh, kw = kernel_sizes
    sh, sw = strides
    ph0, pw0, ph1, pw1 = paddings
    dh, dw = dilations
    x = jnp.pad(x, [(0, 0), (0, 0), (ph0, ph1), (pw0, pw1)])
    oh = (x.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
    ow = (x.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(n, c * kh * kw, oh * ow)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    p = paddings
    if isinstance(p, int):
        p = [p, p, p, p]
    elif len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    return _unfold_impl(x, kernel_sizes=pair(kernel_sizes),
                        strides=pair(strides), paddings=tuple(p),
                        dilations=pair(dilations))


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return apply("cosine_similarity", _cos_sim_impl, x1, x2, axis=axis,
                 eps=eps)


def _cos_sim_impl(a, b, axis, eps):
    num = jnp.sum(a * b, axis=axis)
    den = jnp.sqrt(jnp.sum(a * a, axis=axis) * jnp.sum(b * b, axis=axis))
    return num / jnp.maximum(den, eps)


@primitive(name="label_smooth")
def _label_smooth_impl(label, epsilon=0.1):
    k = label.shape[-1]
    return (1.0 - epsilon) * label + epsilon / k


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        return apply(
            "label_smooth",
            lambda l, p: (1.0 - epsilon) * l + epsilon * p,
            label, prior_dist)
    return _label_smooth_impl(label, epsilon=epsilon)


@primitive(name="normalize")
def _normalize_impl(x, p=2, axis=1, epsilon=1e-12):
    norm = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, epsilon)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _normalize_impl(x, p=p, axis=axis, epsilon=epsilon)


def bilinear(x1, x2, weight, bias=None, name=None):
    """Reference ``nn.functional.bilinear``: out[n, o] =
    x1[n, i] W[o, i, j] x2[n, j] (+ bias)."""
    from ...core.dispatch import apply

    def impl(a, b, w, *rest):
        out = jnp.einsum("ni,oij,nj->no", a, w, b)
        if rest:
            out = out + rest[0].reshape(1, -1)
        return out

    args = [x1, x2, weight] + ([bias] if bias is not None else [])
    return apply("bilinear", impl, *args)

"""Ring attention — context parallelism for long sequences.

Capability analog of the reference's segment-parallel (sep) long-context
path (SURVEY §5 long-context row; reference hybrid topology's sep axis,
``python/paddle/distributed/fleet/base/topology.py:65`` ["data", "pipe",
"sharding", "sep", "model"], and the RingFlashAttention used by its
downstream trainers). TPU-native mechanism: one ``jax.shard_map`` over the
sequence-parallel mesh axis; K/V blocks rotate around the ring via
``lax.ppermute`` (ICI neighbor exchange) while each device holds its Q
block and maintains flash-style online-softmax accumulators. The whole
ring is a ``lax.scan``, so XLA overlaps the permute of step j+1 with the
matmul of step j, and JAX autodiff transposes the ring for the backward
pass (reverse-direction permutes) — no hand-written backward kernel.

Memory: with ``jax.checkpoint`` on the scan body (default), residuals per
step are O(block) and the [S, S] score matrix never materializes — the
context-parallel analog of flash attention's tiling.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...core.dispatch import apply


from ...core.meshutil import pvary as _pvary_impl


def _pvary(xs, axes):
    return _pvary_impl(xs, axes)


def _ring_attention_local(q, k, v, axis, causal, scale, remat=True,
                          mesh_axes=()):
    """Runs INSIDE shard_map: q/k/v are the local blocks [B, S_loc, H, D]
    (kv heads may be fewer — GQA repeats them)."""
    from ...core.meshutil import axis_size as _axis_size
    n = _axis_size(axis)
    i = lax.axis_index(axis)
    s_loc = q.shape[1]
    hq, hk = q.shape[2], k.shape[2]
    if hk != hq:
        k = jnp.repeat(k, hq // hk, axis=2)
        v = jnp.repeat(v, hq // hk, axis=2)
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B, H, Sq, D]
    perm = [(r, (r + 1) % n) for r in range(n)]

    b, h = qf.shape[0], qf.shape[1]
    o0 = jnp.zeros((b, h, s_loc, q.shape[-1]), jnp.float32)
    m0 = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    # constants enter the scan carry as device-invariant; steps make them
    # varying (axis_index masks) — mark them varying up front for shard_map's
    # manual-axes type system
    o0, m0, l0 = _pvary((o0, m0, l0), tuple(mesh_axes))
    pos_q = i * s_loc + jnp.arange(s_loc)  # global positions (contiguous
    # Shard(1) layout; causal load is imbalanced across ranks — the
    # balanced zigzag layout is a possible refinement)

    def body(carry, j):
        o, m, l, kb, vb = carry
        src = (i - j) % n
        kf = jnp.swapaxes(kb, 1, 2).astype(jnp.float32)
        vf = jnp.swapaxes(vb, 1, 2).astype(jnp.float32)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf, kf,
                            preferred_element_type=jnp.float32) * sc
        if causal:
            pos_k = src * s_loc + jnp.arange(kb.shape[1])
            mask = pos_q[:, None] >= pos_k[None, :]
            logits = jnp.where(mask, logits, -jnp.inf)
        blk_max = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # fully-masked blocks keep new_m = -inf: guard exp(-inf - -inf)
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        p = jnp.exp(logits - safe_m[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vf)
        kb, vb = lax.ppermute((kb, vb), axis, perm)
        return (o, jnp.maximum(m, blk_max), l, kb, vb), None

    if remat:
        body = jax.checkpoint(body)
    (o, m, l, _, _), _ = lax.scan(body, (o0, m0, l0, k, v), jnp.arange(n))
    out = o / jnp.where(l == 0.0, 1.0, l)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_flash_attention(query, key, value, mesh=None, sp_axis="sp",
                         batch_axes=None, head_axis=None, is_causal=False,
                         scale=None, remat=True, name=None):
    """Context-parallel attention over a mesh ring.

    Args mirror ``scaled_dot_product_attention`` (paddle layout
    [batch, seq, num_heads, head_dim]) plus the mesh wiring:

    - ``mesh``: a ``ProcessMesh`` (or ``jax.sharding.Mesh``) containing
      ``sp_axis``.
    - ``sp_axis``: mesh axis the sequence dim is sharded over (the ring).
    - ``batch_axes``: optional mesh axis (or tuple) the batch dim is
      sharded over (dp), so the shard_map composes with data parallelism.
    - ``head_axis``: optional mesh axis the head dim is sharded over (mp),
      composing with tensor parallelism.

    Each device computes its Q block against every K/V block as the ring
    rotates; online softmax keeps the result exact (not approximate).
    """
    jmesh = getattr(mesh, "jmesh", mesh)
    if jmesh is None:
        raise ValueError("ring_flash_attention requires a mesh")
    if sp_axis not in jmesh.axis_names:
        raise ValueError(f"mesh has no axis {sp_axis!r}")

    bspec = batch_axes
    spec = P(bspec, sp_axis, head_axis, None)

    def impl(q, k, v):
        fn = partial(_ring_attention_local, axis=sp_axis, causal=is_causal,
                     scale=scale, remat=remat,
                     mesh_axes=tuple(jmesh.axis_names))
        from ...core.meshutil import shard_map as _shard_map
        sm = _shard_map(fn, mesh=jmesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
        return sm(q, k, v)

    return apply("ring_flash_attention", impl, query, key, value)

"""Loss functionals.

Analog of ``python/paddle/nn/functional/loss.py`` (reference; kernels
``paddle/phi/kernels/funcs/cross_entropy.h`` etc.). Cross-entropy follows the
reference semantics: hard or soft labels, ignore_index, class weights,
label_smoothing, use_softmax toggle.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _reduce(loss, reduction, weight_sum=None):
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    if weight_sum is not None:
        return jnp.sum(loss) / jnp.maximum(weight_sum, 1e-12)
    return jnp.mean(loss)


@jax.custom_vjp
def _nll_fused(logits, safe):
    """Per-row -log softmax(logits)[safe]: logits [N, V], safe [N] int32
    -> [N] f32. Residuals are O(N), not O(N*V)."""
    return _nll_fwd(logits, safe)[0]


def _nll_fwd(logits, safe):
    m = jnp.max(logits, axis=1)
    s = jnp.sum(jnp.exp((logits - m[:, None]).astype(jnp.float32)),
                axis=1)
    lse = m.astype(jnp.float32) + jnp.log(s)
    picked = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
    return lse - picked.astype(jnp.float32), (logits, safe, lse)


def _nll_bwd(res, g):
    logits, safe, lse = res
    # d/dlogits = (softmax - onehot) * g, one fused pass, no residual
    p = jnp.exp(logits.astype(jnp.float32) - lse[:, None])
    onehot = (jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
              == safe[:, None])
    d = (p - onehot) * g[:, None].astype(jnp.float32)
    return d.astype(logits.dtype), None


_nll_fused.defvjp(_nll_fwd, _nll_bwd)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    args = [input, label] + ([weight] if weight is not None else [])

    def impl(logits, lab, *w):
        w = w[0] if w else None
        ax = axis if axis >= 0 else logits.ndim + axis
        n_class = logits.shape[ax]
        hard_label = not (soft_label or (
            lab.ndim == logits.ndim and lab.shape[ax] == n_class
            and jnp.issubdtype(lab.dtype, jnp.floating)))
        if (use_softmax and hard_label and w is None
                and label_smoothing == 0.0 and logits.ndim == 2
                and ax == 1):
            # fast path for the LM-loss shape ([tokens, vocab] hard
            # labels): custom-vjp NLL that saves only the [N] logsumexp
            # and recomputes softmax in the backward — the naive autodiff
            # saves a full [N, V] fp32 exp residual (1.6 GB at vocab 50k;
            # profiled ~11 ms/step of the GPT-124M bench in residual +
            # logp traffic).
            idx = lab
            if idx.ndim == logits.ndim:
                idx = jnp.squeeze(idx, axis=ax)
            idx = idx.astype(jnp.int32)
            valid = idx != ignore_index
            safe = jnp.where(valid, idx, 0)
            loss = jnp.where(valid, _nll_fused(logits, safe), 0.0)
            if reduction == "mean":
                n_valid = jnp.sum(valid.astype(jnp.float32))
                return jnp.sum(loss) / jnp.maximum(n_valid, 1.0)
            return _reduce(loss, reduction)
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=ax)
        else:
            logp = jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-37))
        if soft_label or (lab.ndim == logits.ndim and
                          lab.shape[ax] == n_class and
                          jnp.issubdtype(lab.dtype, jnp.floating)):
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0.0:
                soft = (1 - label_smoothing) * soft + label_smoothing / n_class
            loss = -jnp.sum(soft * logp, axis=ax)
            if w is not None:
                wc = jnp.sum(soft * w.astype(jnp.float32), axis=ax)
                loss = loss * wc
                return _reduce(loss, reduction,
                               jnp.sum(wc) if reduction == "mean" else None)
            return _reduce(loss, reduction)
        idx = lab
        if idx.ndim == logits.ndim:
            idx = jnp.squeeze(idx, axis=ax)
        idx = idx.astype(jnp.int32)
        valid = idx != ignore_index
        safe = jnp.where(valid, idx, 0)
        if label_smoothing > 0.0:
            nll = -jnp.take_along_axis(
                logp, safe[..., None] if ax == logits.ndim - 1
                else jnp.expand_dims(safe, ax), axis=ax).squeeze(ax)
            smooth = -jnp.mean(logp, axis=ax)
            loss = (1 - label_smoothing) * nll + label_smoothing * smooth
        else:
            loss = -jnp.take_along_axis(
                logp, jnp.expand_dims(safe, ax), axis=ax).squeeze(ax)
        loss = jnp.where(valid, loss, 0.0)
        if w is not None:
            wc = jnp.where(valid, jnp.take(w.astype(jnp.float32), safe), 0.0)
            loss = loss * wc
            return _reduce(loss, reduction,
                           jnp.sum(wc) if reduction == "mean" else None)
        if reduction == "mean":
            n_valid = jnp.sum(valid.astype(jnp.float32))
            return jnp.sum(loss) / jnp.maximum(n_valid, 1.0)
        return _reduce(loss, reduction)

    return apply("cross_entropy", impl, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax as _softmax
    from ... import ops
    loss = ops.unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    args = [input, label] + ([weight] if weight is not None else [])

    def impl(logp, lab, *w):
        w = w[0] if w else None
        idx = lab.astype(jnp.int32)
        valid = idx != ignore_index
        safe = jnp.where(valid, idx, 0)
        loss = -jnp.take_along_axis(
            logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = jnp.where(valid, loss, 0.0)
        if w is not None:
            wc = jnp.where(valid, jnp.take(w, safe), 0.0)
            loss = loss * wc
            return _reduce(loss, reduction,
                           jnp.sum(wc) if reduction == "mean" else None)
        if reduction == "mean":
            n_valid = jnp.sum(valid.astype(jnp.float32))
            return jnp.sum(loss) / jnp.maximum(n_valid, 1.0)
        return _reduce(loss, reduction)

    return apply("nll_loss", impl, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply("mse_loss",
                 lambda a, b: _reduce(jnp.square(a - b), reduction),
                 input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply("l1_loss",
                 lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def impl(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle multiplies by delta (huber parametrization)
        return _reduce(loss * delta, reduction)

    return apply("smooth_l1_loss", impl, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    args = [input, label] + ([weight] if weight is not None else [])

    def impl(p, y, *w):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p32) + (1 - y) * jnp.log1p(-p32))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return apply("binary_cross_entropy", impl, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    args = [logit, label]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        args.append(weight)
    if has_pw:
        args.append(pos_weight)

    def impl(z, y, *rest):
        z32 = z.astype(jnp.float32)
        y32 = y.astype(jnp.float32)
        i = 0
        w = rest[i] if has_w else None
        if has_w:
            i += 1
        pw = rest[i] if has_pw else None
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), pos_weight scales +term
        log1pexp = jnp.logaddexp(0.0, -jnp.abs(z32))
        if pw is not None:
            coeff = (pw - 1.0) * y32 + 1.0
            loss = (1 - y32) * z32 + coeff * (
                jnp.logaddexp(0.0, -z32))
        else:
            loss = jnp.maximum(z32, 0) - z32 * y32 + log1pexp
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return apply("bce_with_logits", impl, *args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def impl(logp, y):
        if log_target:
            loss = jnp.exp(y) * (y - logp)
        else:
            y32 = y.astype(jnp.float32)
            loss = jnp.where(y32 > 0, y32 * (jnp.log(jnp.maximum(y32, 1e-37))
                                             - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / loss.shape[0]
        return _reduce(loss, reduction)

    return apply("kl_div", impl, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def impl(a, b, y):
        loss = jnp.maximum(-y * (a - b) + margin, 0.0)
        return _reduce(loss, reduction)

    return apply("margin_ranking_loss", impl, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def impl(x, y):
        loss = jnp.where(y == 1.0, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)

    return apply("hinge_embedding_loss", impl, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def impl(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply("cosine_embedding_loss", impl, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def impl(a, pos, neg):
        d_ap = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + epsilon, p),
                                 axis=-1), 1.0 / p)
        d_an = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + epsilon, p),
                                 axis=-1), 1.0 / p)
        if swap:
            d_pn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + epsilon,
                                               p), axis=-1), 1.0 / p)
            d_an = jnp.minimum(d_an, d_pn)
        loss = jnp.maximum(d_ap - d_an + margin, 0.0)
        return _reduce(loss, reduction)

    return apply("triplet_margin_loss", impl, input, positive, negative)


def square_error_cost(input, label):
    return apply("square_error_cost", lambda a, b: jnp.square(a - b),
                 input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    args = [logit, label] + ([normalizer] if normalizer is not None else [])

    def impl(z, y, *n):
        z32, y32 = z.astype(jnp.float32), y.astype(jnp.float32)
        p = jax.nn.sigmoid(z32)
        ce = jnp.maximum(z32, 0) - z32 * y32 + jnp.logaddexp(0.0, -jnp.abs(z32))
        p_t = p * y32 + (1 - p) * (1 - y32)
        a_t = alpha * y32 + (1 - alpha) * (1 - y32)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    return apply("sigmoid_focal_loss", impl, *args)


def log_loss(input, label, epsilon=1e-4, name=None):
    def impl(p, y):
        p32 = p.astype(jnp.float32)
        return -(y * jnp.log(p32 + epsilon) +
                 (1 - y) * jnp.log(1 - p32 + epsilon))

    return apply("log_loss", impl, input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard dynamic program in log space (lax.scan over
    time). Reference: warpctc binding (``paddle/phi/kernels/gpu/
    warpctc_kernel.cu``); here it's pure XLA so it runs on TPU."""
    args = [log_probs, labels, input_lengths, label_lengths]

    def impl(lp, lab, in_len, lab_len):
        # lp: [T, B, C] logits (paddle convention); normalize to log-probs
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        ext = 2 * S + 1
        NEG = -1e30
        # extended label seq: blank l1 blank l2 ... blank
        ext_lab = jnp.full((B, ext), blank, dtype=jnp.int32)
        ext_lab = ext_lab.at[:, 1::2].set(lab.astype(jnp.int32))
        same_as_prev2 = jnp.concatenate(
            [jnp.zeros((B, 2), bool),
             ext_lab[:, 2:] == ext_lab[:, :-2]], axis=1)
        is_blank = ext_lab == blank

        alpha0 = jnp.full((B, ext), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(lp[0], ext_lab[:, 1:2], axis=1)[:, 0])

        def step(alpha, lp_t):
            shift1 = jnp.concatenate(
                [jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
            shift2 = jnp.concatenate(
                [jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
            allow2 = (~is_blank) & (~same_as_prev2)
            merged = jnp.logaddexp(alpha, shift1)
            merged = jnp.where(allow2, jnp.logaddexp(merged, shift2), merged)
            emit = jnp.take_along_axis(lp_t, ext_lab, axis=1)
            return merged + emit, merged + emit

        _, alphas = jax.lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T,B,ext]
        t_idx = (in_len.astype(jnp.int32) - 1)
        last = jnp.take_along_axis(
            alphas, t_idx[None, :, None].repeat(ext, 2), axis=0)[0]
        end1 = 2 * lab_len.astype(jnp.int32)      # final blank
        end2 = 2 * lab_len.astype(jnp.int32) - 1  # final label
        ll = jnp.logaddexp(
            jnp.take_along_axis(last, end1[:, None], axis=1)[:, 0],
            jnp.take_along_axis(last, jnp.maximum(end2, 0)[:, None],
                                axis=1)[:, 0])
        loss = -ll
        if norm_by_times:
            loss = loss / in_len.astype(jnp.float32)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(
                lab_len.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)

    return apply("ctc_loss", impl, *args)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """Reference ``huber_loss`` op: 0.5 r^2 inside |r| <= delta, linear
    outside (the unscaled Huber — ``smooth_l1_loss`` is paddle's
    delta-scaled variant)."""
    def impl(a, b):
        r = jnp.abs(a - b)
        loss = jnp.where(r <= delta, 0.5 * r * r,
                         delta * (r - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply("huber_loss", impl, input, label)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Reference ``hsigmoid_loss``: hierarchical sigmoid over a binary
    tree; returns the per-sample loss [N, 1] (reference output shape).
    Default tree = complete binary heap (leaf of class c at heap slot
    c + num_classes - 1, internal nodes 0..num_classes-2), computed with
    traceable bit arithmetic so the loss works under jit; custom trees
    come via ``path_table``/``path_code`` [N, L] (padded with -1)."""
    import numpy as np

    from ...core.dispatch import unwrap

    n = int(num_classes)
    depth = max(int(np.ceil(np.log2(max(n, 2)))), 1)
    use_default_tree = path_table is None
    if not use_default_tree:
        path_table = np.asarray(unwrap(path_table), np.int32)
        path_code = np.asarray(unwrap(path_code), np.float32)

    def impl(x, lab, w, *maybe_bias):
        if use_default_tree:
            # walk the heap from each label's leaf up — fixed `depth`
            # unrolled steps, pure jnp (jit-traceable)
            node = lab.reshape(-1).astype(jnp.int32) + n - 1
            steps = []
            for _ in range(depth):
                parent = (node - 1) // 2
                steps.append((parent, (node == 2 * parent + 2), node > 0))
                node = parent
            pt = jnp.stack([s[0] for s in steps[::-1]], axis=1)
            pc = jnp.stack([s[1] for s in steps[::-1]],
                           axis=1).astype(x.dtype)
            vmask = jnp.stack([s[2] for s in steps[::-1]],
                              axis=1).astype(x.dtype)
        else:
            pt = jnp.asarray(path_table)
            pc = jnp.asarray(path_code)
            vmask = (pt >= 0).astype(x.dtype)
        idx = jnp.maximum(pt, 0)
        wn = jnp.take(w, idx, axis=0)             # [N, L, D]
        logits = jnp.einsum("nd,nld->nl", x, wn)
        if maybe_bias:
            logits = logits + jnp.take(maybe_bias[0].reshape(-1), idx)
        # sigmoid CE with target = code (1 right, 0 left)
        ce = jnp.maximum(logits, 0) - logits * pc + jnp.log1p(
            jnp.exp(-jnp.abs(logits)))
        return jnp.sum(ce * vmask, axis=1, keepdims=True)  # [N, 1]

    args = (input, label, weight) + ((bias,) if bias is not None else ())
    return apply("hsigmoid_loss", impl, *args)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """Reference ``warprnnt`` op (``rnnt_loss``): RNN-Transducer negative
    log-likelihood over logits [B, T, U+1, V] and labels [B, U] —
    log-domain forward DP as a scan over time (the TPU-shaped replacement
    for the warp-rnnt CUDA kernel)."""
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: FastEmit regularization is not implemented; "
            "pass fastemit_lambda=0")

    def impl(logits, labels, in_len, lab_len):
        B, T, U1, V = logits.shape
        U = U1 - 1
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lp_blank = lp[..., blank]                      # [B, T, U+1]
        lab = labels.astype(jnp.int32)                 # [B, U]
        # emit log-prob at (t, u): P(label_u | t, u), u < U
        lp_emit = jnp.take_along_axis(
            lp[:, :, :U, :], lab[:, None, :, None], axis=-1)[..., 0]
        NEG = jnp.float32(-1e30)

        def emit_at(t, u_minus_1):
            # lp_emit[:, t, max(u-1, 0)] without dynamic gather per batch
            return jnp.take_along_axis(
                lp_emit[:, t, :],
                jnp.broadcast_to(jnp.maximum(u_minus_1, 0), (B, 1)),
                axis=1)[:, 0]

        def row(from_blank, t):
            """alpha row at time t given the blank-moves column
            from_blank[u]; vertical emit recurrence is sequential in u."""
            def scan_u(carry, u):
                a = jnp.where(u == 0, from_blank[:, 0],
                              jnp.logaddexp(from_blank[:, u],
                                            carry + emit_at(t, u - 1)))
                return a, a

            _, cols = lax.scan(scan_u, jnp.full((B,), NEG),
                               jnp.arange(U1))
            return jnp.swapaxes(cols, 0, 1)

        # t = 0: no blank moves; alpha[0,0] = 0, alpha[0,u] pure emits
        def scan_u0(carry, u):
            a = jnp.where(u == 0, 0.0, carry + emit_at(0, u - 1))
            return a, a

        _, cols0 = lax.scan(scan_u0, jnp.zeros((B,)), jnp.arange(U1))
        alpha0 = jnp.swapaxes(cols0, 0, 1)

        def full_step(alpha, t):
            new = row(alpha + lp_blank[:, t - 1, :], t)
            return new, new

        _, rows = lax.scan(full_step, alpha0, jnp.arange(1, T))
        alphas = jnp.concatenate([alpha0[None], rows], axis=0)  # [T,B,U1]
        t_last = (in_len.astype(jnp.int32) - 1)
        last = jnp.take_along_axis(
            alphas, t_last[None, :, None].repeat(U1, 2), axis=0)[0]
        a_end = jnp.take_along_axis(
            last, lab_len.astype(jnp.int32)[:, None], axis=1)[:, 0]
        blank_end = jnp.take_along_axis(
            jnp.take_along_axis(
                lp_blank, t_last[:, None, None].repeat(U1, 2),
                axis=1)[:, 0, :],
            lab_len.astype(jnp.int32)[:, None], axis=1)[:, 0]
        loss = -(a_end + blank_end)
        return _reduce(loss, reduction)

    return apply("rnnt_loss", impl, input, label, input_lengths,
                 label_lengths)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean",
                         name=None):
    """Reference ``margin_cross_entropy`` (ArcFace/CosFace family):
    target logit cos(theta) -> cos(m1*theta + m2) - m3, all scaled by
    ``scale``, then softmax CE. Single-program form — under TP the vocab
    dim shards via GSPMD instead of the reference's c_softmax collective
    (``group`` accepted for signature parity)."""
    def impl(lg, y):
        yy = y.reshape(-1).astype(jnp.int32)
        cos_t = jnp.take_along_axis(lg, yy[:, None], axis=1)[:, 0]
        # stay strictly inside (-1, 1): arccos' derivative is -inf at
        # the boundary and a perfectly-aligned feature would NaN the step
        cos_t = jnp.clip(cos_t, -1.0 + 1e-6, 1.0 - 1e-6)
        theta = jnp.arccos(cos_t)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adj = lg.at[jnp.arange(lg.shape[0]), yy].set(target) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.take_along_axis(logp, yy[:, None], axis=1)
        sm = jnp.exp(logp)
        if reduction == "mean":
            loss_out = jnp.mean(loss)
        elif reduction == "sum":
            loss_out = jnp.sum(loss)
        else:
            loss_out = loss
        return (loss_out, sm) if return_softmax else loss_out

    return apply("margin_cross_entropy", impl, logits, label)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Reference ``class_center_sample``: keep the batch's positive
    classes plus random negatives up to ``num_samples`` unique centers;
    returns (remapped_label, sampled_class_indices). Host-side sampling
    (data-dependent sizes), seeded by the framework RNG."""
    import numpy as np

    from ...core import state
    from ...core.dispatch import unwrap
    from ...core.tensor import Tensor

    if num_samples > num_classes:
        raise ValueError(f"class_center_sample: num_samples "
                         f"{num_samples} > num_classes {num_classes}")
    y = np.asarray(unwrap(label)).reshape(-1)
    pos = np.unique(y)
    import jax as _jax
    key = np.asarray(_jax.random.key_data(state.default_rng.next_key()))
    rng = np.random.default_rng(key.astype(np.uint32))
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos)
        extra = rng.choice(neg_pool, size=num_samples - len(pos),
                           replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(remap[y].astype(np.int64)),
            Tensor(sampled.astype(np.int64)))

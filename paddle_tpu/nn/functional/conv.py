"""Convolution functionals over ``jax.lax.conv_general_dilated``.

Analog of ``python/paddle/nn/functional/conv.py`` (reference; kernels
``paddle/phi/kernels/gpu/conv_kernel.cu`` via cudnn). TPU-native: one XLA
convolution primitive covers conv1d/2d/3d, grouped, dilated and transposed
convs; XLA lays it out for the MXU (no im2col / algo-search machinery needed).
Weights use paddle's [out_c, in_c/groups, *k] layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _norm_padding(padding, n, strides, dilations, kernel):
    """Returns (list of (lo, hi) per spatial dim) or the string 'SAME'."""
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return [(0, 0)] * n
        if p == "SAME":
            return "SAME"
        raise ValueError(f"bad padding {padding}")
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(np.asarray(padding).ravel())
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _dim_numbers(nd, channel_last):
    if nd == 1:
        return ("NWC", "OIW", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if nd == 2:
        return (("NHWC", "OIHW", "NHWC") if channel_last
                else ("NCHW", "OIHW", "NCHW"))
    return (("NDHWC", "OIDHW", "NDHWC") if channel_last
            else ("NCDHW", "OIDHW", "NCDHW"))


def _conv_impl(x, weight, bias, strides, padding, dilations, groups,
               channel_last, nd):
    dn = _dim_numbers(nd, channel_last)
    y = jax.lax.conv_general_dilated(
        x, weight, window_strides=strides, padding=padding,
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=dn,
        preferred_element_type=x.dtype)
    if bias is not None:
        shape = [1] * y.ndim
        shape[-1 if channel_last else 1] = bias.shape[0]
        y = y + bias.reshape(shape)
    return y


def _conv(name, x, weight, bias, stride, padding, dilation, groups,
          data_format, nd):
    strides = _tuplize(stride, nd)
    dilations = _tuplize(dilation, nd)
    channel_last = data_format.endswith("C")
    kernel = weight.shape[2:]
    pad = _norm_padding(padding, nd, strides, dilations, kernel)
    args = (x, weight) if bias is None else (x, weight, bias)

    def impl(x_, w_, b_=None):
        return _conv_impl(x_, w_, b_, strides, pad, dilations, int(groups),
                          channel_last, nd)

    return apply(name, impl, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv("conv1d", x, weight, bias, stride, padding, dilation,
                 groups, fmt, 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv("conv2d", x, weight, bias, stride, padding, dilation,
                 groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv("conv3d", x, weight, bias, stride, padding, dilation,
                 groups, data_format, 3)


def _conv_transpose(name, x, weight, bias, stride, padding, output_padding,
                    dilation, groups, data_format, nd, output_size=None):
    strides = _tuplize(stride, nd)
    dilations = _tuplize(dilation, nd)
    channel_last = data_format.endswith("C")
    kernel = [int(k) for k in weight.shape[2:]]
    pad = _norm_padding(padding, nd, strides, dilations, kernel)
    if pad == "SAME":
        raise NotImplementedError("SAME padding for conv_transpose")
    opad = _tuplize(output_padding or 0, nd)
    # grad-of-conv formulation: lhs_dilation = stride, padding adjusted
    trans_pad = []
    for i in range(nd):
        k_eff = dilations[i] * (kernel[i] - 1) + 1
        lo = k_eff - 1 - pad[i][0]
        hi = k_eff - 1 - pad[i][1] + opad[i]
        trans_pad.append((lo, hi))

    dn = _dim_numbers(nd, channel_last)
    g = int(groups)

    def impl(x_, w_, b_=None):
        # weight layout [in_c, out_c/groups, *k] for paddle conv_transpose;
        # flip spatial dims and swap io for the dilated-conv formulation.
        w = jnp.flip(w_, axis=tuple(range(2, w_.ndim)))
        if g > 1:
            ic, ocg = w.shape[0], w.shape[1]
            w = w.reshape((g, ic // g) + w.shape[1:])
            w = jnp.swapaxes(w, 1, 2)
            w = w.reshape((g * ocg, ic // g) + w.shape[3:])
        else:
            w = jnp.swapaxes(w, 0, 1)
        y = jax.lax.conv_general_dilated(
            x_, w, window_strides=(1,) * nd, padding=trans_pad,
            lhs_dilation=strides, rhs_dilation=dilations,
            feature_group_count=g, dimension_numbers=dn,
            preferred_element_type=x_.dtype)
        if b_ is not None:
            shape = [1] * y.ndim
            shape[-1 if channel_last else 1] = b_.shape[0]
            y = y + b_.reshape(shape)
        return y

    args = (x, weight) if bias is None else (x, weight, bias)
    out = apply(name, impl, *args)
    if output_size is not None:
        want = ([int(s) for s in output_size]
                if not isinstance(output_size, int)
                else [int(output_size)] * nd)
        got = out.shape[2:] if not channel_last else out.shape[1:-1]
        if list(got) != want:
            raise ValueError(
                f"output_size {want} unreachable, got {list(got)}; adjust "
                "output_padding")
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose("conv1d_transpose", x, weight, bias, stride,
                           padding, output_padding, dilation, groups, fmt, 1,
                           output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose("conv2d_transpose", x, weight, bias, stride,
                           padding, output_padding, dilation, groups,
                           data_format, 2, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose("conv3d_transpose", x, weight, bias, stride,
                           padding, output_padding, dilation, groups,
                           data_format, 3, output_size)

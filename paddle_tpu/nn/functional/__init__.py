"""paddle_tpu.nn.functional — the functional op surface.

Analog of ``python/paddle/nn/functional/`` (reference). All ops are XLA-
lowerable framework primitives; attention routes to Pallas on TPU.
"""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose,
)
from .pooling import (  # noqa: F401
    max_unpool1d, max_unpool2d, max_unpool3d,
    fractional_max_pool2d, fractional_max_pool3d,
    max_pool1d, max_pool2d, max_pool3d, avg_pool1d, avg_pool2d, avg_pool3d,
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
)
from .norm import (  # noqa: F401
    layer_norm, rms_norm, batch_norm, instance_norm, group_norm,
    local_response_norm, spectral_norm, fused_residual_norm,
)
from .loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    smooth_l1_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    kl_div, margin_ranking_loss, hinge_embedding_loss, cosine_embedding_loss,
    triplet_margin_loss, square_error_cost, sigmoid_focal_loss, log_loss,
    ctc_loss, huber_loss, hsigmoid_loss, rnnt_loss,
    margin_cross_entropy, class_center_sample,
)
from .attention import (  # noqa: F401
    scaled_dot_product_attention, flash_attention, flash_attn_qkvpacked,
    flash_attn_unpadded, sdp_kernel,
)
from .ring_attention import ring_flash_attention  # noqa: F401
from .vision_ops import (  # noqa: F401
    grid_sample, affine_grid, fold, channel_shuffle, temporal_shift,
    sequence_mask, logit, pairwise_distance, soft_margin_loss,
    multi_label_soft_margin_loss, gaussian_nll_loss, poisson_nll_loss,
)

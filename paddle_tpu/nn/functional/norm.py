"""Normalization functionals.

Analog of ``python/paddle/nn/functional/norm.py`` (reference; fused kernels
``paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu``,
``rms_norm_kernel``). On TPU these are single XLA fusion clusters; stats are
computed in float32 regardless of input dtype (matching the reference's
welford/float accumulate behavior under AMP).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _pallas_norms():
    """Fused Pallas norm kernels — OPT-IN via
    ``PDTPU_NORM_BACKEND=pallas``. Measured in-context (r5 step
    anatomy, GPT-124M b8 x s1024): the Pallas LN custom call is a
    fusion BARRIER — its input and output must materialize in HBM — and
    costs ~6 ms/step over the jnp formulation, which XLA fuses into the
    neighboring residual-add/cast chains (full step 100.4 ms with
    Pallas LN, 94.4 ms with XLA LN, 87.1 ms with LN deleted). The same
    isolated-vs-in-context trap as the flash-attention block autotune:
    the kernel wins alone and loses inside the step."""
    import os
    if jax.default_backend() != "tpu" \
            or os.environ.get("PDTPU_NORM_BACKEND") != "pallas":
        return None
    try:
        from ...ops.pallas import norms
        return norms
    except ImportError:
        return None


def _moments(v, axes):
    v32 = v.astype(jnp.float32)
    mean = jnp.mean(v32, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(v32), axis=axes, keepdims=True) - \
        jnp.square(mean)
    return mean, var


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(tuple(normalized_shape))

    def impl(v, *wb):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        pn = _pallas_norms()
        if (pn is not None and n_axes == 1 and weight is not None
                and bias is not None):
            return pn.layer_norm(v, wb[0], wb[1], eps=epsilon)
        mean, var = _moments(v, axes)
        out = (v.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        # saveable under "transformer_saveable" remat: keeps the normed
        # activation as a residual instead of re-reducing in backward
        from jax.ad_checkpoint import checkpoint_name
        return checkpoint_name(out, "ln_out")

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("layer_norm", impl, *args)


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1,
             name=None):
    """RMSNorm (reference fused rms_norm kernel,
    ``paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu``)."""

    def impl(v, *wb):
        axis = begin_norm_axis if begin_norm_axis >= 0 else v.ndim + begin_norm_axis
        axes = tuple(range(axis, v.ndim))
        pn = _pallas_norms()
        if (pn is not None and axes == (v.ndim - 1,) and weight is not None
                and bias is None):
            return pn.rms_norm(v, wb[0], eps=epsilon)
        v32 = v.astype(jnp.float32)
        ms = jnp.mean(jnp.square(v32), axis=axes, keepdims=True)
        out = (v32 * jax.lax.rsqrt(ms + epsilon)).astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("rms_norm", impl, *args)


def fused_residual_norm(x, y, weight, bias=None, epsilon=None,
                        norm="layer", name=None):
    """Fused residual-add + norm glue op (ISSUE 19,
    ``ops.pallas.fused_residual_norm``): returns ``(res, normed)`` with
    ``res = x + y`` (the residual-stream value the next adder consumes)
    and ``normed`` its layer/rms norm — ONE dispatch with a fused
    custom-vjp backward, replacing the separate add and norm ops of the
    training glue chain. ``norm`` selects "layer" (weight+bias) or
    "rms" (weight only). Unlike ``layer_norm``/``rms_norm`` this always
    takes the Pallas kernel path (interpret mode off-TPU); callers gate
    on the ``train_glue_fusion`` flag — see its help for why the fused
    path is an A/B knob rather than a default."""
    if norm not in ("layer", "rms"):
        raise ValueError(f"norm must be 'layer' or 'rms', got {norm!r}")
    if norm == "layer" and bias is None:
        raise ValueError("fused_residual_norm(norm='layer') requires "
                         "bias (LayerNorm's affine pair)")
    eps = epsilon if epsilon is not None else \
        (1e-5 if norm == "layer" else 1e-6)

    def impl(xv, yv, *wb):
        from ...ops.pallas import fused_residual_norm as frn
        if norm == "layer":
            return frn.fused_residual_layer_norm(xv, yv, wb[0], wb[1],
                                                 eps=eps)
        return frn.fused_residual_rms_norm(xv, yv, wb[0], eps=eps)

    args = [x, y] + [t for t in (weight, bias) if t is not None]
    return apply("fused_residual_norm", impl, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Reference ``functional/norm.py`` batch_norm. In training mode the
    running stats buffers are updated in place (host-side assign, matching
    the reference's in-kernel update of mean_out/variance_out)."""
    channel_axis = (1 if data_format.startswith("NC") or x.ndim <= 2
                    else x.ndim - 1)
    if x.ndim <= 2:
        channel_axis = x.ndim - 1
    use_stats = (not training) if use_global_stats is None else use_global_stats

    def impl(v, rm, rv, *wb):
        axes = tuple(a for a in range(v.ndim) if a != channel_axis)
        if use_stats:
            mean = rm.astype(jnp.float32)
            var = rv.astype(jnp.float32)
            bshape = [1] * v.ndim
            bshape[channel_axis] = v.shape[channel_axis]
            mean = mean.reshape(bshape)
            var = var.reshape(bshape)
        else:
            mean, var = _moments(v, axes)
        out = (v.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(v.dtype)
        bshape = [1] * v.ndim
        bshape[channel_axis] = v.shape[channel_axis]
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out

    args = [x, running_mean, running_var] + \
        [t for t in (weight, bias) if t is not None]
    out = apply("batch_norm", impl, *args)

    if training and not use_stats:
        # update running stats (unbiased variance, matching reference)
        val = x._read() if isinstance(x, Tensor) else x
        axes = tuple(a for a in range(val.ndim) if a != channel_axis)
        n = float(np.prod([val.shape[a] for a in axes]))
        m32 = jnp.mean(val.astype(jnp.float32), axis=axes)
        v32 = jnp.var(val.astype(jnp.float32), axis=axes)
        if n > 1:
            v32 = v32 * (n / (n - 1))
        rm, rv = running_mean, running_var
        rm_val = rm._read() if isinstance(rm, Tensor) else rm
        rv_val = rv._read() if isinstance(rv, Tensor) else rv
        new_m = momentum * rm_val + (1 - momentum) * m32.astype(rm_val.dtype)
        new_v = momentum * rv_val + (1 - momentum) * v32.astype(rv_val.dtype)
        if isinstance(rm, Tensor):
            rm._write(new_m)
            rv._write(new_v)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1

    def impl(v, *wb):
        axes = tuple(a for a in range(v.ndim)
                     if a != channel_axis and a != 0)
        mean, var = _moments(v, axes)
        out = (v.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)
        out = out.astype(v.dtype)
        bshape = [1] * v.ndim
        bshape[channel_axis] = v.shape[channel_axis]
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("instance_norm", impl, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")

    def impl(v, *wb):
        if channel_last:
            perm = (0, v.ndim - 1) + tuple(range(1, v.ndim - 1))
            v_t = jnp.transpose(v, perm)
        else:
            v_t = v
        n, c = v_t.shape[0], v_t.shape[1]
        rest = v_t.shape[2:]
        g = v_t.reshape((n, num_groups, c // num_groups) + rest)
        axes = tuple(range(2, g.ndim))
        mean, var = _moments(g, axes)
        out = (g.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(v.dtype).reshape(v_t.shape)
        bshape = [1, c] + [1] * (v_t.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        if channel_last:
            inv = (0,) + tuple(range(2, v.ndim)) + (1,)
            out = jnp.transpose(out, inv)
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("group_norm", impl, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def impl(v):
        channel_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        sq = jnp.square(v.astype(jnp.float32))
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        win = [1] * v.ndim
        win[channel_axis] = size
        pads = [(0, 0)] * v.ndim
        pads[channel_axis] = (pad_lo, pad_hi)
        s = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(win),
                                  (1,) * v.ndim, pads)
        div = jnp.power(k + alpha * s, beta)
        return (v.astype(jnp.float32) / div).astype(v.dtype)

    return apply("local_response_norm", impl, x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Reference ``spectral_norm`` op
    (``python/paddle/static/nn/common.py`` spectral_norm;
    ``paddle/phi/kernels/impl/spectral_norm_kernel_impl.h``): normalize a
    weight by its largest singular value, estimated with ``power_iters``
    rounds of power iteration on W reshaped to [shape[dim], -1].

    Deterministic u/v start vectors keep the op functional — the
    reference keeps persistent randomly-initialized U/V buffers; the
    layer wrapper owns those here. The start vector is a fixed-key
    Gaussian draw rather than all-ones: an all-ones start is exactly
    orthogonal to any zero-sum left-singular vector (common in centered
    weights), which would converge power iteration to a smaller singular
    value and under-normalize."""
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply

    def impl(w):
        d = dim if dim >= 0 else w.ndim + dim
        perm = [d] + [i for i in range(w.ndim) if i != d]
        mat = jnp.transpose(w, perm).reshape(w.shape[d], -1)
        h, wdim = mat.shape
        u = jax.random.normal(jax.random.PRNGKey(0), (h,), jnp.float32)
        u = u / (jnp.linalg.norm(u) + eps)
        v = None
        m = mat.astype(jnp.float32)
        for _ in range(max(1, int(power_iters))):
            v = m.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = m @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ (m @ v)
        out = (m / jnp.maximum(sigma, eps)).astype(w.dtype)
        inv = [perm.index(i) for i in range(w.ndim)]
        return jnp.transpose(
            out.reshape([w.shape[p] for p in perm]), inv)

    return apply("spectral_norm", impl, weight)

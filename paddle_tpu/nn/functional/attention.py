"""Attention functionals.

Analog of ``python/paddle/nn/functional/flash_attention.py`` (reference
``flash_attention.py:147,303,442``; CUDA kernels
``paddle/phi/kernels/gpu/flash_attn_kernel.cu:91``). TPU-native: the public
API keeps paddle's [batch, seq, heads, head_dim] signature; the implementation
dispatches to a Pallas flash-attention kernel on TPU (``paddle_tpu.ops.pallas``)
and falls back to an XLA soft(max(QK))V composition elsewhere (CPU tests).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply


def _use_pallas(q):
    if jax.default_backend() != "tpu":
        return False
    try:
        from ...ops.pallas import flash_attention  # noqa: F401
        return True
    except ImportError:
        return False


def _dropout_probs(probs, dropout, key):
    keep = jax.random.bernoulli(key, 1.0 - dropout, probs.shape)
    return jnp.where(keep, probs / (1.0 - dropout),
                     jnp.zeros((), probs.dtype))


def _sdpa_xla(q, k, v, mask=None, dropout=0.0, causal=False, scale=None,
              dropout_key=None):
    # q,k,v: [B, S, H, D] (paddle layout) -> compute in [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    d = qt.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # grouped-query attention: repeat kv heads if fewer than q heads
    hq, hk = qt.shape[1], kt.shape[1]
    if hk != hq:
        rep = hq // hk
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                        preferred_element_type=jnp.float32) * s
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        idx_q = jnp.arange(q_len)[:, None] + (k_len - q_len)
        idx_k = jnp.arange(k_len)[None, :]
        cmask = idx_q >= idx_k
        logits = jnp.where(cmask, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(qt.dtype)
    if dropout > 0.0 and dropout_key is not None:
        probs = _dropout_probs(probs, dropout,
                               jax.random.wrap_key_data(
                                   dropout_key.astype(jnp.uint32)))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None, backend=None):
    """paddle.nn.functional.scaled_dot_product_attention parity
    (layout [batch, seq, num_heads, head_dim]).

    ``backend`` (extension over the reference signature): None = auto
    (Pallas flash attention on TPU when eligible), "xla" forces the
    unfused fallback, "pallas" requires the flash kernel."""
    if backend not in (None, "xla", "pallas"):
        raise ValueError(
            f"backend must be None, 'xla' or 'pallas'; got {backend!r}")
    args = [query, key, value]
    has_mask = attn_mask is not None
    if has_mask:
        args.append(attn_mask)
    drop = float(dropout_p) if training else 0.0
    if drop > 0.0:
        from ...core import state
        from ...core.tensor import Tensor
        args.append(Tensor(jax.random.key_data(
            state.default_rng.next_key())))

    def impl(q, k, v, *rest):
        i = 0
        m = rest[i] if has_mask else None
        if has_mask:
            i += 1
        dk = rest[i] if drop > 0.0 else None
        eligible = m is None and drop == 0.0
        if backend == "pallas" and not eligible:
            raise ValueError("backend='pallas' requires no attn_mask and "
                             "dropout_p == 0")
        use_pl = (backend == "pallas" or
                  (backend is None and _use_pallas(q) and eligible))
        if use_pl:
            from ...ops.pallas import flash_attention as fa
            return fa.flash_attention(q, k, v, causal=is_causal)
        return _sdpa_xla(q, k, v, mask=m, dropout=drop, causal=is_causal,
                         dropout_key=dk)

    return apply("scaled_dot_product_attention", impl, *args)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle flash_attention parity (reference
    ``nn/functional/flash_attention.py:147``): returns (out, softmax_lse)
    shaped like the reference's (out, None) when return_softmax=False."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, training=True, name=None):
    from ... import ops
    q, k, v = ops.unbind(qkv, axis=2)
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, training=True,
                        name=None):
    """Varlen flash attention (reference ``flash_attention.py:303``,
    kernel ``flash_attn_kernel.cu:91`` flash_attn_varlen_fwd): packed
    [total_tokens, heads, dim] with cu_seqlens prefix sums.

    On TPU with identically-packed q/k this runs the Pallas flash kernel
    with per-token segment ids (no [S,S] mask ever materializes); otherwise
    it falls back to the masked XLA path (still static-shaped)."""
    args = [query, key, value, cu_seqlens_q, cu_seqlens_k]

    def impl(q, k, v, cu_q, cu_k):
        total_q = q.shape[0]
        total_k = k.shape[0]
        # segment ids from cu_seqlens: token i belongs to segment
        # sum(cu <= i) - 1
        pos_q = jnp.arange(total_q)
        pos_k = jnp.arange(total_k)
        seg_q = jnp.searchsorted(cu_q, pos_q, side="right") - 1
        seg_k = jnp.searchsorted(cu_k, pos_k, side="right") - 1
        # "same packing" must be decided statically (it picks the traced
        # program): same object always qualifies; equal VALUES qualify only
        # fully eagerly, so a captured program can't diverge between the
        # discovery (concrete) and replay (traced) passes.
        from ...core import tensor as tensor_mod
        same_packing = total_q == total_k and (
            cu_q is cu_k
            or (tensor_mod._tracker is None
                and not isinstance(cu_q, jax.core.Tracer)
                and not isinstance(cu_k, jax.core.Tracer)
                and bool(np.array_equal(np.asarray(cu_q),
                                        np.asarray(cu_k)))))
        if _use_pallas(q) and (same_packing or not causal):
            # per-segment causal == global causal only when q/k share the
            # packing; non-causal needs no position alignment at all
            from ...ops.pallas import flash_attention as fa
            out = fa.flash_attention(
                q[None], k[None], v[None], causal=causal, scale=scale,
                segment_ids=(seg_q[None], seg_k[None]))
            return out[0]
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            off_q = pos_q - jnp.take(cu_q, seg_q)
            off_k = pos_k - jnp.take(cu_k, seg_k)
            mask = mask & (off_q[:, None] >= off_k[None, :])
        out = _sdpa_xla(q[None], k[None], v[None], mask=mask[None, None],
                        scale=scale)
        return out[0]

    out = apply("flash_attn_unpadded", impl, *args)
    return out, None


def sdp_kernel(*a, **k):  # compatibility no-op context
    import contextlib
    return contextlib.nullcontext()

"""Vision/warping/sequence functionals closing the ops.yaml gaps:
grid_sample (reference ``paddle/phi/kernels/gpu/grid_sample_kernel.cu``),
affine_grid, fold (col2im), channel_shuffle, temporal_shift,
sequence_mask, plus small math/loss functionals (logit,
pairwise_distance, soft_margin_loss, multi_label_soft_margin_loss,
gaussian_nll_loss, poisson_nll_loss)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive


@primitive("grid_sample")
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """x: [N, C, H, W]; grid: [N, Hg, Wg, 2] in [-1, 1] (xy order).
    Reference ``nn/functional/vision.py grid_sample``."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"mode must be bilinear|nearest, got {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"bad padding_mode {padding_mode!r}")
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]

    def unnorm(g, size):
        if align_corners:
            return (g + 1) * (size - 1) / 2
        return ((g + 1) * size - 1) / 2

    fx = unnorm(gx, w)
    fy = unnorm(gy, h)

    def reflect(p, size):
        if align_corners:
            span = 2 * (size - 1)
            p = jnp.abs(jnp.mod(p, span))
            return jnp.where(p > size - 1, span - p, p)
        span = 2 * size
        p = jnp.mod(p + 0.5, span)
        p = jnp.abs(p) - 0.5
        p = jnp.where(p > size - 0.5, span - 1 - p - 0.5, p)
        return jnp.clip(p, 0, size - 1)

    if padding_mode == "reflection":
        fx = reflect(fx, w)
        fy = reflect(fy, h)

    def gather2d(ix, iy):
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        batch = jnp.arange(n).reshape(n, 1, 1)
        vals = x[batch, :, iyc, ixc]           # [N, Hg, Wg, C]
        if padding_mode == "zeros":
            inb = ((ix >= 0) & (ix <= w - 1) &
                   (iy >= 0) & (iy <= h - 1))
            vals = vals * inb[..., None].astype(vals.dtype)
        return vals

    if mode == "nearest":
        out = gather2d(jnp.round(fx).astype(jnp.int32),
                       jnp.round(fy).astype(jnp.int32))
    else:
        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = (fx - x0)[..., None]
        wy = (fy - y0)[..., None]
        out = (gather2d(x0, y0) * (1 - wx) * (1 - wy) +
               gather2d(x1, y0) * wx * (1 - wy) +
               gather2d(x0, y1) * (1 - wx) * wy +
               gather2d(x1, y1) * wx * wy)
    return jnp.moveaxis(out, -1, 1)            # [N, C, Hg, Wg]


@primitive("affine_grid")
def affine_grid(theta, out_shape, align_corners=True):
    """theta: [N, 2, 3]; out_shape: [N, C, H, W] -> grid [N, H, W, 2].
    Reference ``nn/functional/vision.py affine_grid``."""
    n, _, h, w = [int(s) for s in out_shape]

    def linspace(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = linspace(h)
    xs = linspace(w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
    return jnp.einsum("hwk,nik->nhwi", base, theta)


@primitive("fold")
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im — inverse of unfold (reference ``nn/functional/common.py``
    fold). x: [N, C*kh*kw, L] -> [N, C, H, W]."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings)
    dh, dw = pair(dilations)
    n, ckk, llen = x.shape
    c = ckk // (kh * kw)
    nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    assert nh * nw == llen, f"fold: L={llen} != {nh}*{nw}"
    cols = x.reshape(n, c, kh, kw, nh, nw)
    out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            hi = i * dh
            wj = j * dw
            out = out.at[:, :, hi:hi + nh * sh:sh,
                         wj:wj + nw * sw:sw].add(cols[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


@primitive("channel_shuffle")
def channel_shuffle(x, groups, data_format="NCHW"):
    """Reference ``nn/functional/vision.py channel_shuffle``."""
    if data_format == "NCHW":
        n, c, h, w = x.shape
        return x.reshape(n, groups, c // groups, h, w) \
                .swapaxes(1, 2).reshape(n, c, h, w)
    n, h, w, c = x.shape
    return x.reshape(n, h, w, groups, c // groups) \
            .swapaxes(3, 4).reshape(n, h, w, c)


@primitive("temporal_shift")
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """Reference ``nn/functional/extension.py temporal_shift``."""
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    nt, c, h, w = x.shape
    n = nt // seg_num
    v = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    back = jnp.concatenate([v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])],
                           axis=1)
    fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, c1:c2]),
                           v[:, :-1, c1:c2]], axis=1)
    out = jnp.concatenate([back, fwd, v[:, :, c2:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


@primitive("sequence_mask")
def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """Reference ``nn/functional/extension.py sequence_mask``."""
    from ...core.dtype import convert_dtype
    ml = int(maxlen) if maxlen is not None else None
    if ml is None:
        raise ValueError(
            "sequence_mask on TPU requires an explicit maxlen (static "
            "shapes); pass maxlen=int(lengths.max())")
    pos = jnp.arange(ml)
    mask = pos[None, :] < lengths[..., None]
    return mask.astype(convert_dtype(dtype) or jnp.int64)


@primitive("logit")
def logit(x, eps=None):
    """Reference ``tensor/ops.py logit``."""
    if eps is not None:
        x = jnp.clip(x, eps, 1 - eps)
    return jnp.log(x) - jnp.log1p(-x)


@primitive("pairwise_distance")
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    """Reference ``nn/functional/distance.py``."""
    d = x - y + epsilon
    out = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    if keepdim:
        out = out[..., None]
    return out


@primitive("soft_margin_loss")
def soft_margin_loss(input, label, reduction="mean"):
    """Reference ``nn/functional/loss.py soft_margin_loss``."""
    loss = jnp.log1p(jnp.exp(-label * input))
    return _reduce(loss, reduction)


@primitive("multi_label_soft_margin_loss")
def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean"):
    loss = -(label * jax.nn.log_sigmoid(input) +
             (1 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    loss = loss.mean(axis=-1)
    return _reduce(loss, reduction)


@primitive("gaussian_nll_loss")
def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    import math
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + (label - input) ** 2 / var)
    if full:
        loss = loss + 0.5 * math.log(2 * math.pi)
    return _reduce(loss, reduction)


@primitive("poisson_nll_loss")
def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = (label * jnp.log(label) - label +
                    0.5 * jnp.log(2 * jnp.pi * label))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def _reduce(loss, reduction):
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    if reduction == "none":
        return loss
    raise ValueError(f"bad reduction {reduction!r}")

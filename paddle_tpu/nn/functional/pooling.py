"""Pooling functionals over ``jax.lax.reduce_window``.

Analog of ``python/paddle/nn/functional/pooling.py`` (reference; kernels
``paddle/phi/kernels/funcs/pooling.h``). One XLA reduce_window primitive
covers max/avg 1d/2d/3d; adaptive pools compute per-output windows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply
from .conv import _tuplize, _norm_padding


def _spatial_axes(nd, channel_last, ndim):
    if channel_last:
        return list(range(1, 1 + nd))
    return list(range(ndim - nd, ndim))


def _window(nd, ndim, channel_last, sizes):
    w = [1] * ndim
    for ax, s in zip(_spatial_axes(nd, channel_last, ndim), sizes):
        w[ax] = s
    return tuple(w)


def _pool(name, x, nd, kernel_size, stride, padding, ceil_mode, data_format,
          kind, exclusive=True, divisor_override=None):
    ks = _tuplize(kernel_size, nd)
    st = _tuplize(stride if stride is not None else kernel_size, nd)
    channel_last = data_format.endswith("C")
    pad = _norm_padding(padding, nd, st, (1,) * nd, ks)
    if pad == "SAME":
        pads = "SAME"
    else:
        if ceil_mode:
            # extend the high side so that ceil-division windows fit
            pads = []
            spatial = (x.shape[1:1 + nd] if channel_last
                       else x.shape[x.ndim - nd:])
            for i in range(nd):
                size = spatial[i] + pad[i][0] + pad[i][1]
                out_ceil = -(-(size - ks[i]) // st[i]) + 1
                needed = (out_ceil - 1) * st[i] + ks[i] - size
                pads.append((pad[i][0], pad[i][1] + max(0, needed)))
        else:
            pads = list(pad)

    def impl(v):
        ndim = v.ndim
        win = _window(nd, ndim, channel_last, ks)
        strd = _window(nd, ndim, channel_last, st)
        if pads == "SAME":
            padcfg = "SAME"
        else:
            padcfg = [(0, 0)] * ndim
            for ax, p in zip(_spatial_axes(nd, channel_last, ndim), pads):
                padcfg[ax] = p
        # init must be a CONCRETE numpy scalar: lax.reduce_window only
        # routes to its differentiable max/add monoid primitives when it
        # can recognize (computation, init) — a device-array init forces
        # the generic primitive, whose vjp fails under an outer jit trace
        if kind == "max":
            init = (v.dtype.type(-np.inf)
                    if jnp.issubdtype(v.dtype, jnp.floating)
                    else v.dtype.type(jnp.iinfo(v.dtype).min))
            return jax.lax.reduce_window(
                v, init, jax.lax.max, win, strd, padcfg)
        s = jax.lax.reduce_window(
            v, v.dtype.type(0), jax.lax.add, win, strd, padcfg)
        if divisor_override:
            return s / divisor_override
        if exclusive and padcfg != "SAME":
            ones = jnp.ones(v.shape, v.dtype)
            cnt = jax.lax.reduce_window(
                ones, v.dtype.type(0), jax.lax.add, win, strd, padcfg)
            return s / cnt
        return s / float(np.prod(ks))

    return apply(name, impl, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    out = _pool("max_pool1d", x, 1, kernel_size, stride, padding, ceil_mode,
                fmt, "max")
    if return_mask:
        return out, _pool_mask(x, out, 1, kernel_size, stride, padding, fmt,
                               ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool("max_pool2d", x, 2, kernel_size, stride, padding, ceil_mode,
                data_format, "max")
    if return_mask:
        return out, _pool_mask(x, out, 2, kernel_size, stride, padding,
                               data_format, ceil_mode)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool("max_pool3d", x, 3, kernel_size, stride, padding, ceil_mode,
                data_format, "max")
    if return_mask:
        return out, _pool_mask(x, out, 3, kernel_size, stride, padding,
                               data_format, ceil_mode)
    return out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _pool("avg_pool1d", x, 1, kernel_size, stride, padding, ceil_mode,
                 fmt, "avg", exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool("avg_pool2d", x, 2, kernel_size, stride, padding, ceil_mode,
                 data_format, "avg", exclusive=exclusive,
                 divisor_override=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool("avg_pool3d", x, 3, kernel_size, stride, padding, ceil_mode,
                 data_format, "avg", exclusive=exclusive,
                 divisor_override=divisor_override)


def _pool_mask(x, out, nd, kernel_size, stride, padding, data_format,
               ceil_mode=False):
    """Flat-spatial argmax index per window for ``return_mask=True``
    (reference ``max_pool2d_with_index``, kernels
    ``paddle/phi/kernels/funcs/pooling.h``). TPU-native: one static slice per
    kernel offset (K slices, K = prod(kernel)) + argmax over the stacked
    candidates — static shapes, no gather loops."""
    ks = _tuplize(kernel_size, nd)
    st = _tuplize(stride if stride is not None else kernel_size, nd)
    channel_last = data_format.endswith("C")
    pad = _norm_padding(padding, nd, st, (1,) * nd, ks)
    if pad == "SAME":
        raise NotImplementedError("return_mask with SAME padding")

    def impl(v):
        ndim = v.ndim
        axes = _spatial_axes(nd, channel_last, ndim)
        spatial = [v.shape[a] for a in axes]
        outsp = []
        for i in range(nd):
            size = spatial[i] + pad[i][0] + pad[i][1]
            n = (size - ks[i]) // st[i] + 1
            if ceil_mode:
                n = -(-(size - ks[i]) // st[i]) + 1
            outsp.append(n)
        neg = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) \
            else jnp.iinfo(v.dtype).min
        padcfg = [(0, 0)] * ndim
        hi_ext = [(outsp[i] - 1) * st[i] + ks[i] - spatial[i] - pad[i][0]
                  for i in range(nd)]
        for i, a in enumerate(axes):
            padcfg[a] = (pad[i][0], max(0, hi_ext[i]))
        vp = jnp.pad(v, padcfg, mode="constant", constant_values=neg)

        import itertools
        cands = []
        for offs in itertools.product(*[range(k) for k in ks]):
            sl = [slice(None)] * ndim
            for i, a in enumerate(axes):
                sl[a] = slice(offs[i], offs[i] + (outsp[i] - 1) * st[i] + 1,
                              st[i])
            cands.append(vp[tuple(sl)])
        stacked = jnp.stack(cands, axis=0)      # [K, ...out...]
        k_idx = jnp.argmax(stacked, axis=0)     # first max, paddle semantics

        # decompose candidate id into per-axis kernel offsets, then map to
        # flat index over the ORIGINAL (unpadded) spatial dims
        flat = jnp.zeros_like(k_idx)
        rem = k_idx
        for i in range(nd):
            kprod = int(np.prod(ks[i + 1:])) if i + 1 < nd else 1
            off_i = rem // kprod
            rem = rem % kprod
            shape = [1] * k_idx.ndim
            shape[axes[i]] = outsp[i]
            base = (jnp.arange(outsp[i]) * st[i] - pad[i][0]).reshape(shape)
            coord = base + off_i
            sprod = int(np.prod(spatial[i + 1:])) if i + 1 < nd else 1
            flat = flat + coord * sprod
        return flat.astype(jnp.int32)

    return apply("max_pool_mask", impl, x)


def _adaptive_windows(in_size, out_size):
    starts = (np.arange(out_size) * in_size) // out_size
    ends = -(-((np.arange(out_size) + 1) * in_size) // out_size)
    return starts, ends


def _adaptive_pool(name, x, nd, output_size, data_format, kind):
    channel_last = data_format.endswith("C")
    out_sizes = _tuplize(output_size, nd)
    in_ndim = x.ndim

    def impl(v):
        axes = _spatial_axes(nd, channel_last, in_ndim)
        out_sz = [v.shape[a] if o is None else int(o)
                  for a, o in zip(axes, out_sizes)]
        # uniform-window fast path: reduces to plain pooling
        if all(v.shape[a] % o == 0 for a, o in zip(axes, out_sz)):
            ks = [v.shape[a] // o for a, o in zip(axes, out_sz)]
            win = _window(nd, in_ndim, channel_last, ks)
            # numpy-scalar init: keeps lax.reduce_window on its
            # DIFFERENTIABLE max/add monoid primitives (an array init
            # forces the generic primitive, whose vjp fails under trace)
            if kind == "max":
                init = (v.dtype.type(-np.inf)
                        if jnp.issubdtype(v.dtype, jnp.floating)
                        else v.dtype.type(jnp.iinfo(v.dtype).min))
                return jax.lax.reduce_window(
                    v, init, jax.lax.max, win, win,
                    [(0, 0)] * in_ndim)
            s = jax.lax.reduce_window(
                v, v.dtype.type(0), jax.lax.add, win, win,
                [(0, 0)] * in_ndim)
            return s / float(np.prod(ks))
        # general path: gather per-output windows axis by axis
        out = v
        for a, o in zip(axes, out_sz):
            starts, ends = _adaptive_windows(out.shape[a], o)
            pieces = []
            for s0, e0 in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[a] = slice(int(s0), int(e0))
                seg = out[tuple(sl)]
                red = (jnp.max if kind == "max" else jnp.mean)(
                    seg, axis=a, keepdims=True)
                pieces.append(red)
            out = jnp.concatenate(pieces, axis=a)
        return out

    return apply(name, impl, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool("adaptive_avg_pool1d", x, 1, output_size, "NCW",
                          "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool("adaptive_avg_pool2d", x, 2, output_size,
                          data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool("adaptive_avg_pool3d", x, 3, output_size,
                          data_format, "avg")


def _adaptive_max_with_mask(name, x, nd, output_size, data_format):
    """Adaptive max pool WITH flat-spatial argmax indices (reference
    ``max_pool2d_with_index`` adaptive=true) via the shared region
    reducer."""
    from ...core.dispatch import apply

    if not data_format.startswith("NC"):
        raise ValueError(f"{name}: return_mask needs channel-first")
    out_sizes = _tuplize(output_size, nd)

    def impl(v):
        out_sz = tuple(v.shape[2 + i] if o is None else int(o)
                       for i, o in enumerate(out_sizes))

        def bounds(_i, in_size, out_size):
            starts, ends = _adaptive_windows(in_size, out_size)
            return starts, ends - starts

        out, idx = _region_pool_nd(v, out_sz, bounds)
        return out.astype(v.dtype), idx

    return apply(name, impl, x)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask("adaptive_max_pool1d", x, 1,
                                       output_size, "NCW")
    return _adaptive_pool("adaptive_max_pool1d", x, 1, output_size, "NCW",
                          "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask("adaptive_max_pool2d", x, 2,
                                       output_size, "NCHW")
    return _adaptive_pool("adaptive_max_pool2d", x, 2, output_size, "NCHW",
                          "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask("adaptive_max_pool3d", x, 3,
                                       output_size, "NCDHW")
    return _adaptive_pool("adaptive_max_pool3d", x, 3, output_size, "NCDHW",
                          "max")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Reference ``unpool`` op: scatter pooled values back to the flat
    per-plane positions recorded by ``max_pool2d(..., return_mask=True)``."""
    import jax.numpy as jnp

    from ...core.dispatch import apply

    if data_format != "NCHW":
        raise ValueError("max_unpool2d supports NCHW")
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride, stride) if isinstance(stride, int) else tuple(stride))
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)

    def impl(v, idx):
        n, c, h, w = v.shape
        if output_size is not None:
            oh, ow = output_size[-2], output_size[-1]
        else:
            oh = (h - 1) * st[0] - 2 * pd[0] + ks[0]
            ow = (w - 1) * st[1] - 2 * pd[1] + ks[1]
        flat = jnp.zeros((n, c, oh * ow), v.dtype)
        upd = jnp.reshape(v, (n, c, -1))
        ii = idx.reshape(n, c, -1).astype(jnp.int32)
        # scatter values to their recorded positions
        bn = jnp.arange(n)[:, None, None]
        cn = jnp.arange(c)[None, :, None]
        flat = flat.at[bn, cn, ii].set(upd)
        return flat.reshape(n, c, oh, ow)

    return apply("max_unpool2d", impl, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Reference ``unpool`` 1d variant (scatter by recorded indices)."""
    import jax.numpy as jnp

    from ...core.dispatch import apply

    if data_format != "NCL":
        raise ValueError("max_unpool1d supports NCL")
    ks = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    st = ks if stride is None else (
        stride if isinstance(stride, int) else stride[0])
    pd = padding if isinstance(padding, int) else padding[0]

    def impl(v, idx):
        n, c, l = v.shape
        ol = (output_size[-1] if output_size is not None
              else (l - 1) * st - 2 * pd + ks)
        flat = jnp.zeros((n, c, ol), v.dtype)
        bn = jnp.arange(n)[:, None, None]
        cn = jnp.arange(c)[None, :, None]
        return flat.at[bn, cn, idx.astype(jnp.int32)].set(v)

    return apply("max_unpool1d", impl, x, indices)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """Reference ``unpool3d`` op: scatter pooled values back to the flat
    per-volume positions from ``max_pool3d(..., return_mask=True)``."""
    import jax.numpy as jnp

    from ...core.dispatch import apply

    if data_format != "NCDHW":
        raise ValueError("max_unpool3d supports NCDHW")

    def _tup3(v):
        return (v,) * 3 if isinstance(v, int) else tuple(v)

    ks, pd = _tup3(kernel_size), _tup3(padding)
    st = ks if stride is None else _tup3(stride)

    def impl(v, idx):
        n, c, d, h, w = v.shape
        if output_size is not None:
            od, oh, ow = output_size[-3], output_size[-2], output_size[-1]
        else:
            od = (d - 1) * st[0] - 2 * pd[0] + ks[0]
            oh = (h - 1) * st[1] - 2 * pd[1] + ks[1]
            ow = (w - 1) * st[2] - 2 * pd[2] + ks[2]
        flat = jnp.zeros((n, c, od * oh * ow), v.dtype)
        upd = jnp.reshape(v, (n, c, -1))
        ii = idx.reshape(n, c, -1).astype(jnp.int32)
        bn = jnp.arange(n)[:, None, None]
        cn = jnp.arange(c)[None, :, None]
        flat = flat.at[bn, cn, ii].set(upd)
        return flat.reshape(n, c, od, oh, ow)

    return apply("max_unpool3d", impl, x, indices)


def _fractional_starts(in_size, out_size, u):
    """Pseudo-random pooling boundaries (Graham, Fractional Max-Pooling;
    reference ``fractional_max_pool2d`` kernel): region i spans
    [a_i, a_{i+1}) with a_i = ceil(alpha * (i + u)) - 1, a_0 = 0."""
    import numpy as np
    alpha = in_size / out_size
    idx = np.arange(1, out_size, dtype=np.float64)
    starts = np.ceil(alpha * (idx + u)).astype(np.int64) - 1
    starts = np.concatenate([[0], starts])
    ends = np.concatenate([starts[1:], [in_size]])
    return starts, np.maximum(ends - starts, 1)


_frac_generator = None


def _frac_rng():
    global _frac_generator
    if _frac_generator is None:
        import numpy as np

        from ... import core
        _frac_generator = np.random.default_rng(
            core.state.default_rng._seed)
    return _frac_generator


def _region_pool_nd(v, out_sz, bounds):
    """Gather each axis's regions (``bounds(in_size, out_size) ->
    (starts, lens)``) and max-reduce: returns (max, flat argmax index
    over the ORIGINAL spatial dims). Shared by fractional and adaptive
    max pooling (both are variable-window region reductions)."""
    import jax.numpy as jnp
    import numpy as np

    nd = len(out_sz)
    spatial = v.shape[2:]
    starts_all, lens_max = [], []
    cur = v
    for i in range(nd):
        axis = 2 + 2 * i  # earlier axes each expanded into [out, L]
        in_size = cur.shape[axis]
        starts, ln = bounds(i, in_size, out_sz[i])
        L = int(ln.max())
        gm = np.minimum(starts[:, None] + np.arange(L)[None, :],
                        in_size - 1)
        cur = jnp.take(cur, jnp.asarray(gm.reshape(-1)), axis=axis)
        shp = list(cur.shape)
        shp[axis:axis + 1] = [out_sz[i], L]
        cur = cur.reshape(shp)
        vmask = np.arange(L)[None, :] < ln[:, None]
        ms = [1] * len(shp)
        ms[axis], ms[axis + 1] = out_sz[i], L
        cur = jnp.where(jnp.asarray(vmask).reshape(ms), cur, -jnp.inf)
        starts_all.append(starts)
        lens_max.append(L)
    # [N, C, o1, L1, o2, L2, ...] -> L dims last, flattened
    perm = ([0, 1] + [2 + 2 * i for i in range(nd)]
            + [3 + 2 * i for i in range(nd)])
    cur = jnp.transpose(cur, perm)
    flat = cur.reshape(cur.shape[:2 + nd] + (-1,))
    out = jnp.max(flat, axis=-1)
    arg = jnp.argmax(flat, axis=-1)
    offs, rem = [], arg
    for L in reversed(lens_max):
        offs.append(rem % L)
        rem = rem // L
    offs = offs[::-1]
    flat_idx = jnp.zeros(out.shape, jnp.int32)
    for i in range(nd):
        shape = [1] * (2 + nd)
        shape[2 + i] = out_sz[i]
        pos = (jnp.asarray(starts_all[i], jnp.int32).reshape(shape)
               + offs[i].astype(jnp.int32))
        flat_idx = flat_idx * spatial[i] + pos
    return out, flat_idx


def _fractional_pool(name, x, nd, output_size, kernel_size, random_u,
                     return_mask):
    from ... import core
    from ...core.dispatch import apply

    if random_u is None:
        # fresh u per call (the reference redraws per invocation); stream
        # seeded from paddle.seed for reproducibility. Under jit capture
        # the draw happens at trace time and is baked into the program —
        # pass random_u explicitly for traced-fresh randomness.
        random_u = float(_frac_rng().uniform(0.05, 0.95))
    u = float(random_u)
    if not 0.0 < u < 1.0:
        raise ValueError(f"random_u must be in (0, 1), got {u}")
    out_sz = ((output_size,) * nd if isinstance(output_size, int)
              else tuple(output_size))
    caps = None
    if kernel_size is not None:
        caps = ((kernel_size,) * nd if isinstance(kernel_size, int)
                else tuple(kernel_size))

    def bounds(i, in_size, out_size):
        import numpy as np
        starts, ln = _fractional_starts(in_size, out_size, u)
        if caps and caps[i]:
            ln = np.minimum(ln, caps[i])
        return starts, ln

    def impl(v):
        out, _ = _region_pool_nd(v, out_sz, bounds)
        return out.astype(v.dtype)

    def impl_mask(v):
        out, idx = _region_pool_nd(v, out_sz, bounds)
        return out.astype(v.dtype), idx

    if return_mask:
        return apply(name, impl_mask, x)
    return apply(name, impl, x)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Reference ``fractional_max_pool2d`` (ops.yaml): pseudo-random
    fractional pooling regions; ``random_u`` pins the sequence."""
    return _fractional_pool("fractional_max_pool2d", x, 2, output_size,
                            kernel_size, random_u, return_mask)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_pool("fractional_max_pool3d", x, 3, output_size,
                            kernel_size, random_u, return_mask)

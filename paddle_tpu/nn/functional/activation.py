"""Activation functionals.

Analog of ``python/paddle/nn/functional/activation.py`` (reference). Each op
is a framework primitive: XLA fuses these into surrounding matmuls, which is
the TPU replacement for the reference's fused CUDA activation kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive, apply, unwrap
from ...core.tensor import Tensor


@primitive
def relu(x):
    return jnp.maximum(x, 0)


@primitive
def relu6(x):
    return jnp.clip(x, 0, 6)


@primitive(name="gelu")
def _gelu_impl(x, approximate=False):
    # checkpoint_name: under recompute policies that list "act_out"
    # (fleet/recompute.py "transformer_saveable") the activation is
    # saved across backward instead of re-running the transcendental
    from jax.ad_checkpoint import checkpoint_name
    out = jax.nn.gelu(x, approximate=bool(approximate))
    return checkpoint_name(out, "act_out")


def gelu(x, approximate=False, name=None):
    return _gelu_impl(x, approximate=approximate)


@primitive
def sigmoid(x):
    return jax.nn.sigmoid(x)


@primitive
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@primitive
def silu(x):
    return jax.nn.silu(x)


def swish(x, name=None):
    return silu(x)


@primitive
def tanh(x):
    return jnp.tanh(x)


@primitive
def tanhshrink(x):
    return x - jnp.tanh(x)


@primitive(name="softmax")
def _softmax_impl(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    if dtype is not None:
        from ... import ops
        x = ops.cast(x, convert_dtype(dtype))
    return _softmax_impl(x, axis=axis)


@primitive(name="log_softmax")
def _log_softmax_impl(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    if dtype is not None:
        from ... import ops
        x = ops.cast(x, convert_dtype(dtype))
    return _log_softmax_impl(x, axis=axis)


@primitive(name="leaky_relu")
def _leaky_relu_impl(x, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _leaky_relu_impl(x, negative_slope=negative_slope)


@primitive(name="elu")
def _elu_impl(x, alpha=1.0):
    return jax.nn.elu(x, alpha=alpha)


def elu(x, alpha=1.0, name=None):
    return _elu_impl(x, alpha=alpha)


@primitive(name="celu")
def _celu_impl(x, alpha=1.0):
    return jax.nn.celu(x, alpha=alpha)


def celu(x, alpha=1.0, name=None):
    return _celu_impl(x, alpha=alpha)


@primitive
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@primitive
def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@primitive
def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@primitive(name="hardtanh")
def _hardtanh_impl(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _hardtanh_impl(x, min=min, max=max)


@primitive(name="hardshrink")
def _hardshrink_impl(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def hardshrink(x, threshold=0.5, name=None):
    return _hardshrink_impl(x, threshold=threshold)


@primitive(name="softshrink")
def _softshrink_impl(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def softshrink(x, threshold=0.5, name=None):
    return _softshrink_impl(x, threshold=threshold)


@primitive(name="softplus")
def _softplus_impl(x, beta=1.0, threshold=20.0):
    bx = beta * x
    return jnp.where(bx > threshold, x, jnp.logaddexp(bx, 0.0) / beta)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _softplus_impl(x, beta=beta, threshold=threshold)


@primitive
def softsign(x):
    return x / (1.0 + jnp.abs(x))


@primitive
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@primitive
def prelu(x, weight):
    w = weight
    if w.ndim == 1 and w.shape[0] > 1 and x.ndim > 1:
        # per-channel (NCHW convention: channel axis 1)
        shape = [1] * x.ndim
        shape[1] = w.shape[0]
        w = w.reshape(shape)
    return jnp.where(x >= 0, x, w * x)


@primitive(name="glu")
def _glu_impl(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def glu(x, axis=-1, name=None):
    return _glu_impl(x, axis=axis)


def swiglu(x, y=None, name=None):
    """SwiGLU fusion (reference incubate fused swiglu): silu(x) * y."""
    if y is None:
        return _glu_swish_split(x)
    return _swiglu_impl(x, y)


@primitive(name="swiglu")
def _swiglu_impl(x, y):
    return jax.nn.silu(x) * y


@primitive(name="swiglu_split")
def _glu_swish_split(x):
    a, b = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(a) * b


@primitive(name="maxout")
def _maxout_impl(x, groups, axis=1):
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def maxout(x, groups, axis=1, name=None):
    return _maxout_impl(x, groups=groups, axis=axis)


@primitive(name="thresholded_relu")
def _thresholded_relu_impl(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return _thresholded_relu_impl(x, threshold=threshold, value=value)


@primitive(name="rrelu")
def _rrelu_eval(x, lower, upper):
    return jnp.where(x >= 0, x, (lower + upper) / 2.0 * x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    if not training:
        return _rrelu_eval(x, lower=lower, upper=upper)
    from ...core import state
    key = Tensor(jax.random.key_data(state.default_rng.next_key()))
    return apply("rrelu", _rrelu_train_impl, x, key, lower=lower, upper=upper)


def _rrelu_train_impl(x, key, lower, upper):
    k = jax.random.wrap_key_data(key.astype(jnp.uint32))
    a = jax.random.uniform(k, x.shape, jnp.float32, lower, upper).astype(x.dtype)
    return jnp.where(x >= 0, x, a * x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import state
    key = Tensor(jax.random.key_data(state.default_rng.next_key()))
    return apply("gumbel_softmax", _gumbel_softmax_impl, x, key,
                 temperature=temperature, hard=hard, axis=axis)


def _gumbel_softmax_impl(x, key, temperature, hard, axis):
    k = jax.random.wrap_key_data(key.astype(jnp.uint32))
    g = jax.random.gumbel(k, x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                    inplace=False)
        # straight-through: hard value forward, soft gradient backward
        y = y_hard + y - jax.lax.stop_gradient(y)
    return y

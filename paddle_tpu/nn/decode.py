"""Beam-search decoding (reference ``python/paddle/nn/decode.py``:
``Decoder`` base, ``BeamSearchDecoder`` :153, ``dynamic_decode`` :994).

Host-driven decode loop over a cell (the reference's dynamic decode is a
while-loop too); the per-step math (cell forward, top-k over beam*vocab,
state gather) runs as framework ops, and the final backtrace reuses
``gather_tree``. Works with any ``RNNCellBase``-interface cell.
"""
from __future__ import annotations

import numpy as np

from ..core.dispatch import unwrap
from ..core.tensor import Tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Decode-loop contract consumed by ``dynamic_decode`` (capability
    analog of the reference ``Decoder``; the state is carried as ONE
    object here instead of the reference's (inputs, states, finished)
    triple — simpler to thread through a host loop):

    - ``initialize(inits) -> state``
    - ``step(time, state) -> (tokens [B, beam], parents [B, beam],
      new_state)`` where ``new_state['finished']`` is a bool [B, beam]
    - ``finalize(token_steps, parent_steps, final_state) -> outputs``
    """

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, state):
        raise NotImplementedError

    def finalize(self, token_steps, parent_steps, final_state):
        raise NotImplementedError


class BeamSearchDecoder(Decoder):
    """Reference ``BeamSearchDecoder``: wraps a cell; each step scores
    ``beam_size * vocab`` continuations per batch row, keeps the top
    ``beam_size``, and gathers cell states by parent beam. Finished beams
    are locked: they only ever continue with ``end_token`` at score 0."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] (repeat each row beam_size times);
        the reference helper for attention memories."""
        v = np.asarray(unwrap(x))
        return Tensor(np.repeat(v, beam_size, axis=0))

    # -- host-side beam bookkeeping (numpy) ---------------------------
    def initialize(self, initial_cell_states):
        states = initial_cell_states
        flat = [np.asarray(unwrap(s)) for s in
                (states if isinstance(states, (list, tuple)) else [states])]
        batch = flat[0].shape[0]
        k = self.beam_size
        tiled = [Tensor(np.repeat(f, k, axis=0)) for f in flat]
        tokens = np.full((batch, k), self.start_token, np.int64)
        # only beam 0 is live initially (others would duplicate it)
        log_probs = np.full((batch, k), -1e9, np.float32)
        log_probs[:, 0] = 0.0
        finished = np.zeros((batch, k), bool)
        init = {"tokens": tokens, "log_probs": log_probs,
                "finished": finished, "cell": tiled, "batch": batch}
        return init

    def _embed(self, tokens):
        t = Tensor(tokens.reshape(-1).astype(np.int64))
        if self.embedding_fn is not None:
            return self.embedding_fn(t)
        raise ValueError("BeamSearchDecoder needs embedding_fn to map "
                         "token ids to cell inputs")

    def step(self, time, state):
        k = self.beam_size
        batch = state["batch"]
        inputs = self._embed(state["tokens"])           # [B*k, D]
        cell_states = state["cell"]
        out, new_states = self.cell(
            inputs, cell_states if len(cell_states) > 1
            else cell_states[0])
        logits = self.output_fn(out) if self.output_fn else out
        lg = np.asarray(unwrap(logits)).reshape(batch, k, -1)
        logp = lg - lg.max(-1, keepdims=True)
        logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
        vocab = logp.shape[-1]
        # finished beams may only emit end_token at no cost
        fin = state["finished"]
        locked = np.full_like(logp, -1e9)
        locked[:, :, self.end_token] = 0.0
        logp = np.where(fin[:, :, None], locked, logp)
        total = state["log_probs"][:, :, None] + logp    # [B, k, V]
        flat = total.reshape(batch, -1)
        top = np.argsort(-flat, axis=-1)[:, :k]          # [B, k]
        parents = top // vocab
        tokens = (top % vocab).astype(np.int64)
        log_probs = np.take_along_axis(flat, top, axis=-1)
        finished = np.take_along_axis(fin, parents, axis=-1) \
            | (tokens == self.end_token)
        # gather cell states by parent beam
        new_flat = [np.asarray(unwrap(s)) for s in
                    (new_states if isinstance(new_states, (list, tuple))
                     else [new_states])]
        idx = (np.arange(batch)[:, None] * k + parents).reshape(-1)
        gathered = [Tensor(f[idx]) for f in new_flat]
        new_state = {"tokens": tokens, "log_probs": log_probs,
                     "finished": finished, "cell": gathered,
                     "batch": batch}
        return tokens, parents, new_state

    def finalize(self, token_steps, parent_steps, final_state):
        """Backtrace via gather_tree -> [T, B, beam] sequences."""
        from ..ops.special import gather_tree
        ids = Tensor(np.stack(token_steps).astype(np.int64))
        parents = Tensor(np.stack(parent_steps).astype(np.int64))
        return gather_tree(ids, parents)


def dynamic_decode(decoder, inits=None, max_step_num=25,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Reference ``dynamic_decode``: run ``decoder.step`` until every
    beam finished or ``max_step_num``. Returns (outputs [B, T, beam] or
    [T, B, beam], final scores [B, beam]) (+ lengths)."""
    state = decoder.initialize(inits)
    token_steps, parent_steps = [], []
    for t in range(max_step_num):
        tokens, parents, state = decoder.step(t, state)
        token_steps.append(tokens)
        parent_steps.append(parents)
        if state["finished"].all():
            break
    outputs = decoder.finalize(token_steps, parent_steps, state)
    if not output_time_major:
        from .. import ops
        outputs = ops.transpose(outputs, [1, 0, 2])
    scores = Tensor(state["log_probs"].astype(np.float32))
    if return_length:
        seqs = np.asarray(unwrap(outputs))
        arr = (seqs if not output_time_major
               else np.swapaxes(seqs, 0, 1))  # [B, T, beam]
        lens = (arr != decoder.end_token).sum(axis=1) + \
            (arr == decoder.end_token).any(axis=1)
        return outputs, scores, Tensor(lens.astype(np.int64))
    return outputs, scores

"""Recurrent layers — SimpleRNN/LSTM/GRU cells and sequence wrappers.

Capability analog of ``python/paddle/nn/layer/rnn.py`` (RNNCellBase :234,
SimpleRNNCell :260, LSTMCell :860 [i,f,g,o gate order], GRUCell :1055
[r,z,c], RNN :1280, BiRNN :1350, SimpleRNN/LSTM/GRU :1430+) and the cudnn
rnn kernel (``paddle/phi/kernels/gpu/rnn_kernel.cu.cc``). TPU-native: the
time loop is ONE ``lax.scan`` per layer-direction inside a single
dispatched primitive — XLA compiles the whole unrolled recurrence with the
cell's matmuls batched on the MXU; the generic ``RNN`` wrapper runs ANY
user cell by functionalizing it (``functional_call``), the analog of the
reference's Python control-flow RNN wrapper but trace-compiled instead of
eagerly stepped.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from . import initializer as I
from .layer import Layer


class RNNCellBase(Layer):
    """Reference ``rnn.py RNNCellBase``."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from .. import ops
        shape = shape or self.state_shape
        batch = batch_ref.shape[batch_dim_idx]

        def build(s):
            if isinstance(s, tuple) and s and isinstance(s[0], tuple):
                return tuple(build(e) for e in s)
            if isinstance(s, (list, tuple)) and s and \
                    isinstance(s[0], (list, tuple)):
                return tuple(build(tuple(e)) for e in s)
            return ops.full([batch] + list(s), init_value,
                            dtype=dtype or "float32")

        s = self.state_shape
        if isinstance(s[0], (list, tuple)):
            return tuple(build(tuple(e)) for e in s)
        return build(tuple(s))


def _uniform_param(layer, shape, attr, std):
    if attr is False:
        p = layer.create_parameter(shape, None,
                                   default_initializer=I.Constant(1.0))
        p.stop_gradient = True
        return p
    return layer.create_parameter(shape, attr,
                                  default_initializer=I.Uniform(-std, std))


class SimpleRNNCell(RNNCellBase):
    """Reference ``rnn.py:260`` — h' = act(W_ih x + b_ih + W_hh h + b_hh)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = _uniform_param(self, (hidden_size, input_size),
                                        weight_ih_attr, std)
        self.weight_hh = _uniform_param(self, (hidden_size, hidden_size),
                                        weight_hh_attr, std)
        self.bias_ih = _uniform_param(self, (hidden_size,), bias_ih_attr,
                                      std)
        self.bias_hh = _uniform_param(self, (hidden_size,), bias_hh_attr,
                                      std)
        self.input_size = input_size
        self.hidden_size = hidden_size
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.activation = activation

    @staticmethod
    def _step(p, x, h, activation="tanh"):
        z = (x @ p["weight_ih"].T + p["bias_ih"] +
             h @ p["weight_hh"].T + p["bias_hh"])
        return jnp.tanh(z) if activation == "tanh" else jax.nn.relu(z)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def impl(x, h, wi, wh, bi, bh):
            return self._step(
                {"weight_ih": wi, "weight_hh": wh, "bias_ih": bi,
                 "bias_hh": bh}, x, h, self.activation)

        h = apply("simple_rnn_cell", impl, inputs, states, self.weight_ih,
                  self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    """Reference ``rnn.py:860`` — gates split [i, f, g, o]."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = _uniform_param(
            self, (4 * hidden_size, input_size), weight_ih_attr, std)
        self.weight_hh = _uniform_param(
            self, (4 * hidden_size, hidden_size), weight_hh_attr, std)
        self.bias_ih = _uniform_param(self, (4 * hidden_size,),
                                      bias_ih_attr, std)
        self.bias_hh = _uniform_param(self, (4 * hidden_size,),
                                      bias_hh_attr, std)
        self.hidden_size = hidden_size
        self.input_size = input_size

    @staticmethod
    def _step(p, x, hc):
        h, c = hc
        gates = (x @ p["weight_ih"].T + p["bias_ih"] +
                 h @ p["weight_hh"].T + p["bias_hh"])
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c2 = f * c + i * jnp.tanh(g)
        h2 = o * jnp.tanh(c2)
        return h2, (h2, c2)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states

        def impl(x, h, c, wi, wh, bi, bh):
            _, (h2, c2) = self._step(
                {"weight_ih": wi, "weight_hh": wh, "bias_ih": bi,
                 "bias_hh": bh}, x, (h, c))
            return h2, c2

        h, c = apply("lstm_cell", impl, inputs, h0, c0, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    """Reference ``rnn.py:1055`` — gates [r, z, c];
    h' = z*h + (1-z)*tanh(W_ic x + b_ic + r*(W_hc h + b_hc))."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = _uniform_param(
            self, (3 * hidden_size, input_size), weight_ih_attr, std)
        self.weight_hh = _uniform_param(
            self, (3 * hidden_size, hidden_size), weight_hh_attr, std)
        self.bias_ih = _uniform_param(self, (3 * hidden_size,),
                                      bias_ih_attr, std)
        self.bias_hh = _uniform_param(self, (3 * hidden_size,),
                                      bias_hh_attr, std)
        self.hidden_size = hidden_size
        self.input_size = input_size

    @staticmethod
    def _step(p, x, h):
        xg = x @ p["weight_ih"].T + p["bias_ih"]
        hg = h @ p["weight_hh"].T + p["bias_hh"]
        xr, xz, xc = jnp.split(xg, 3, axis=-1)
        hr, hz, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        cand = jnp.tanh(xc + r * hc)
        return z * h + (1 - z) * cand

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def impl(x, h, wi, wh, bi, bh):
            return self._step(
                {"weight_ih": wi, "weight_hh": wh, "bias_ih": bi,
                 "bias_hh": bh}, x, h)

        h = apply("gru_cell", impl, inputs, states, self.weight_ih,
                  self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


# --- sequence wrappers -----------------------------------------------------

def _cell_kind(cell):
    if isinstance(cell, LSTMCell):
        return "lstm"
    if isinstance(cell, GRUCell):
        return "gru"
    if isinstance(cell, SimpleRNNCell):
        return "rnn"
    return "custom"


def _run_layer(cell, inputs, init_states, reverse=False,
               sequence_length=None, time_major=False):
    """One layer-direction as a single primitive: lax.scan over time."""
    kind = _cell_kind(cell)
    params = dict(cell.named_parameters())
    names = list(params)
    is_tuple_state = kind == "lstm" or (
        kind == "custom" and isinstance(init_states, (tuple, list)))

    if kind == "custom":
        from ..distributed.fleet.pipeline import functional_call

    act = getattr(cell, "activation", "tanh")

    def impl(xv, *rest):
        if is_tuple_state:
            h0, c0 = rest[0], rest[1]
            w = rest[2:len(names) + 2]
            sl = rest[len(names) + 2] if sequence_length is not None \
                else None
        else:
            h0 = rest[0]
            w = rest[1:len(names) + 1]
            sl = rest[len(names) + 1] if sequence_length is not None \
                else None
        p = dict(zip(names, w))
        xs = xv if time_major else jnp.swapaxes(xv, 0, 1)  # [T, B, I]
        t_len = xs.shape[0]
        if reverse:
            xs = xs[::-1]

        def masked(t, new, old):
            if sl is None:
                return new
            # time index for masking honors the reversal
            real_t = (t_len - 1 - t) if reverse else t
            m = (real_t < sl)[:, None].astype(new.dtype)
            return m * new + (1 - m) * old

        def step(carry, inp):
            t, x_t = inp
            if kind == "lstm":
                h, c = carry
                _, (h2, c2) = LSTMCell._step(p, x_t, (h, c))
                h2, c2 = masked(t, h2, h), masked(t, c2, c)
                return (h2, c2), h2
            if kind == "gru":
                h = carry
                h2 = masked(t, GRUCell._step(p, x_t, h), h)
                return h2, h2
            if kind == "rnn":
                h = carry
                h2 = masked(t, SimpleRNNCell._step(p, x_t, h, act), h)
                return h2, h2
            # custom cell: functionalize its forward
            out, new_states = None, None
            res = functional_call(cell, p, x_t, carry)
            out, new_states = res
            if isinstance(new_states, (tuple, list)):
                new_states = tuple(
                    masked(t, n, o) for n, o in zip(new_states, carry))
            else:
                new_states = masked(t, new_states, carry)
            return new_states, out

        carry0 = (h0, c0) if is_tuple_state else h0
        carry, outs = jax.lax.scan(step, carry0,
                                   (jnp.arange(t_len), xs))
        if sl is not None:
            # zero outputs past each sequence's length
            real_t = (t_len - 1 - jnp.arange(t_len)) if reverse \
                else jnp.arange(t_len)
            m = (real_t[:, None] < sl[None, :]).astype(outs.dtype)
            outs = outs * m[..., None]
        if reverse:
            outs = outs[::-1]
        if not time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        if is_tuple_state:
            return outs, carry[0], carry[1]
        return outs, carry

    args = [inputs]
    if is_tuple_state:
        args += [init_states[0], init_states[1]]
    else:
        args += [init_states]
    args += [params[n] for n in names]
    if sequence_length is not None:
        args += [sequence_length]
    res = apply("rnn_scan", impl, *args)
    if is_tuple_state:
        outs, h, c = res
        return outs, (h, c)
    outs, h = res
    return outs, h


class RNN(Layer):
    """Reference ``rnn.py RNN`` — wraps a single cell over a sequence."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            batch_idx = 1 if self.time_major else 0
            initial_states = self.cell.get_initial_states(
                inputs, batch_dim_idx=batch_idx)
        return _run_layer(self.cell, inputs, initial_states,
                          reverse=self.is_reverse,
                          sequence_length=sequence_length,
                          time_major=self.time_major)


class BiRNN(Layer):
    """Reference ``rnn.py BiRNN``."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from .. import ops
        batch_idx = 1 if self.time_major else 0
        if initial_states is None:
            states_fw = self.cell_fw.get_initial_states(
                inputs, batch_dim_idx=batch_idx)
            states_bw = self.cell_bw.get_initial_states(
                inputs, batch_dim_idx=batch_idx)
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = _run_layer(self.cell_fw, inputs, states_fw,
                                   reverse=False,
                                   sequence_length=sequence_length,
                                   time_major=self.time_major)
        out_bw, st_bw = _run_layer(self.cell_bw, inputs, states_bw,
                                   reverse=True,
                                   sequence_length=sequence_length,
                                   time_major=self.time_major)
        return ops.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Shared multilayer/direction machinery of SimpleRNN/LSTM/GRU
    (reference ``rnn.py RNNBase``)."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError("direction must be forward|bidirect")
        self.mode = mode
        self.num_layers = num_layers
        self.bidirectional = direction != "forward"
        self.time_major = time_major
        self.dropout = dropout
        self.hidden_size = hidden_size
        num_dir = 2 if self.bidirectional else 1

        def mk(in_sz):
            kw = dict(weight_ih_attr=weight_ih_attr,
                      weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
            if mode == "LSTM":
                return LSTMCell(in_sz, hidden_size, **kw)
            if mode == "GRU":
                return GRUCell(in_sz, hidden_size, **kw)
            return SimpleRNNCell(in_sz, hidden_size,
                                 activation=activation, **kw)

        self.cells = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * num_dir
            for d in range(num_dir):
                cell = mk(in_sz)
                self.add_sublayer(f"cell_{layer}_{d}", cell)
                self.cells.append(cell)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import ops
        from .functional import dropout as F_dropout
        num_dir = 2 if self.bidirectional else 1
        batch_idx = 1 if self.time_major else 0
        batch = inputs.shape[batch_idx]
        lstm = self.mode == "LSTM"

        def init_for(idx):
            if initial_states is None:
                return self.cells[idx].get_initial_states(
                    inputs, batch_dim_idx=batch_idx)
            if lstm:
                h, c = initial_states
                return (h[idx], c[idx])
            return initial_states[idx]

        x = inputs
        finals = []
        for layer in range(self.num_layers):
            outs = []
            for d in range(num_dir):
                idx = layer * num_dir + d
                o, st = _run_layer(self.cells[idx], x, init_for(idx),
                                   reverse=(d == 1),
                                   sequence_length=sequence_length,
                                   time_major=self.time_major)
                outs.append(o)
                finals.append(st)
            x = outs[0] if num_dir == 1 else ops.concat(outs, axis=-1)
            if self.dropout and layer < self.num_layers - 1 \
                    and self.training:
                x = F_dropout(x, p=self.dropout, training=True)
        if lstm:
            h = ops.stack([st[0] for st in finals], axis=0)
            c = ops.stack([st[1] for st in finals], axis=0)
            return x, (h, c)
        return x, ops.stack(finals, axis=0)


class SimpleRNN(_RNNBase):
    """Reference ``rnn.py SimpleRNN``."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation,
                         **kwargs)


class LSTM(_RNNBase):
    """Reference ``rnn.py LSTM``."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    """Reference ``rnn.py GRU``."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]

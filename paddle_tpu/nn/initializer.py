"""paddle_tpu.nn.initializer — parameter initializers.

Capability analog of ``python/paddle/nn/initializer/`` (reference: constant,
normal, uniform, xavier, kaiming, truncated normal...). TPU-native: each
initializer is a callable ``(shape, dtype) -> jnp.ndarray`` drawing from the
framework's global functional PRNG (``core.state.default_rng``), so seeding
via ``paddle_tpu.seed`` makes init deterministic.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import state


def _fan_in_out(shape):
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # Linear weights are [in, out] in paddle convention.
        return shape[0], shape[1]
    # Conv weights [out_c, in_c, *k] (paddle convention).
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        k = state.default_rng.next_key()
        return (self.mean + self.std *
                jax.random.normal(k, shape, jnp.float32)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=jnp.float32):
        k = state.default_rng.next_key()
        x = jax.random.truncated_normal(k, self.a, self.b, shape, jnp.float32)
        return (self.mean + self.std * x).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        k = state.default_rng.next_key()
        return jax.random.uniform(
            k, shape, jnp.float32, self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = (math.sqrt(2.0 / (1 + self.negative_slope ** 2))
                if self.nonlinearity == "leaky_relu" else math.sqrt(2.0))
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = (math.sqrt(2.0 / (1 + self.negative_slope ** 2))
                if self.nonlinearity == "leaky_relu" else math.sqrt(2.0))
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        arr = jnp.asarray(np.asarray(self.value), dtype=dtype)
        assert tuple(arr.shape) == tuple(shape), (
            f"Assign initializer shape {arr.shape} != parameter shape {shape}")
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        k = state.default_rng.next_key()
        return (self.gain * jax.random.orthogonal(
            k, int(shape[-1]), tuple(shape[:-1]))).astype(dtype)


class Dirac(Initializer):
    """Identity-preserving conv init (reference nn/initializer/dirac.py)."""

    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        out_c, in_c = int(shape[0]), int(shape[1])
        kernel = [int(s) for s in shape[2:]]
        w = np.zeros(tuple(shape), dtype=np.float32)
        per = out_c // self.groups
        center = tuple(k // 2 for k in kernel)
        for o in range(out_c):
            i = o % per
            if i < in_c:
                w[(o, i) + center] = 1.0
        return jnp.asarray(w, dtype=dtype)


# paddle-compatible default: XavierUniform-like "default" is actually
# Uniform(-sqrt(1/fan_in)) for Linear/Conv in paddle (GlorotUniform for some).
def _default_weight_init(shape, dtype=jnp.float32):
    return XavierUniform()(shape, dtype)


def calculate_gain(nonlinearity, param=None):
    recommended = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "selu": 3.0 / 4,
    }
    if nonlinearity == "leaky_relu":
        slope = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + slope ** 2))
    if nonlinearity in recommended:
        return recommended[nonlinearity]
    raise ValueError(f"unsupported nonlinearity {nonlinearity!r}")


def to_initializer(x):
    """Coerce user input (None | Initializer | number | array | bool) into an
    Initializer. ``False`` means "no parameter" and is handled by callers."""
    if x is None:
        return None
    if isinstance(x, Initializer):
        return x
    if isinstance(x, (int, float)):
        return Constant(float(x))
    return Assign(x)

"""Gradient clipping.

Analog of ``python/paddle/nn/clip.py`` (reference: ClipGradByGlobalNorm used
by every fleet optimizer). Operates on (param, grad) lists, returning new
grads — the optimizer applies them.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)

    def _dygraph_clip(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._read(), self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                out.append((p, g))
                continue
            v = g._read()
            norm = jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, Tensor((v.astype(jnp.float32) * scale)
                                  .astype(v.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _flat_scale(self, sq_terms):
        """Fused-path twin of ``_dygraph_clip``'s scale: the same
        formula over precomputed sum-of-squares terms (one per flat
        bucket + one per leftover grad) — a SINGLE global reduction tree
        instead of the per-param chain below."""
        global_norm = jnp.sqrt(sum(sq_terms))
        return self.clip_norm / jnp.maximum(global_norm, self.clip_norm)

    @staticmethod
    def _apply_scale(params_grads, scale):
        """Scale each clippable grad (new tensors, originals untouched);
        shared by the per-param path below and the fused path's
        leftover-grad handling so the two can never drift apart."""
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                out.append((p, g))
                continue
            v = g._read()
            out.append((p, Tensor((v.astype(jnp.float32) * scale)
                                  .astype(v.dtype))))
        return out

    def _dygraph_clip(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                continue
            v = g._read()
            sq.append(jnp.sum(jnp.square(v.astype(jnp.float32))))
        if not sq:
            return params_grads
        return self._apply_scale(params_grads, self._flat_scale(sq))

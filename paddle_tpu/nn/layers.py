"""Concrete nn layers.

Analog of ``python/paddle/nn/layer/{common,conv,norm,pooling,activation,
container}.py`` (reference). Parameter layouts follow paddle conventions
(Linear weight [in, out]; Conv weight [out_c, in_c/groups, *k]) so state
dicts round-trip with reference checkpoints.
"""
from __future__ import annotations

import collections
import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer, ParamAttr


# --------------------------------------------------------------------------
# common
# --------------------------------------------------------------------------
class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        if padding_idx is not None and padding_idx < 0:
            padding_idx += num_embeddings
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        if padding_idx is not None:
            w = self.weight._read()
            self.weight._write(w.at[padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis,
                         training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from .. import ops
        return ops.flatten(x, start_axis=self.start_axis,
                           stop_axis=self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format=None,
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode, value, data_format):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


# --------------------------------------------------------------------------
# conv
# --------------------------------------------------------------------------
class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW", transposed=False, output_padding=0):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * nd
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format
        self._output_padding = output_padding
        self._nd = nd
        if transposed:
            wshape = [in_channels, out_channels // groups, *kernel_size]
        else:
            wshape = [out_channels, in_channels // groups, *kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(kernel_size))
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound)
            if bias_attr is None else None)

    def _pm_input(self, x):
        """Non-zero ``padding_mode`` (reflect/replicate/circular) is realised
        by pre-padding the input with F.pad and running the conv unpadded
        (XLA's conv only zero-pads)."""
        if self._padding_mode == "zeros":
            return x, self._padding
        from .functional.conv import _norm_padding, _tuplize
        nd = self._nd
        pairs = _norm_padding(self._padding, nd, _tuplize(self._stride, nd),
                              _tuplize(self._dilation, nd),
                              self._kernel_size)
        if pairs == "SAME":
            raise ValueError(
                "padding_mode != 'zeros' requires explicit integer padding, "
                f"got {self._padding!r}")
        flat = []
        for lo, hi in reversed(pairs):  # innermost spatial axis first
            flat += [lo, hi]
        x = F.pad(x, flat, mode=self._padding_mode,
                  data_format=self._data_format)
        return x, 0

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={list(self._kernel_size)}, "
                f"stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        x, pad = self._pm_input(x)
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        pad, self._dilation, self._groups,
                        self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        x, pad = self._pm_input(x)
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        pad, self._dilation, self._groups,
                        self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        x, pad = self._pm_input(x)
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        pad, self._dilation, self._groups,
                        self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._groups, self._dilation,
            output_size, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._groups, self._dilation,
            output_size, self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._groups, self._dilation,
            output_size, self._data_format)


# --------------------------------------------------------------------------
# norm
# --------------------------------------------------------------------------
class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-first norm for LLM blocks (reference fused rms_norm kernel)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self._epsilon,
                          begin_norm_axis=-len(self._normalized_shape))


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Under pjit/GSPMD the batch axis is sharded and XLA computes global
    batch statistics automatically when the reduction spans the mesh — so
    SyncBatchNorm ≡ BatchNorm on TPU (the reference needs an explicit NCCL
    allreduce, ``python/paddle/nn/layer/norm.py`` SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    pass


class InstanceNorm3D(InstanceNorm1D):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Reference ``nn.SpectralNorm`` (``python/paddle/nn/layer/norm.py``):
    forward(weight) returns the spectrally-normalized weight via power
    iteration (functional ``F.spectral_norm``)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._weight_shape = tuple(weight_shape)
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps

    def forward(self, weight):
        from .functional import spectral_norm
        if tuple(weight.shape) != self._weight_shape:
            raise ValueError(
                f"SpectralNorm: expected weight shape "
                f"{self._weight_shape}, got {tuple(weight.shape)}")
        return spectral_norm(weight, dim=self._dim,
                             power_iters=self._power_iters, eps=self._eps)


# --------------------------------------------------------------------------
# pooling
# --------------------------------------------------------------------------
class _Pool(Layer):
    """Shared storage for pool layers; each subclass owns its __init__ so the
    positional parameter order matches the reference exactly
    (``python/paddle/nn/layer/pooling.py:79,185,284,388,498,598``)."""

    def _store(self, kernel_size, stride, padding, ceil_mode=False,
               return_mask=False, data_format=None, exclusive=True,
               divisor_override=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.data_format = data_format
        self.exclusive = exclusive
        self.divisor_override = divisor_override


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, name=None):
        self._store(kernel_size, stride, padding, ceil_mode=ceil_mode,
                    return_mask=return_mask)

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode, "NCL")


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCHW",
                 name=None):
        self._store(kernel_size, stride, padding, ceil_mode=ceil_mode,
                    return_mask=return_mask, data_format=data_format)

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode,
                            self.data_format)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCDHW",
                 name=None):
        self._store(kernel_size, stride, padding, ceil_mode=ceil_mode,
                    return_mask=return_mask, data_format=data_format)

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode,
                            self.data_format)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        self._store(kernel_size, stride, padding, ceil_mode=ceil_mode,
                    exclusive=exclusive)

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.exclusive, self.ceil_mode, "NCL")


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        self._store(kernel_size, stride, padding, ceil_mode=ceil_mode,
                    exclusive=exclusive, divisor_override=divisor_override,
                    data_format=data_format)

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            self.divisor_override,
                            self.data_format)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        self._store(kernel_size, stride, padding, ceil_mode=ceil_mode,
                    exclusive=exclusive, divisor_override=divisor_override,
                    data_format=data_format)

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            self.divisor_override,
                            self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size, self._data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size, self._return_mask)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size, self._return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size, self._return_mask)


# --------------------------------------------------------------------------
# activations as layers
# --------------------------------------------------------------------------
def _act_layer(fn_name, **defaults):
    fn = getattr(F, fn_name)

    class _Act(Layer):
        def __init__(self, name=None, **kw):
            super().__init__()
            self._kw = {**defaults, **kw}

        def forward(self, x):
            return fn(x, **self._kw)

    _Act.__name__ = "".join(p.capitalize() for p in fn_name.split("_"))
    return _Act


class ReLU(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.relu6(x)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self._approximate)


class Sigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.sigmoid(x)


class LogSigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.log_sigmoid(x)


class Silu(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.silu(x)


class Swish(Silu):
    pass


class Tanh(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.tanh(x)


class Tanhshrink(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.tanhshrink(x)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self._axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, scale=self._scale, alpha=self._alpha)


class Hardswish(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardswish(x)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class Softsign(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.softsign(x)


class Mish(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.mish(x)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, self._axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self._threshold, self._value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold, self._value)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, training=self.training)


# --------------------------------------------------------------------------
# containers
# --------------------------------------------------------------------------
class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0],
                                           collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        elif len(layers) == 1 and isinstance(layers[0], (list, tuple)):
            # Sequential([l1, l2]) or Sequential([("name", l), ...])
            for i, item in enumerate(layers[0]):
                if isinstance(item, (list, tuple)):
                    self.add_sublayer(item[0], item[1])
                else:
                    self.add_sublayer(str(i), item)
        elif len(layers) > 0 and all(
                isinstance(l, (list, tuple)) and len(l) == 2 and
                isinstance(l[0], str) for l in layers):
            # Sequential(("a", l1), ("b", l2)) named form
            for name, l in layers:
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __setitem__(self, idx, layer):
        keys = list(self._sub_layers)
        self._sub_layers[keys[idx]] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        keys = list(self._parameters)
        return self._parameters[keys[idx]]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        l = self._sub_layers.pop(key)
        return l

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, dict) \
            else sublayers
        for k, v in items:
            self.add_sublayer(k, v)


class Bilinear(Layer):
    """Reference ``nn.Bilinear``: out = x1 W x2 + b with
    W [out_features, in1_features, in2_features]."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from .functional import bilinear
        return bilinear(x1, x2, self.weight, self.bias)

"""paddle_tpu.nn — the neural network layer library.

Analog of ``python/paddle/nn/`` (reference). ``Layer`` is the module base;
``functional`` the op surface; concrete layers mirror paddle.nn's names.
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
from .layer import Layer, ParamAttr  # noqa: F401
from .layers import (  # noqa: F401
    Bilinear, Identity, Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    PixelShuffle, PixelUnshuffle, Pad1D, Pad2D, Pad3D, ZeroPad2D,
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose,
    Conv3DTranspose,
    LayerNorm, RMSNorm, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
    ReLU, ReLU6, GELU, Sigmoid, LogSigmoid, Silu, Swish, Tanh, Tanhshrink,
    Softmax, LogSoftmax, LeakyReLU, ELU, CELU, SELU, Hardswish, Hardsigmoid,
    Hardtanh, Hardshrink, Softshrink, Softplus, Softsign, Mish, PReLU, GLU,
    Maxout, ThresholdedReLU, RReLU,
    Sequential, LayerList, ParameterList, LayerDict,
)
from .loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, CTCLoss,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN,
    SimpleRNN, LSTM, GRU,
)

"""paddle_tpu.nn.Layer — module base class.

Capability analog of ``paddle.nn.Layer`` (reference
``python/paddle/nn/layer/layers.py:334``): parameter/buffer/sublayer
registries, forward hooks, state_dict round-trip, train/eval mode, dtype/
device movement. TPU-native storage: parameters are ``Parameter`` facades over
jax.Arrays; ``state_dict`` yields host-transferable tensors for orbax-style
checkpointing in ``paddle_tpu.framework.save``.
"""
from __future__ import annotations

import collections
from typing import Iterator, Optional

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Parameter, Tensor
from . import initializer as I


class ParamAttr:
    """Analog of ``paddle.ParamAttr`` (reference python/paddle/base/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


_layer_counters: dict[str, int] = collections.defaultdict(int)


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        cls = type(self).__name__.lower()
        _layer_counters[cls] += 1
        self._full_name = f"{name_scope or cls}_{_layer_counters[cls] - 1}"
        self._dtype = convert_dtype(dtype) or np.dtype("float32")
        self._parameters: dict[str, Optional[Parameter]] = \
            collections.OrderedDict()
        self._buffers: dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: dict[str, "Layer"] = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True

    # --- construction helpers -------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None,
                         is_bias=False, default_initializer=None):
        """Reference ``layers.py`` create_parameter: resolve ParamAttr +
        initializer, build a Parameter. ``attr=False`` -> no parameter."""
        if attr is False:
            return None
        if attr is None:
            attr = ParamAttr()
        elif isinstance(attr, str):
            attr = ParamAttr(name=attr)
        elif isinstance(attr, I.Initializer):
            attr = ParamAttr(initializer=attr)
        dtype = convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I._default_weight_init
        elif not isinstance(init, I.Initializer) and not callable(init):
            init = I.to_initializer(init)
        from ..core import lazy as _lazy
        if _lazy.in_lazy_mode():
            # LazyGuard: no storage — abstract shape/dtype only
            import jax
            data = jax.ShapeDtypeStruct(
                tuple(int(s) for s in shape), jnp.dtype(dtype))
        else:
            data = init(tuple(int(s) for s in shape), jnp.dtype(dtype))
        p = Parameter(data, trainable=attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError(f"expected Parameter, got {type(parameter)}")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError(f"expected Layer, got {type(sublayer)}")
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # --- attribute protocol ---------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        sublayers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (sublayers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if sublayers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            sublayers[name] = value
        elif buffers is not None and name in buffers:
            if value is not None and not isinstance(value, Tensor):
                value = Tensor(value)
            buffers[name] = value
        else:
            if params is not None and name in params:
                if value is None:
                    params[name] = None
                    return
                del params[name]
            if sublayers is not None and name in sublayers:
                if value is None:
                    sublayers[name] = None
                    return
                del sublayers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # --- iteration ------------------------------------------------------
    def named_members(self, get_members_fn, prefix="", include_self=True,
                      layers_set=None):
        layers_set = layers_set if layers_set is not None else set()
        for lname, layer in self.named_sublayers(
                prefix=prefix, include_self=include_self):
            if id(layer) in layers_set:
                continue
            layers_set.add(id(layer))
            for k, v in get_members_fn(layer):
                if v is None:
                    continue
                yield (lname + "." + k if lname else k), v

    def parameters(self, include_sublayers=True) -> list:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        if not include_sublayers:
            for k, v in self._parameters.items():
                if v is not None:
                    yield k, v
            return
        seen = set()
        for name, p in self.named_members(
                lambda l: l._parameters.items(), prefix=prefix):
            if id(p) in seen:
                continue
            seen.add(id(p))
            yield name, p

    def buffers(self, include_sublayers=True) -> list:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        if not include_sublayers:
            for k, v in self._buffers.items():
                if v is not None:
                    yield k, v
            return
        for name, b in self.named_members(
                lambda l: l._buffers.items(), prefix=prefix):
            yield name, b

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False) -> list:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        layers_set = layers_set if layers_set is not None else set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from l.named_sublayers(
                prefix=sub_prefix, include_self=True, layers_set=layers_set)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # --- mode / dtype / device -----------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        dtype = convert_dtype(dtype)

        def move(t):
            if t is None:
                return None
            val = t._read()
            if dtype is not None and jnp.issubdtype(val.dtype, jnp.floating):
                val = val.astype(dtype)
            t._write(val)
            return t

        for l in self.sublayers(include_self=True):
            for k in l._parameters:
                move(l._parameters[k])
            for k in l._buffers:
                move(l._buffers[k])
            if dtype is not None:
                l._dtype = np.dtype(str(jnp.dtype(dtype)))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # --- state dict -----------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None \
            else collections.OrderedDict()
        for name, p in self.named_parameters(
                include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers(
                include_sublayers=include_sublayers):
            leaf = name.rsplit(".", 1)[-1]
            if leaf in self._find_owner(name)._non_persistable_buffer_names:
                continue
            dest[structured_name_prefix + name] = b
        return dest

    def _find_owner(self, dotted_name):
        layer = self
        parts = dotted_name.split(".")[:-1]
        for p in parts:
            nxt = layer._sub_layers.get(p)
            if nxt is None:
                return layer
            layer = nxt
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = dict(self.state_dict())
        matched = set()
        for name, value in state_dict.items():
            target = own.get(name)
            if target is None:
                unexpected.append(name)
                continue
            matched.add(name)
            val = value._read() if isinstance(value, Tensor) else \
                jnp.asarray(np.asarray(value))
            if tuple(val.shape) != tuple(target._read().shape):
                raise ValueError(
                    f"shape mismatch for {name}: checkpoint {tuple(val.shape)}"
                    f" vs model {tuple(target._read().shape)}")
            target._write(val.astype(target._read().dtype))
        missing = [k for k in own if k not in matched]
        return missing, unexpected

    load_dict = set_state_dict

    # --- hooks ----------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        hid = id(hook)
        self._forward_pre_hooks[hid] = hook
        return HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        hid = id(hook)
        self._forward_post_hooks[hid] = hook
        return HookRemoveHelper(self._forward_post_hooks, hid)

    # --- call -----------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub = repr(l).split("\n")
            sub = [sub[0]] + ["  " + s for s in sub[1:]]
            lines.append(f"({name}): " + "\n".join(sub))
        main = f"{type(self).__name__}({extra}"
        if lines:
            main += "\n" + "\n".join("  " + ln for ln in lines) + "\n"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

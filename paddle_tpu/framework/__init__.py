"""paddle_tpu.framework — save/load and framework-level helpers.

Analog of ``python/paddle/framework/io.py`` (reference ``io.py:721`` save,
``:960`` load): pickle-based nested state dicts with tensors converted to
numpy on save and restored as device tensors on load.
"""
from __future__ import annotations

import pickle

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, Parameter


_SENTINEL = "__pdtpu_tensor__"


def _to_host(obj):
    if isinstance(obj, Tensor):
        return {_SENTINEL: True, "data": np.asarray(obj._read()),
                "stop_gradient": obj.stop_gradient,
                "is_param": isinstance(obj, Parameter)}
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_host(v) for v in obj)
    return obj


def _to_device(obj):
    if isinstance(obj, dict):
        if obj.get(_SENTINEL):
            if obj.get("is_param"):
                return Parameter(jnp.asarray(obj["data"]),
                                 trainable=not obj["stop_gradient"])
            t = Tensor(jnp.asarray(obj["data"]))
            t.stop_gradient = obj["stop_gradient"]
            return t
        return {k: _to_device(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_device(v) for v in obj)
    return obj


def save(obj, path, protocol=4):
    # atomic commit (resilience.atomic): a crash mid-save leaves the
    # previous file intact instead of a torn pickle that loads garbage
    from ..resilience.atomic import atomic_write

    with atomic_write(path) as f:
        pickle.dump(_to_host(obj), f, protocol=protocol)


def load(path, return_numpy=False):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if return_numpy:
        return obj
    return _to_device(obj)


def set_grad_enabled(mode):
    from ..core.autograd import set_grad_enabled as _sge
    return _sge(mode)

"""``paddle.sparse`` parity — COO/CSR sparse tensors and ops.

Capability analog of SURVEY C8's sparse tensor types
(``paddle/phi/core/sparse_coo_tensor.h``, ``sparse_csr_tensor.h``) and the
``python/paddle/sparse/`` op surface (creation ``creation.py``
sparse_coo_tensor/sparse_csr_tensor, unary/binary ``unary.py,binary.py``,
``nn/layer/activation.py``). TPU-native: storage is
``jax.experimental.sparse`` BCOO/BCSR; matmuls lower to
``bcoo_dot_general`` (gather/scatter + MXU dots under XLA). Sparse
tensors interoperate with dense ``Tensor`` at the boundaries
(``to_dense``/``to_sparse_coo``); elementwise ops on matching sparsity
run on values directly.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.dispatch import unwrap
from ..core.tensor import Tensor


class SparseTensor:
    """Common surface of sparse COO/CSR wrappers (the DenseTensor-facade
    analog of ``SparseCooTensor``/``SparseCsrTensor``)."""

    def __init__(self, mat, shape):
        self._mat = mat
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._mat.dtype

    @property
    def nnz(self):
        return int(self._mat.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._mat.todense())

    def __repr__(self):
        return (f"{type(self).__name__}(shape={self._shape}, "
                f"nnz={self.nnz}, dtype={self.dtype})")


class SparseCooTensor(SparseTensor):
    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._mat.indices, 0, 1))

    def values(self) -> Tensor:
        return Tensor(self._mat.data)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def to_sparse_csr(self) -> "SparseCsrTensor":
        bcsr = jsparse.BCSR.from_bcoo(self._mat.sort_indices())
        return SparseCsrTensor(bcsr, self._shape)

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(
            self._mat.sum_duplicates(nse=self._mat.nse), self._shape)


class SparseCsrTensor(SparseTensor):
    def crows(self) -> Tensor:
        return Tensor(self._mat.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._mat.indices)

    def values(self) -> Tensor:
        return Tensor(self._mat.data)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def to_sparse_coo(self, sparse_dim=2) -> SparseCooTensor:
        return SparseCooTensor(self._mat.to_bcoo(), self._shape)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Reference ``sparse/creation.py sparse_coo_tensor``:
    indices [ndim, nnz], values [nnz]."""
    idx = jnp.asarray(unwrap(indices), jnp.int32)
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    mat = jsparse.BCOO((vals, jnp.swapaxes(idx, 0, 1)),
                       shape=tuple(int(s) for s in shape))
    return SparseCooTensor(mat, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Reference ``sparse/creation.py sparse_csr_tensor``."""
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    mat = jsparse.BCSR(
        (vals, jnp.asarray(unwrap(cols), jnp.int32),
         jnp.asarray(unwrap(crows), jnp.int32)),
        shape=tuple(int(s) for s in shape))
    return SparseCsrTensor(mat, shape)


def to_sparse_coo(x: Tensor, sparse_dim=None) -> SparseCooTensor:
    v = unwrap(x)
    n = int((v != 0).sum())
    return SparseCooTensor(jsparse.BCOO.fromdense(v, nse=max(n, 1)),
                           v.shape)


def to_sparse_csr(x: Tensor) -> SparseCsrTensor:
    return to_sparse_coo(x).to_sparse_csr()


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x._mat.to_bcoo()
    return x._mat


def _same_pattern(a, b):
    return (a.indices.shape == b.indices.shape and
            bool(jnp.all(a.indices == b.indices)))


def _binary(name, fn, x, y):
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        ma, mb = _coo(x).sort_indices(), _coo(y).sort_indices()
        if _same_pattern(ma, mb):
            out = jsparse.BCOO((fn(ma.data, mb.data), ma.indices),
                               shape=ma.shape)
            return SparseCooTensor(out, x._shape)
        # mismatched patterns: fall back through dense (reference kernels
        # require matched patterns for csr; coo merges)
        return to_sparse_coo(Tensor(fn(ma.todense(), mb.todense())))
    raise TypeError(f"sparse.{name} expects two sparse tensors")


def add(x, y):
    return _binary("add", jnp.add, x, y)


def subtract(x, y):
    return _binary("subtract", jnp.subtract, x, y)


def multiply(x, y):
    return _binary("multiply", jnp.multiply, x, y)


def divide(x, y):
    return _binary("divide", jnp.divide, x, y)


def matmul(x, y):
    """sparse @ dense (reference ``sparse/binary.py matmul``)."""
    if isinstance(x, SparseTensor):
        dense = unwrap(y) if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(_coo(x) @ dense)
    raise TypeError("sparse.matmul expects a sparse lhs")


def masked_matmul(x: Tensor, y: Tensor, mask: SparseCooTensor):
    """Reference ``sparse/binary.py masked_matmul``: (x @ y) sampled at
    mask's sparsity — lowers to bcoo_dot_general_sampled (SDDMM)."""
    out = jsparse.bcoo_dot_general_sampled(
        unwrap(x), unwrap(y), _coo(mask).indices,
        dimension_numbers=(((1,), (0,)), ((), ())))
    return SparseCooTensor(
        jsparse.BCOO((out, _coo(mask).indices), shape=mask._mat.shape),
        mask._shape)


def _preserve(x, m, data):
    """Rebuild x's storage kind around new values on the same pattern."""
    out = SparseCooTensor(jsparse.BCOO((data, m.indices), shape=m.shape),
                          x._shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def _unary(fn):
    def op(x, name=None):
        m = _coo(x)
        return _preserve(x, m, fn(m.data))
    return op


# zero-preserving elementwise set (reference ``sparse/unary.py`` — the op
# list is exactly the f(0)=0 functions, so the sparsity pattern carries)
relu = _unary(lambda v: jnp.maximum(v, 0))
sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
tanh = _unary(jnp.tanh)
square = _unary(jnp.square)
sqrt = _unary(jnp.sqrt)
abs = _unary(jnp.abs)  # noqa: A001
neg = _unary(jnp.negative)
log1p = _unary(jnp.log1p)
expm1 = _unary(jnp.expm1)
rad2deg = _unary(jnp.rad2deg)
deg2rad = _unary(jnp.deg2rad)
isnan = _unary(jnp.isnan)


def pow(x, factor, name=None):  # noqa: A001
    """Reference ``unary.py:575``."""
    m = _coo(x)
    return _preserve(x, m, jnp.power(m.data, unwrap(factor)))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """Reference ``unary.py:537``."""
    from ..core.dtype import convert_dtype
    m = _coo(x)
    idx, vals = m.indices, m.data
    if index_dtype is not None:
        idx = idx.astype(convert_dtype(index_dtype))
    if value_dtype is not None:
        vals = vals.astype(convert_dtype(value_dtype))
    if index_dtype is None:
        return _preserve(x, m, vals)
    out = SparseCooTensor(jsparse.BCOO((vals, idx), shape=m.shape),
                          x._shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def coalesce(x, name=None):
    """Reference ``unary.py:675``: merge duplicate coordinates."""
    if isinstance(x, SparseCooTensor):
        return x.coalesce()
    return x


def transpose(x, perm, name=None):
    """Reference ``unary.py:136``: permute dims by index-row shuffle —
    no value movement."""
    m = _coo(x)
    perm = [int(p) for p in perm]
    idx = m.indices[:, jnp.asarray(perm)]
    shape = tuple(x._shape[p] for p in perm)
    out = SparseCooTensor(jsparse.BCOO((m.data, idx), shape=shape),
                          shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def reshape(x, shape, name=None):
    """Reference ``unary.py:812``: linearize coordinates, unravel into
    the new shape."""
    import numpy as _np
    m = _coo(x).sum_duplicates(nse=_coo(x).nse)
    old = x._shape
    n = int(_np.prod(old))
    shape = list(shape)
    if -1 in shape:
        known = int(_np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // known
    shape = tuple(int(s) for s in shape)
    if int(_np.prod(shape)) != n:
        raise ValueError(f"cannot reshape {old} into {shape}")
    lin = jnp.zeros(m.indices.shape[0], jnp.int32)
    stride = 1
    for d in range(len(old) - 1, -1, -1):
        lin = lin + m.indices[:, d].astype(jnp.int32) * stride
        stride *= old[d]
    new_idx = []
    for d in range(len(shape) - 1, -1, -1):
        new_idx.append((lin % shape[d]).astype(jnp.int32))
        lin = lin // shape[d]
    idx = jnp.stack(list(reversed(new_idx)), axis=1)
    return SparseCooTensor(jsparse.BCOO((m.data, idx), shape=shape),
                           shape)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """Reference ``unary.py:170``. axis=None collapses to a dense
    scalar; otherwise the axis is dropped from the coordinates and
    duplicates merge."""
    m = _coo(x)
    if axis is None:
        out = m.data.sum()
        if dtype is not None:
            from ..core.dtype import convert_dtype
            out = out.astype(convert_dtype(dtype))
        return Tensor(out)
    ax = int(axis) if int(axis) >= 0 else len(x._shape) + int(axis)
    keep = [d for d in range(len(x._shape)) if d != ax]
    idx = m.indices[:, jnp.asarray(keep)]
    if keepdim:
        idx = jnp.insert(idx, ax, 0, axis=1)
        shape = tuple(1 if d == ax else s
                      for d, s in enumerate(x._shape))
    else:
        shape = tuple(x._shape[d] for d in keep)
    vals = m.data if dtype is None else m.data.astype(dtype)
    out = jsparse.BCOO((vals, idx), shape=shape)
    return SparseCooTensor(out.sum_duplicates(nse=out.nse), shape)


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    """Reference ``unary.py:947``: crop coordinate ranges (eager-only —
    the output nnz is data-dependent)."""
    import numpy as _np
    m = _coo(x)
    idx = _np.asarray(m.indices)
    vals = _np.asarray(m.data)
    shape = list(x._shape)
    mask = _np.ones(idx.shape[0], bool)
    for ax, s, e in zip(axes, starts, ends):
        ax = int(ax) if int(ax) >= 0 else len(shape) + int(ax)
        s = int(s) if int(s) >= 0 else shape[ax] + int(s)
        e = int(e) if int(e) >= 0 else shape[ax] + int(e)
        s, e = max(s, 0), min(e, shape[ax])
        mask &= (idx[:, ax] >= s) & (idx[:, ax] < e)
        idx = idx.copy()
        idx[:, ax] -= s
        shape[ax] = max(e - s, 0)
    idx, vals = idx[mask], vals[mask]
    out = jsparse.BCOO((jnp.asarray(vals), jnp.asarray(idx)),
                       shape=tuple(shape))
    return SparseCooTensor(out, tuple(shape))


def mv(x, vec, name=None):
    """sparse matrix @ dense vector (reference ``binary.py:176``)."""
    if not isinstance(x, SparseTensor):
        raise TypeError("sparse.mv expects a sparse lhs")
    v = unwrap(vec) if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(_coo(x) @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) (reference ``multiary.py:22``)."""
    d = unwrap(input) if isinstance(input, Tensor) else jnp.asarray(input)
    prod = unwrap(matmul(x, y))
    return Tensor(beta * d + alpha * prod)


def is_same_shape(x, y):
    """Reference ``binary.py:425``."""
    sx = x._shape if isinstance(x, SparseTensor) else tuple(x.shape)
    sy = y._shape if isinstance(y, SparseTensor) else tuple(y.shape)
    return tuple(sx) == tuple(sy)


from . import nn  # noqa: E402,F401

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "to_sparse_coo", "to_sparse_csr", "add",
    "subtract", "multiply", "divide", "matmul", "masked_matmul", "mv",
    "addmm", "is_same_shape", "relu", "sin", "tan", "asin", "atan",
    "sinh", "asinh", "atanh", "tanh", "square", "sqrt", "abs", "neg",
    "log1p", "expm1", "rad2deg", "deg2rad", "isnan", "pow", "cast",
    "coalesce", "transpose", "reshape", "sum", "slice", "nn",
]

"""``paddle.sparse`` parity — COO/CSR sparse tensors and ops.

Capability analog of SURVEY C8's sparse tensor types
(``paddle/phi/core/sparse_coo_tensor.h``, ``sparse_csr_tensor.h``) and the
``python/paddle/sparse/`` op surface (creation ``creation.py``
sparse_coo_tensor/sparse_csr_tensor, unary/binary ``unary.py,binary.py``,
``nn/layer/activation.py``). TPU-native: storage is
``jax.experimental.sparse`` BCOO/BCSR; matmuls lower to
``bcoo_dot_general`` (gather/scatter + MXU dots under XLA). Sparse
tensors interoperate with dense ``Tensor`` at the boundaries
(``to_dense``/``to_sparse_coo``); elementwise ops on matching sparsity
run on values directly.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.dispatch import unwrap
from ..core.tensor import Tensor


class SparseTensor:
    """Common surface of sparse COO/CSR wrappers (the DenseTensor-facade
    analog of ``SparseCooTensor``/``SparseCsrTensor``)."""

    def __init__(self, mat, shape):
        self._mat = mat
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._mat.dtype

    @property
    def nnz(self):
        return int(self._mat.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._mat.todense())

    def __repr__(self):
        return (f"{type(self).__name__}(shape={self._shape}, "
                f"nnz={self.nnz}, dtype={self.dtype})")


class SparseCooTensor(SparseTensor):
    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._mat.indices, 0, 1))

    def values(self) -> Tensor:
        return Tensor(self._mat.data)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def to_sparse_csr(self) -> "SparseCsrTensor":
        bcsr = jsparse.BCSR.from_bcoo(self._mat.sort_indices())
        return SparseCsrTensor(bcsr, self._shape)

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(
            self._mat.sum_duplicates(nse=self._mat.nse), self._shape)


class SparseCsrTensor(SparseTensor):
    def crows(self) -> Tensor:
        return Tensor(self._mat.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._mat.indices)

    def values(self) -> Tensor:
        return Tensor(self._mat.data)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def to_sparse_coo(self, sparse_dim=2) -> SparseCooTensor:
        return SparseCooTensor(self._mat.to_bcoo(), self._shape)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Reference ``sparse/creation.py sparse_coo_tensor``:
    indices [ndim, nnz], values [nnz]."""
    idx = jnp.asarray(unwrap(indices), jnp.int32)
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    mat = jsparse.BCOO((vals, jnp.swapaxes(idx, 0, 1)),
                       shape=tuple(int(s) for s in shape))
    return SparseCooTensor(mat, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Reference ``sparse/creation.py sparse_csr_tensor``."""
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    mat = jsparse.BCSR(
        (vals, jnp.asarray(unwrap(cols), jnp.int32),
         jnp.asarray(unwrap(crows), jnp.int32)),
        shape=tuple(int(s) for s in shape))
    return SparseCsrTensor(mat, shape)


def to_sparse_coo(x: Tensor, sparse_dim=None) -> SparseCooTensor:
    v = unwrap(x)
    n = int((v != 0).sum())
    return SparseCooTensor(jsparse.BCOO.fromdense(v, nse=max(n, 1)),
                           v.shape)


def to_sparse_csr(x: Tensor) -> SparseCsrTensor:
    return to_sparse_coo(x).to_sparse_csr()


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x._mat.to_bcoo()
    return x._mat


def _same_pattern(a, b):
    return (a.indices.shape == b.indices.shape and
            bool(jnp.all(a.indices == b.indices)))


def _binary(name, fn, x, y):
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        ma, mb = _coo(x).sort_indices(), _coo(y).sort_indices()
        if _same_pattern(ma, mb):
            out = jsparse.BCOO((fn(ma.data, mb.data), ma.indices),
                               shape=ma.shape)
            return SparseCooTensor(out, x._shape)
        # mismatched patterns: fall back through dense (reference kernels
        # require matched patterns for csr; coo merges)
        return to_sparse_coo(Tensor(fn(ma.todense(), mb.todense())))
    raise TypeError(f"sparse.{name} expects two sparse tensors")


def add(x, y):
    return _binary("add", jnp.add, x, y)


def subtract(x, y):
    return _binary("subtract", jnp.subtract, x, y)


def multiply(x, y):
    return _binary("multiply", jnp.multiply, x, y)


def divide(x, y):
    return _binary("divide", jnp.divide, x, y)


def matmul(x, y):
    """sparse @ dense (reference ``sparse/binary.py matmul``)."""
    if isinstance(x, SparseTensor):
        dense = unwrap(y) if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(_coo(x) @ dense)
    raise TypeError("sparse.matmul expects a sparse lhs")


def masked_matmul(x: Tensor, y: Tensor, mask: SparseCooTensor):
    """Reference ``sparse/binary.py masked_matmul``: (x @ y) sampled at
    mask's sparsity — lowers to bcoo_dot_general_sampled (SDDMM)."""
    out = jsparse.bcoo_dot_general_sampled(
        unwrap(x), unwrap(y), _coo(mask).indices,
        dimension_numbers=(((1,), (0,)), ((), ())))
    return SparseCooTensor(
        jsparse.BCOO((out, _coo(mask).indices), shape=mask._mat.shape),
        mask._shape)


def _unary(fn):
    def op(x):
        m = _coo(x)
        return SparseCooTensor(jsparse.BCOO((fn(m.data), m.indices),
                                            shape=m.shape), x._shape)
    return op


relu = _unary(lambda v: jnp.maximum(v, 0))
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)
sqrt = _unary(jnp.sqrt)
abs = _unary(jnp.abs)  # noqa: A001
neg = _unary(jnp.negative)
log1p = _unary(jnp.log1p)
expm1 = _unary(jnp.expm1)


class nn:
    """``paddle.sparse.nn`` activation layers."""

    class ReLU:
        def __call__(self, x):
            return relu(x)


__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "to_sparse_coo", "to_sparse_csr", "add",
    "subtract", "multiply", "divide", "matmul", "masked_matmul", "relu",
    "sin", "tanh", "sqrt", "abs", "neg", "log1p", "expm1", "nn",
]

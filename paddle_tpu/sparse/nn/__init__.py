"""``paddle.sparse.nn`` — layers over sparse tensors.

Capability analog of ``python/paddle/sparse/nn/layer/`` (conv.py:27
_Conv3D/_Conv2D + Conv3D/Conv2D/SubmConv3D/SubmConv2D, pooling.py:20
MaxPool3D, norm.py:24 BatchNorm, activation.py ReLU/ReLU6/LeakyReLU/
Softmax). TPU-shaped where it matters, honest where it doesn't: the
convolutions run the standard gather-GEMM-scatter rulebook (per-kernel-
offset index matching in numpy, channel GEMMs in jnp — the MXU work),
eager-only since the output nnz is data-dependent; activations and
BatchNorm act on the value array and jit-fuse."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ...core.tensor import Tensor
from ...nn.layer import Layer
from .. import SparseCooTensor, SparseCsrTensor, _coo
from . import functional  # noqa: F401
from .functional import (conv2d, conv3d, max_pool3d, subm_conv2d,
                         subm_conv3d)

__all__ = ["Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D", "MaxPool3D",
           "BatchNorm", "ReLU", "ReLU6", "LeakyReLU", "Softmax",
           "functional"]


class _ConvNd(Layer):
    def __init__(self, ndim, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None, subm=False):
        super().__init__()
        from ...nn import initializer as I
        if groups != 1:
            raise NotImplementedError("sparse conv: groups != 1")
        self._ndim = ndim
        self._subm = subm
        k = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else [kernel_size] * ndim
        self._kernel_size = [int(v) for v in k]
        s = stride if isinstance(stride, (list, tuple)) \
            else [stride] * ndim
        self._stride = [int(v) for v in s]
        p = padding if isinstance(padding, (list, tuple)) \
            else [padding] * ndim
        self._padding = [int(v) for v in p]
        d = dilation if isinstance(dilation, (list, tuple)) \
            else [dilation] * ndim
        self._dilation = [int(v) for v in d]
        self.weight = self.create_parameter(
            self._kernel_size + [in_channels, out_channels],
            attr=weight_attr, default_initializer=I.XavierUniform())
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        fn = {(3, False): conv3d, (3, True): subm_conv3d,
              (2, False): conv2d, (2, True): subm_conv2d}[
                  (self._ndim, self._subm)]
        return fn(x, self.weight, self.bias, stride=self._stride,
                  padding=self._padding, dilation=self._dilation)


class Conv3D(_ConvNd):
    """Reference ``sparse/nn/layer/conv.py:239``: input is a 5-D
    SparseCooTensor [N, D, H, W, C_in]."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(3, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, subm=False)


class SubmConv3D(_ConvNd):
    """Reference ``conv.py:509``: submanifold conv — output sites are
    exactly the input sites (no dilation of the active set)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(3, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, subm=True)


class Conv2D(_ConvNd):
    """Reference ``conv.py:374``: 4-D SparseCooTensor [N, H, W, C]."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(2, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, subm=False)


class SubmConv2D(_ConvNd):
    """Reference ``conv.py:649``."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(2, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, subm=True)


class MaxPool3D(Layer):
    """Reference ``sparse/nn/layer/pooling.py:20``."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        self._kernel_size = kernel_size
        self._stride = stride if stride is not None else kernel_size
        self._padding = padding

    def forward(self, x):
        return max_pool3d(x, self._kernel_size, self._stride,
                          self._padding)


class BatchNorm(Layer):
    """Reference ``sparse/nn/layer/norm.py:24``: BatchNorm over the
    channel (last) dim of the VALUES array — the active sites are the
    batch."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        m = _coo(x)
        out = self._bn(Tensor(m.data))
        data = out._read() if isinstance(out, Tensor) else out
        return SparseCooTensor(
            jsparse.BCOO((data, m.indices), shape=m.shape), x._shape)


def _values_layer(fn_builder):
    class _L(Layer):
        def __init__(self, *a, **kw):
            super().__init__()
            self._fn = fn_builder(*a, **kw)

        def forward(self, x):
            m = _coo(x)
            out = SparseCooTensor(
                jsparse.BCOO((self._fn(m.data), m.indices),
                             shape=m.shape), x._shape)
            if isinstance(x, SparseCsrTensor):
                return out.to_sparse_csr()
            return out
    return _L


ReLU = _values_layer(lambda name=None: lambda v: jnp.maximum(v, 0))
ReLU.__doc__ = "Reference ``sparse/nn/layer/activation.py:22``."
ReLU.__name__ = "ReLU"
ReLU6 = _values_layer(
    lambda name=None: lambda v: jnp.clip(v, 0.0, 6.0))
ReLU6.__name__ = "ReLU6"
LeakyReLU = _values_layer(
    lambda negative_slope=0.01, name=None:
    lambda v: jnp.where(v >= 0, v, negative_slope * v))
LeakyReLU.__name__ = "LeakyReLU"


class Softmax(Layer):
    """Reference ``activation.py:66``: softmax over the stored values of
    each row (zeros act as -inf), axis=-1 of a 2-D csr/coo matrix."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        if axis != -1:
            raise NotImplementedError("sparse Softmax: only axis=-1")

    def forward(self, x):
        csr = x if isinstance(x, SparseCsrTensor) else x.to_sparse_csr()
        indptr = np.asarray(csr._mat.indptr)
        vals = np.asarray(csr._mat.data, np.float64)
        out = np.empty_like(vals)
        for r in range(len(indptr) - 1):
            s, e = indptr[r], indptr[r + 1]
            if e > s:
                v = vals[s:e]
                v = np.exp(v - v.max())
                out[s:e] = v / v.sum()
        new = SparseCsrTensor(
            jsparse.BCSR((jnp.asarray(out, csr._mat.data.dtype),
                          csr._mat.indices, csr._mat.indptr),
                         shape=csr._mat.shape), csr._shape)
        return new if isinstance(x, SparseCsrTensor) \
            else new.to_sparse_coo()

"""``paddle.sparse.nn.functional`` — sparse conv/pool kernels.

Reference ``python/paddle/sparse/nn/functional/conv.py`` (conv3d,
subm_conv3d, conv2d, subm_conv2d) and ``pooling.py`` (max_pool3d); the
reference lowers to the phi gpu rulebook kernels
(``paddle/phi/kernels/sparse/gpu/conv_kernel.cu``). Here the rulebook
(per-kernel-offset matching of input sites to output sites) is built in
numpy — output nnz is data-dependent, so this is an eager-mode op family
like the reference's dygraph-only sparse API — and the per-offset
channel GEMMs + scatter-adds run in jnp, which is where the FLOPs are.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ...core.dispatch import unwrap
from ...core.tensor import Tensor
from .. import SparseCooTensor, _coo

__all__ = ["conv3d", "subm_conv3d", "conv2d", "subm_conv2d",
           "max_pool3d", "relu"]


def _norm(v, n):
    v = list(v) if isinstance(v, (list, tuple)) else [v] * n
    return [int(x) for x in v]


# rulebook cache for static sparsity: point-cloud workloads reuse one
# active-site pattern across many layers/steps, and rebuilding the
# python-loop matching per call dominated repeated-call cost (VERDICT
# r4 weak #8). Keyed by a digest of the indices + all geometry params;
# small LRU since each entry holds per-offset row arrays.
from collections import OrderedDict as _OD

_RB_CACHE: "_OD[tuple, tuple]" = _OD()
_RB_CACHE_MAX = 16


def _rulebook_cached(in_idx, spatial_in, kernel, stride, padding,
                     dilation, subm):
    import hashlib
    key = (hashlib.sha1(in_idx.tobytes()).hexdigest(), in_idx.shape,
           tuple(spatial_in), tuple(kernel), tuple(stride),
           tuple(padding), tuple(dilation), bool(subm))
    hit = _RB_CACHE.get(key)
    if hit is not None:
        _RB_CACHE.move_to_end(key)
        return hit
    out = _rulebook(in_idx, spatial_in, kernel, stride, padding,
                    dilation, subm)
    _RB_CACHE[key] = out
    if len(_RB_CACHE) > _RB_CACHE_MAX:
        _RB_CACHE.popitem(last=False)
    return out


def _rulebook(in_idx, spatial_in, kernel, stride, padding, dilation,
              subm):
    """Match input sites to output sites per kernel offset.

    Returns (out_idx [M, 1+nd], pairs: list over offsets of
    (in_rows, out_rows)). Coordinates are [n, *spatial]."""
    nd = len(kernel)
    if subm:
        # output sites == input sites; build a coord hash for lookup
        out_idx = in_idx
        key = {tuple(c): i for i, c in enumerate(map(tuple, in_idx))}
        spatial_out = list(spatial_in)
    else:
        spatial_out = [
            (spatial_in[d] + 2 * padding[d]
             - dilation[d] * (kernel[d] - 1) - 1) // stride[d] + 1
            for d in range(nd)]
        key = None

    offsets = np.stack(np.meshgrid(
        *[np.arange(k) for k in kernel], indexing="ij"),
        axis=-1).reshape(-1, nd)

    all_out = []
    raw_pairs = []
    for off in offsets:
        # out*stride = in + pad - off*dilation
        num = in_idx[:, 1:] + np.asarray(padding) \
            - off * np.asarray(dilation)
        ok = np.ones(len(in_idx), bool)
        for d in range(nd):
            ok &= (num[:, d] % stride[d] == 0)
        out_sp = num // np.asarray(stride)
        for d in range(nd):
            ok &= (out_sp[:, d] >= 0) & (out_sp[:, d]
                                         < (spatial_out[d]))
        rows = np.nonzero(ok)[0]
        oc = np.concatenate([in_idx[rows, :1], out_sp[rows]], axis=1)
        if subm:
            hit = np.array([key.get(tuple(c), -1) for c in oc],
                           np.int64)
            keep = hit >= 0
            raw_pairs.append((rows[keep], hit[keep]))
        else:
            raw_pairs.append((rows, oc))
            all_out.append(oc)

    if subm:
        return in_idx, raw_pairs, spatial_out
    if all_out:
        cat = np.concatenate(all_out, axis=0)
    else:
        cat = np.zeros((0, 1 + nd), np.int64)
    out_idx, inverse = np.unique(cat, axis=0, return_inverse=True)
    pairs = []
    pos = 0
    for rows, oc in raw_pairs:
        pairs.append((rows, inverse[pos:pos + len(rows)]))
        pos += len(rows)
    return out_idx, pairs, spatial_out


def _conv_impl(x, weight, bias, stride, padding, dilation, subm, nd):
    m = _coo(x).sum_duplicates(nse=_coo(x).nse)
    if m.n_dense != 1:
        raise ValueError(
            "sparse conv expects a SparseCooTensor with dense channel "
            "values: indices over [N, *spatial], values [nnz, C]")
    in_idx = np.asarray(m.indices, np.int64)
    vals = m.data                                   # (nnz, Cin) jnp
    w = unwrap(weight) if isinstance(weight, Tensor) \
        else jnp.asarray(weight)                    # (*K, Cin, Cout)
    kernel = list(w.shape[:nd])
    cin, cout = int(w.shape[nd]), int(w.shape[nd + 1])
    spatial_in = list(x._shape[1:1 + nd])
    stride = _norm(stride, nd)
    padding = _norm(padding, nd)
    dilation = _norm(dilation, nd)

    out_idx, pairs, spatial_out = _rulebook_cached(
        in_idx, spatial_in, kernel, stride, padding, dilation, subm)

    wflat = w.reshape(-1, cin, cout)
    out_vals = jnp.zeros((len(out_idx), cout), vals.dtype)
    for k, (in_rows, out_rows) in enumerate(pairs):
        if len(in_rows) == 0:
            continue
        contrib = vals[jnp.asarray(in_rows)] @ wflat[k]   # GEMM on MXU
        out_vals = out_vals.at[jnp.asarray(out_rows)].add(contrib)
    if bias is not None:
        b = unwrap(bias) if isinstance(bias, Tensor) \
            else jnp.asarray(bias)
        out_vals = out_vals + b

    shape = (x._shape[0], *spatial_out, cout)
    mat = jsparse.BCOO((out_vals, jnp.asarray(out_idx, jnp.int32)),
                       shape=shape)
    return SparseCooTensor(mat, shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """Reference ``sparse/nn/functional/conv.py conv3d``."""
    return _conv_impl(x, weight, bias, stride, padding, dilation,
                      subm=False, nd=3)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Reference ``subm_conv3d``: output sites == input sites."""
    return _conv_impl(x, weight, bias, stride, padding, dilation,
                      subm=True, nd=3)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NHWC", name=None):
    return _conv_impl(x, weight, bias, stride, padding, dilation,
                      subm=False, nd=2)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _conv_impl(x, weight, bias, stride, padding, dilation,
                      subm=True, nd=2)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Reference ``sparse/nn/functional/pooling.py max_pool3d``: max
    over the active sites in each window (inactive sites do not
    contribute zeros, matching the reference kernel)."""
    nd = 3
    kernel = _norm(kernel_size, nd)
    stride = _norm(stride if stride is not None else kernel_size, nd)
    padding = _norm(padding, nd)

    m = _coo(x).sum_duplicates(nse=_coo(x).nse)
    if m.n_dense != 1:
        raise ValueError("sparse max_pool3d expects values [nnz, C]")
    in_idx = np.asarray(m.indices, np.int64)
    vals = np.asarray(m.data)
    out_idx, pairs, spatial_out = _rulebook_cached(
        in_idx, list(x._shape[1:1 + nd]), kernel, stride, padding,
        [1] * nd, subm=False)

    out_vals = np.full((len(out_idx), vals.shape[1]), -np.inf,
                       vals.dtype)
    for in_rows, out_rows in pairs:
        if len(in_rows):
            np.maximum.at(out_vals, out_rows, vals[in_rows])
    shape = (x._shape[0], *spatial_out, vals.shape[1])
    mat = jsparse.BCOO((jnp.asarray(out_vals),
                        jnp.asarray(out_idx, jnp.int32)), shape=shape)
    return SparseCooTensor(mat, shape)


def relu(x, name=None):
    from .. import relu as _relu
    return _relu(x)

"""``paddle.quantization`` parity — QAT fake-quant, PTQ observers, and
weight-only int8 inference ops.

Capability analog of ``python/paddle/quantization/`` (QuantConfig
``config.py``, QAT ``qat.py``, PTQ ``ptq.py``, abs-max quanters
``quanters/abs_max.py``, observers ``observers/abs_max.py``) and the
``weight_quantize/weight_dequantize/weight_only_linear`` ops
(``paddle/phi/kernels/gpu/weight_only_linear_kernel.cu``).

TPU-native mechanics: fake-quant uses the straight-through estimator
expressed as ``x + stop_gradient(q(x) - x)`` on the tape (no custom
backward kernel needed); weight-only int8 stores per-channel abs-max
scales and dequantizes into the matmul, which XLA fuses into one HBM pass.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..nn.layer import Layer


# --- weight-only ops -------------------------------------------------------

def _pack_int4(q):
    """[in, out] int8 nibbles in [-8, 7] -> [ceil(in/2), out] int8 with
    row 2k in the low nibble and row 2k+1 in the high nibble (the
    2-values-per-byte layout of the reference's weight-only int4 GEMMs,
    ``paddle/phi/kernels/fusion/cutlass/``)."""
    if q.shape[0] % 2:
        q = jnp.pad(q, ((0, 1), (0, 0)))
    lo, hi = q[0::2], q[1::2]
    return (jnp.left_shift(hi, 4)
            | jnp.bitwise_and(lo, jnp.int8(0xF))).astype(jnp.int8)


def _unpack_int4(p, n_in):
    """Inverse of :func:`_pack_int4`; arithmetic shifts sign-extend the
    nibbles. XLA fuses this unpack + the scale multiply into the matmul
    read, so int4 weights cost half the int8 HBM traffic."""
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)
    hi = jnp.right_shift(p, 4)
    w = jnp.stack([lo, hi], axis=1).reshape(-1, p.shape[-1])
    return w[:n_in]


@primitive("weight_quantize")
def _weight_quantize_impl(w, algo="weight_only_int8"):
    if algo not in ("weight_only_int8", "abs_max", "weight_only_int4"):
        raise ValueError(f"unsupported algo {algo!r}")
    bits = 4 if algo == "weight_only_int4" else 8
    qmax = (1 << (bits - 1)) - 1
    scale = jnp.max(jnp.abs(w), axis=0) / qmax  # per out-channel [out]
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(w / scale), -qmax - 1, qmax).astype(jnp.int8)
    if algo == "weight_only_int4":
        q = _pack_int4(q)
    return q, scale.astype(jnp.float32)


def weight_quantize(w, algo="weight_only_int8"):
    """w: [in, out] float -> (quantized weights, [out] scales). int8:
    one int8 per value; int4: two nibbles per byte ([ceil(in/2), out]),
    matching the reference ``weight_quantize(..., algo="weight_only_int4")``
    (``python/paddle/nn/quant/quantized_linear.py``)."""
    return _weight_quantize_impl(w, algo=algo)


@primitive("weight_dequantize")
def weight_dequantize(qw, scale, algo="weight_only_int8",
                      out_dtype="float32", in_features=None):
    """``in_features`` (int4 only): unpadded input dim when the packed
    rows carry a pad nibble (odd in_features)."""
    from ..core.dtype import convert_dtype
    if algo == "weight_only_int4":
        qw = _unpack_int4(qw, in_features
                          if in_features is not None else 2 * qw.shape[0])
    return (qw.astype(jnp.float32) * scale).astype(
        convert_dtype(out_dtype) or jnp.float32)


@primitive("weight_only_linear")
def weight_only_linear(x, qweight, scale, bias=None,
                       weight_dtype="int8"):
    """y = x @ dequant(qweight) + bias; the dequant (and for int4 the
    nibble unpack) feeds the MXU matmul directly — one fused HBM pass
    under XLA at the quantized byte width."""
    if weight_dtype in ("int4", "weight_only_int4"):
        w = _unpack_int4(qweight, x.shape[-1]).astype(x.dtype) \
            * scale.astype(x.dtype)
    else:
        w = qweight.astype(x.dtype) * scale.astype(x.dtype)
    y = x @ w
    if bias is not None:
        y = y + bias
    return y


# --- fake quant (QAT) ------------------------------------------------------

def fake_quant(x, scale, bits=8):
    """Straight-through fake quantization on the tape."""
    from .. import ops
    qmax = float((1 << (bits - 1)) - 1)
    s = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(scale))
    q = ops.clip(ops.round(x / s * qmax), -qmax - 1, qmax) / qmax * s
    d = q - x
    d.stop_gradient = True  # STE: grad flows through x alone
    return x + d


class FakeQuanterWithAbsMaxObserver(Layer):
    """Reference ``quanters/abs_max.py`` — moving-average abs-max scale +
    fake quant in training; frozen scale in eval."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bits = bit_length
        self._scale = 1.0
        self._initialized = False

    def scale(self):
        return self._scale

    def forward(self, x):
        if self.training:
            cur = float(np.abs(np.asarray(x._read())).max()) or 1e-8
            if not self._initialized:
                self._scale = cur
                self._initialized = True
            else:
                r = self.moving_rate
                self._scale = r * self._scale + (1 - r) * cur
        return fake_quant(x, self._scale, self.bits)


class AbsmaxObserver(Layer):
    """Reference ``observers/abs_max.py`` — PTQ calibration observer:
    collects abs-max, passes activations through unchanged."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.bits = quant_bits
        self._max = 0.0

    def scale(self):
        qmax = float((1 << (self.bits - 1)) - 1)
        return (self._max or 1e-8) / qmax

    def forward(self, x):
        self._max = max(self._max,
                        float(np.abs(np.asarray(x._read())).max()))
        return x


class QuantConfig:
    """Reference ``config.py`` QuantConfig (global + per-layer rules)."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs: list = []

    def add_layer_config(self, layer=None, activation=None, weight=None):
        self._layer_configs.append((layer, activation, weight))

    def _factories_for(self, layer):
        for targets, act, wt in self._layer_configs:
            ts = targets if isinstance(targets, (list, tuple)) else [targets]
            if any(layer is t or isinstance(t, type) and isinstance(layer, t)
                   for t in ts):
                return act, wt
        return self.activation, self.weight


def _make(factory):
    if factory is None:
        return None
    if isinstance(factory, type):
        return factory()
    try:  # QuanterFactory-style: callable returning a quanter
        return factory()
    except TypeError:
        return factory


class QuantedLinear(Layer):
    """QAT wrapper for Linear (reference ``nn/quant/qat/linear.py``)."""

    def __init__(self, linear, act_quanter, weight_quanter):
        super().__init__()
        self.inner = linear
        self.act_q = act_quanter
        self.w_q = weight_quanter

    def forward(self, x):
        from ..nn import functional as F
        if self.act_q is not None:
            x = self.act_q(x)
        w = self.inner.weight
        if self.w_q is not None:
            w = self.w_q(w)
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, conv, act_quanter, weight_quanter):
        super().__init__()
        self.inner = conv
        self.act_q = act_quanter
        self.w_q = weight_quanter

    def forward(self, x):
        from ..nn import functional as F
        if self.act_q is not None:
            x = self.act_q(x)
        w = self.inner.weight
        if self.w_q is not None:
            w = self.w_q(w)
        return F.conv2d(x, w, self.inner.bias,
                        stride=self.inner._stride,
                        padding=self.inner._padding,
                        dilation=self.inner._dilation,
                        groups=self.inner._groups)


def _wrap_layers(model: Layer, config: QuantConfig, cls_map):
    from ..nn.layers import Conv2D, Linear
    for name, child in list(model._sub_layers.items()):
        if isinstance(child, Linear):
            act, wt = config._factories_for(child)
            model._sub_layers[name] = QuantedLinear(
                child, _make(act), _make(wt))
        elif isinstance(child, Conv2D):
            act, wt = config._factories_for(child)
            model._sub_layers[name] = QuantedConv2D(
                child, _make(act), _make(wt))
        else:
            _wrap_layers(child, config, cls_map)
    return model


class QAT:
    """Reference ``qat.py`` — quantize() wraps layers with fake-quant."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=True) -> Layer:
        return _wrap_layers(model, self.config, None)

    def convert(self, model: Layer, inplace=True) -> Layer:
        """Strip wrappers, baking nothing (fake-quant is simulation);
        reference convert() emits an inference program — ours returns the
        plain layers for jit.save."""
        for name, child in list(model._sub_layers.items()):
            if isinstance(child, (QuantedLinear, QuantedConv2D)):
                model._sub_layers[name] = child.inner
            else:
                self.convert(child)
        return model


class PTQ:
    """Reference ``ptq.py`` — observer insertion, calibration, convert."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=True) -> Layer:
        return _wrap_layers(model, self.config, None)

    convert = QAT.convert


__all__ = [
    "QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMaxObserver",
    "AbsmaxObserver", "QuantedLinear", "QuantedConv2D", "fake_quant",
    "weight_quantize", "weight_dequantize", "weight_only_linear",
]

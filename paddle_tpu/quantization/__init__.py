"""``paddle.quantization`` parity — QAT fake-quant, PTQ observers, and
weight-only int8 inference ops.

Capability analog of ``python/paddle/quantization/`` (QuantConfig
``config.py``, QAT ``qat.py``, PTQ ``ptq.py``, abs-max quanters
``quanters/abs_max.py``, observers ``observers/abs_max.py``) and the
``weight_quantize/weight_dequantize/weight_only_linear`` ops
(``paddle/phi/kernels/gpu/weight_only_linear_kernel.cu``).

TPU-native mechanics: fake-quant uses the straight-through estimator
expressed as ``x + stop_gradient(q(x) - x)`` on the tape (no custom
backward kernel needed); weight-only int8 routes through the Pallas
fused dequant-matmul (``ops/pallas/quant_matmul.py``) so the weights
are read at int8 width and the per-channel scale is applied after the
K reduction — one HBM pass at a quarter of the float bytes.

Quantized serving (ISSUE 7) additions:

* ``kv_quantize``/``kv_dequantize`` — the ONE home of the int8 KV-cache
  quantization arithmetic (per-(head, token-slot) absmax scales).  The
  serving engine's page pools, the ragged paged-attention kernel's
  in-DMA dequant, and the parity tests all import these, so the write
  path and the read path cannot drift.
* ``WeightOnlyLinear`` + ``weight_only_quantize(model)`` — swap a
  model's ``nn.Linear`` layers for int8-weight replicas whose forward
  is ``weight_only_linear``; ``models.generate`` and the continuous-
  batching engine then serve the quantized model through the fused
  kernel with no further changes (the decode bodies just call the
  installed layers).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..nn.layer import Layer


# --- weight-only ops -------------------------------------------------------

def _pack_int4(q):
    """[in, out] int8 nibbles in [-8, 7] -> [ceil(in/2), out] int8 with
    row 2k in the low nibble and row 2k+1 in the high nibble (the
    2-values-per-byte layout of the reference's weight-only int4 GEMMs,
    ``paddle/phi/kernels/fusion/cutlass/``)."""
    if q.shape[0] % 2:
        q = jnp.pad(q, ((0, 1), (0, 0)))
    lo, hi = q[0::2], q[1::2]
    return (jnp.left_shift(hi, 4)
            | jnp.bitwise_and(lo, jnp.int8(0xF))).astype(jnp.int8)


def _unpack_int4(p, n_in):
    """Inverse of :func:`_pack_int4`; arithmetic shifts sign-extend the
    nibbles. XLA fuses this unpack + the scale multiply into the matmul
    read, so int4 weights cost half the int8 HBM traffic.

    ``n_in`` must be recoverable from the packed rows (``2*rows`` or
    ``2*rows - 1`` — the odd case carries one pad nibble): anything
    else means the caller's ``in_features`` does not belong to this
    pack, and silently returning ``2*rows`` rows (the old behavior)
    hands back a weight matrix of the WRONG shape."""
    n_in = int(n_in)
    if not (0 < n_in <= 2 * p.shape[0]) or n_in < 2 * p.shape[0] - 1:
        raise ValueError(
            f"_unpack_int4: {p.shape[0]} packed rows hold "
            f"{2 * p.shape[0] - 1} or {2 * p.shape[0]} values, not "
            f"in_features={n_in}")
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)
    hi = jnp.right_shift(p, 4)
    w = jnp.stack([lo, hi], axis=1).reshape(-1, p.shape[-1])
    return w[:n_in]


@primitive("weight_quantize")
def _weight_quantize_impl(w, algo="weight_only_int8"):
    if algo not in ("weight_only_int8", "abs_max", "weight_only_int4"):
        raise ValueError(f"unsupported algo {algo!r}")
    bits = 4 if algo == "weight_only_int4" else 8
    qmax = (1 << (bits - 1)) - 1
    scale = jnp.max(jnp.abs(w), axis=0) / qmax  # per out-channel [out]
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(w / scale), -qmax - 1, qmax).astype(jnp.int8)
    if algo == "weight_only_int4":
        q = _pack_int4(q)
    return q, scale.astype(jnp.float32)


def weight_quantize(w, algo="weight_only_int8"):
    """w: [in, out] float -> (quantized weights, [out] scales). int8:
    one int8 per value; int4: two nibbles per byte ([ceil(in/2), out]),
    matching the reference ``weight_quantize(..., algo="weight_only_int4")``
    (``python/paddle/nn/quant/quantized_linear.py``)."""
    return _weight_quantize_impl(w, algo=algo)


@primitive("weight_dequantize")
def weight_dequantize(qw, scale, algo="weight_only_int8",
                      out_dtype="float32", in_features=None):
    """``in_features`` (int4 only): unpadded input dim when the packed
    rows carry a pad nibble (odd in_features)."""
    from ..core.dtype import convert_dtype
    if algo == "weight_only_int4":
        qw = _unpack_int4(qw, in_features
                          if in_features is not None else 2 * qw.shape[0])
    return (qw.astype(jnp.float32) * scale).astype(
        convert_dtype(out_dtype) or jnp.float32)


@primitive("weight_only_linear")
def weight_only_linear(x, qweight, scale, bias=None,
                       weight_dtype="int8"):
    """y = x @ dequant(qweight) + bias.  int8 routes through the Pallas
    fused dequant-matmul (``ops/pallas/quant_matmul.weight_only_matmul``:
    int8 weight reads, f32 accumulate, per-channel scale applied after
    the K reduction — one HBM pass at a quarter of the float bytes; the
    unjitted jnp twin serves CPU bitwise).  int4 unpacks nibbles into
    the matmul under XLA fusion as before (the packed layout's gather
    does not fit the blocked kernel's weight tiles)."""
    if weight_dtype in ("int4", "weight_only_int4"):
        w = _unpack_int4(qweight, x.shape[-1]).astype(x.dtype) \
            * scale.astype(x.dtype)
        y = x @ w
        if bias is not None:
            y = y + bias
        return y
    from ..ops.pallas.quant_matmul import weight_only_matmul
    return weight_only_matmul(x, qweight.astype(jnp.int8), scale,
                              bias=bias)


# --- int8 KV-cache quantization (serving) ----------------------------------
#
# The ONE home of the KV page-pool quantization arithmetic: the serving
# engine's write path (models/generation.ragged_paged_step /
# paged_slot_attention), the ragged paged-attention kernel's in-DMA
# dequant, and the parity tests all use these two functions, so the
# bytes written and the bytes the kernel reconstructs cannot drift.
#
# Granularity: one absmax scale per (kv head, token slot) — i.e. each
# page carries a small per-page scale VECTOR ([page_size] per head)
# riding in a side-pool indexed by the same block tables as the data
# page.  Per-slot scales keep quantization a pure function of that
# token's K/V vector: a page filled by one prefill chunk, by two
# chunked-prefill steps, or token-by-token by decode holds IDENTICAL
# bytes, which is what lets prefix-cache hits, COW copies and
# preempt-requeue restores stay exact under quantization (a single
# per-page scalar would force requantizing resident tokens on every
# decode append — write-history-dependent bytes and compounding error).

KV_QUANT_QMAX = 127.0


def kv_quantize(x):
    """[..., D] float K/V vectors -> (int8 [..., D], f32 scales [...]).
    Symmetric absmax per vector; all-zero vectors get scale 1 so the
    roundtrip stays exact."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    sc = jnp.where(amax > 0, amax / KV_QUANT_QMAX, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / sc[..., None]),
                 -KV_QUANT_QMAX, KV_QUANT_QMAX).astype(jnp.int8)
    return q, sc.astype(jnp.float32)


def kv_dequantize(q, sc):
    """Inverse of :func:`kv_quantize` (up to the int8 grid): int8
    [..., D] * f32 scales [...] -> f32 [..., D]."""
    return q.astype(jnp.float32) * sc.astype(jnp.float32)[..., None]


# --- fake quant (QAT) ------------------------------------------------------

def fake_quant(x, scale, bits=8):
    """Straight-through fake quantization on the tape."""
    from .. import ops
    qmax = float((1 << (bits - 1)) - 1)
    s = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(scale))
    q = ops.clip(ops.round(x / s * qmax), -qmax - 1, qmax) / qmax * s
    d = q - x
    d.stop_gradient = True  # STE: grad flows through x alone
    return x + d


class FakeQuanterWithAbsMaxObserver(Layer):
    """Reference ``quanters/abs_max.py`` — moving-average abs-max scale +
    fake quant in training; frozen scale in eval."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bits = bit_length
        self._scale = 1.0
        self._initialized = False

    def scale(self):
        return self._scale

    def forward(self, x):
        if self.training:
            cur = float(np.abs(np.asarray(x._read())).max()) or 1e-8
            if not self._initialized:
                self._scale = cur
                self._initialized = True
            else:
                r = self.moving_rate
                self._scale = r * self._scale + (1 - r) * cur
        return fake_quant(x, self._scale, self.bits)


class AbsmaxObserver(Layer):
    """Reference ``observers/abs_max.py`` — PTQ calibration observer:
    collects abs-max, passes activations through unchanged."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.bits = quant_bits
        self._max = 0.0

    def scale(self):
        qmax = float((1 << (self.bits - 1)) - 1)
        return (self._max or 1e-8) / qmax

    def forward(self, x):
        self._max = max(self._max,
                        float(np.abs(np.asarray(x._read())).max()))
        return x


class QuantConfig:
    """Reference ``config.py`` QuantConfig (global + per-layer rules)."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs: list = []

    def add_layer_config(self, layer=None, activation=None, weight=None):
        self._layer_configs.append((layer, activation, weight))

    def _factories_for(self, layer):
        for targets, act, wt in self._layer_configs:
            ts = targets if isinstance(targets, (list, tuple)) else [targets]
            if any(layer is t or isinstance(t, type) and isinstance(layer, t)
                   for t in ts):
                return act, wt
        return self.activation, self.weight


def _make(factory):
    if factory is None:
        return None
    if isinstance(factory, type):
        return factory()
    try:  # QuanterFactory-style: callable returning a quanter
        return factory()
    except TypeError:
        return factory


class QuantedLinear(Layer):
    """QAT wrapper for Linear (reference ``nn/quant/qat/linear.py``)."""

    def __init__(self, linear, act_quanter, weight_quanter):
        super().__init__()
        self.inner = linear
        self.act_q = act_quanter
        self.w_q = weight_quanter

    def forward(self, x):
        from ..nn import functional as F
        if self.act_q is not None:
            x = self.act_q(x)
        w = self.inner.weight
        if self.w_q is not None:
            w = self.w_q(w)
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, conv, act_quanter, weight_quanter):
        super().__init__()
        self.inner = conv
        self.act_q = act_quanter
        self.w_q = weight_quanter

    def forward(self, x):
        from ..nn import functional as F
        if self.act_q is not None:
            x = self.act_q(x)
        w = self.inner.weight
        if self.w_q is not None:
            w = self.w_q(w)
        return F.conv2d(x, w, self.inner.bias,
                        stride=self.inner._stride,
                        padding=self.inner._padding,
                        dilation=self.inner._dilation,
                        groups=self.inner._groups)


def _wrap_layers(model: Layer, config: QuantConfig, cls_map):
    from ..nn.layers import Conv2D, Linear
    for name, child in list(model._sub_layers.items()):
        if isinstance(child, Linear):
            act, wt = config._factories_for(child)
            model._sub_layers[name] = QuantedLinear(
                child, _make(act), _make(wt))
        elif isinstance(child, Conv2D):
            act, wt = config._factories_for(child)
            model._sub_layers[name] = QuantedConv2D(
                child, _make(act), _make(wt))
        else:
            _wrap_layers(child, config, cls_map)
    return model


class QAT:
    """Reference ``qat.py`` — quantize() wraps layers with fake-quant."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=True) -> Layer:
        return _wrap_layers(model, self.config, None)

    def convert(self, model: Layer, inplace=True) -> Layer:
        """Strip wrappers, baking nothing (fake-quant is simulation);
        reference convert() emits an inference program — ours returns the
        plain layers for jit.save."""
        for name, child in list(model._sub_layers.items()):
            if isinstance(child, (QuantedLinear, QuantedConv2D)):
                model._sub_layers[name] = child.inner
            else:
                self.convert(child)
        return model


class PTQ:
    """Reference ``ptq.py`` — observer insertion, calibration, convert."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=True) -> Layer:
        return _wrap_layers(model, self.config, None)

    convert = QAT.convert


# --- weight-only serving path ----------------------------------------------

class WeightOnlyLinear(Layer):
    """Inference replica of ``nn.Linear`` over pre-quantized int8/int4
    weights: forward is :func:`weight_only_linear`, i.e. the Pallas
    fused dequant-matmul for int8.  The quantized weight and scale are
    plain (non-parameter) tensors — an optimizer never sees them, and
    the jit capture funnel threads them like any other referenced
    tensor, so a swapped model serves through ``models.generate`` and
    the continuous-batching engine unchanged."""

    def __init__(self, linear, algo="weight_only_int8"):
        super().__init__()
        qw, scale = weight_quantize(linear.weight, algo=algo)
        self.qweight = qw
        self.scale = scale
        self.bias = linear.bias
        self.weight_dtype = ("int4" if algo == "weight_only_int4"
                             else "int8")
        self.in_features = int(linear.weight.shape[0])
        self.out_features = int(linear.weight.shape[1])

    def forward(self, x):
        return weight_only_linear(x, self.qweight, self.scale, self.bias,
                                  weight_dtype=self.weight_dtype)


def weight_only_quantize(model: Layer, algo="weight_only_int8",
                         min_features: int = 1) -> Layer:
    """Swap every ``nn.Linear`` under ``model`` (in place) for a
    :class:`WeightOnlyLinear` holding int8 (or packed int4) weights +
    per-out-channel scales — the ``models/`` weight-only generation
    path: the returned model's decode/prefill matmuls all route through
    the fused dequant-matmul kernel.  ``min_features`` skips layers
    whose input dim is below it (tiny projections gain nothing)."""
    from ..nn.layers import Linear
    for name, child in list(model._sub_layers.items()):
        if isinstance(child, Linear) \
                and child.weight.shape[0] >= min_features:
            model._sub_layers[name] = WeightOnlyLinear(child, algo=algo)
        else:
            weight_only_quantize(child, algo=algo,
                                 min_features=min_features)
    return model


__all__ = [
    "QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMaxObserver",
    "AbsmaxObserver", "QuantedLinear", "QuantedConv2D", "fake_quant",
    "weight_quantize", "weight_dequantize", "weight_only_linear",
    "kv_quantize", "kv_dequantize", "WeightOnlyLinear",
    "weight_only_quantize",
]

"""``paddle.profiler`` parity — scheduled profiling with chrome-trace export.

Capability analog of SURVEY C29 + the Python profiler API
(``python/paddle/profiler/profiler.py:346`` Profiler,
``utils.py`` RecordEvent, ``profiler_statistic.py`` summaries,
``chrometracing_logger.cc`` export). TPU-native split:

- HOST tracing is framework-owned: ``RecordEvent`` spans + automatic
  per-op dispatch events (a hook in ``core.dispatch``) land in a
  process-local buffer exported as chrome ``trace.json`` (load in
  ``chrome://tracing`` / Perfetto — same workflow as the reference).
- DEVICE tracing delegates to ``jax.profiler`` (XLA's tracer): when a
  device target is enabled the Profiler brackets the record window with
  ``jax.profiler.start_trace/stop_trace``, producing TensorBoard/Perfetto
  traces with per-HLO timing — the CUPTI analog on TPU.
- The wait/warmup/active scheduling model (``make_scheduler``,
  ``export_chrome_tracing``) matches the reference API.
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

from ..core import dispatch as _dispatch


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1          # accepted for API parity; maps to the device tracer
    CUSTOM_DEVICE = 2
    TPU = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int = 0, ready: int = 0, record: int = 1,
                   repeat: int = 0, skip_first: int = 0) -> Callable:
    """Reference ``profiler.py make_scheduler``: per-step state machine
    skip_first -> [closed -> ready -> record...] cycles."""
    period = closed + ready + record
    if record <= 0:
        raise ValueError("record span must be positive")

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        cycle = s // period
        if repeat and cycle >= repeat:
            return ProfilerState.CLOSED
        off = s % period
        if off < closed:
            return ProfilerState.CLOSED
        if off < closed + ready:
            return ProfilerState.READY
        if off == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_state_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None
                          ) -> Callable:
    """Reference ``profiler.py export_chrome_tracing`` handler."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof: "Profiler"):
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(
            dir_name, f"{name}_time_{int(time.time() * 1000)}"
                      f".paddle_trace.json")
        prof.export(path)

    return handler


class _HostEventBuffer:
    def __init__(self):
        self.events: list = []
        self.lock = threading.Lock()

    def add(self, name, ts, dur, tid, cat):
        with self.lock:
            self.events.append((name, ts, dur, tid, cat))

    def clear(self):
        with self.lock:
            self.events = []


_buffer = _HostEventBuffer()
_active_profiler: Optional["Profiler"] = None


class RecordEvent:
    """User-scope span (reference ``profiler/utils.py RecordEvent``); also
    forwards to jax.profiler's TraceAnnotation so the span shows up inside
    device traces."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None
        self._jax_ctx = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        try:
            import jax
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None

    def end(self):
        if self._t0 is None:
            return
        dur_us = (time.perf_counter_ns() - self._t0) // 1000
        if _active_profiler is not None and _active_profiler._recording:
            _buffer.add(self.name, self._t0 // 1000, dur_us,
                        threading.get_ident(), "user")
        # same stream as everything else (ISSUE 8): user spans land in
        # the observability event ring too, so chrome traces and flight
        # records tell one story
        from ..observability import events as _obs_events
        _obs_events.emit("span", name=self.name, dur_us=int(dur_us))
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
            self._jax_ctx = None
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def _op_profile_hook(name: str, t0_ns: int, t1_ns: int):
    dur_us = max((t1_ns - t0_ns) // 1000, 1)
    _buffer.add(name, t0_ns // 1000, dur_us,
                threading.get_ident(), "op")
    # per-op dispatch names feed the observability ring while a record
    # window is open — a flight record dumped during profiling shows
    # the exact dispatch sequence leading up to the failure
    from ..observability import events as _obs_events
    _obs_events.emit("op", name=name, dur_us=int(dur_us))


class Profiler:
    """Reference ``profiler.py:346``. Usage matches the reference:

        with profiler.Profiler(targets=[ProfilerTarget.CPU],
                               scheduler=(2, 5)) as p:
            for batch in loader:
                train_step(batch)
                p.step()
        p.summary()
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, with_flops: bool = False):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        if scheduler is None:
            self.scheduler = _default_state_scheduler
        elif isinstance(scheduler, tuple):
            start, end = scheduler
            self.scheduler = make_scheduler(closed=max(start, 0), ready=0,
                                            record=end - start, repeat=1,
                                            skip_first=0)
        else:
            self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._recording = False
        self._device_tracing = False
        self._trace_dir = None
        self._step_times: list = []
        self._t_step = None

    # -- lifecycle -----------------------------------------------------
    def start(self):
        global _active_profiler
        _active_profiler = self
        self.current_state = self.scheduler(self.step_num)
        self._apply_state()
        self._t_step = time.perf_counter()
        return self

    def stop(self):
        global _active_profiler
        try:
            if self._recording:
                self._stop_record()
                if self.on_trace_ready is not None:
                    self.on_trace_ready(self)
        finally:
            # a raising on_trace_ready handler must not leave the
            # profiler registered as active (the hook is already down:
            # _stop_record runs first and is unconditional)
            _active_profiler = None
            self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._t_step is not None:
            self._step_times.append((now - self._t_step, num_samples))
        self._t_step = now
        prev = self.current_state
        self.step_num += 1
        self.current_state = self.scheduler(self.step_num)
        if prev == ProfilerState.RECORD_AND_RETURN or (
                self._recording and
                self.current_state in (ProfilerState.CLOSED,
                                       ProfilerState.READY)):
            try:
                self._stop_record()
                if self.on_trace_ready is not None:
                    self.on_trace_ready(self)
            except BaseException:
                # fail safe: a raising trace handler leaves the bracket
                # DOWN (hook cleared, device tracer stopped) instead of
                # re-arming a window the caller will never close
                self.current_state = ProfilerState.CLOSED
                raise
        self._apply_state()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- internals -----------------------------------------------------
    def _apply_state(self):
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            if not self._recording:
                self._start_record()

    def _start_record(self):
        """Open a record window. Exception-safe bracket (ISSUE 8
        satellite): if anything raises mid-open — including a
        BaseException out of ``jax.profiler.start_trace`` that the
        Exception net below doesn't catch — the half-opened window is
        torn down before the error propagates, so the global dispatch
        hook and the device tracer can never outlive a failed start."""
        self._recording = True
        try:
            if not self.timer_only:
                _dispatch._profile_hook = _op_profile_hook
            if any(t in (ProfilerTarget.GPU, ProfilerTarget.TPU,
                         ProfilerTarget.CUSTOM_DEVICE)
                   for t in self.targets):
                try:
                    import jax
                    self._trace_dir = os.environ.get(
                        "PDTPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
                    jax.profiler.start_trace(self._trace_dir)
                    self._device_tracing = True
                except Exception:
                    self._device_tracing = False
        except BaseException:
            self._stop_record()
            raise

    def _stop_record(self):
        """Close the record window. The global hook comes down FIRST
        and unconditionally — a raising step inside a RECORD window
        exits through here (``__exit__`` -> ``stop``), and the one
        unrecoverable outcome would be the hook surviving to poison
        every later dispatch; ``jax.profiler.stop_trace`` runs under
        its own net for the same reason."""
        self._recording = False
        _dispatch._profile_hook = None
        if self._device_tracing:
            self._device_tracing = False
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass

    # -- output --------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        """Write collected host events as a chrome trace."""
        events = []
        pid = os.getpid()
        with _buffer.lock:
            snap = list(_buffer.events)
        for name, ts, dur, tid, cat in snap:
            events.append({"ph": "X", "name": name, "cat": cat,
                           "pid": pid, "tid": tid, "ts": ts, "dur": dur})
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return path

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregate host spans by name (the profiler_statistic analog).
        Returns the formatted table and prints it (reference behavior)."""
        agg: dict = {}
        with _buffer.lock:
            snap = list(_buffer.events)
        for name, ts, dur, tid, cat in snap:
            st = agg.setdefault(name, [0, 0, float("inf"), 0.0])
            st[0] += 1
            st[1] += dur
            st[2] = min(st[2], dur)
            st[3] = max(st[3], dur)
        scale = {"s": 1e6, "ms": 1e3, "us": 1.0}[time_unit]
        rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
                 f"{'Avg':>10}{'Min':>10}{'Max':>10}"]
        lines.append("-" * len(lines[0]))
        for name, (cnt, tot, mn, mx) in rows:
            lines.append(
                f"{name[:39]:<40}{cnt:>8}{tot / scale:>14.3f}"
                f"{tot / cnt / scale:>10.3f}{mn / scale:>10.3f}"
                f"{mx / scale:>10.3f}")
        table = "\n".join(lines)
        print(table)
        return table

    def benchmark(self):
        """Throughput info from step() timings (reference Timer analog)."""
        if not self._step_times:
            return {}
        times = [t for t, _ in self._step_times]
        samples = [s for _, s in self._step_times if s]
        out = {"steps": len(times),
               "avg_step_time": sum(times) / len(times),
               "min_step_time": min(times),
               "max_step_time": max(times)}
        if samples and len(samples) == len(times):
            out["ips"] = sum(samples) / sum(times)
        return out

    def reset(self):
        _buffer.clear()
        self._step_times = []


def load_profiler_result(filename: str):
    """Reference ``profiler.py load_profiler_result``."""
    with open(filename) as f:
        return json.load(f)


__all__ = [
    "Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
]

"""Automatic dy2static conversion: plain Python ``if``/``while``/``for``
on tensor predicates must compile into ONE program (no eager fallback),
matching the reference's transformer stack
(``python/paddle/jit/dy2static/transformers/ifelse_transformer.py``,
``loop_transformer.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _t(v, stop_gradient=True):
    t = paddle.to_tensor(np.asarray(v, np.float32))
    t.stop_gradient = stop_gradient
    return t


def _sf(fn):
    return fn if hasattr(fn, "_fallback_keys") else fn.__wrapped__


def test_plain_if_on_tensor_compiles():
    @paddle.jit.to_static
    def fn(x):
        if x.mean() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    x = _t([1.0, 2.0])
    np.testing.assert_allclose(fn(x).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(fn(_t([-1.0, -2.0])).numpy(), [-2.0, -3.0])
    np.testing.assert_allclose(fn(x).numpy(), [2.0, 4.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "plain if fell back to eager"
    assert len(sf._cache) == 1  # one program serves both branches


def test_plain_if_elif_else_chain():
    @paddle.jit.to_static
    def fn(x):
        if x.sum() > 10:
            y = x * 100.0
        elif x.sum() > 0:
            y = x * 10.0
        else:
            y = x
        return y

    np.testing.assert_allclose(fn(_t([6.0, 6.0])).numpy(), [600.0, 600.0])
    np.testing.assert_allclose(fn(_t([1.0, 1.0])).numpy(), [10.0, 10.0])
    np.testing.assert_allclose(fn(_t([-1.0, -1.0])).numpy(), [-1.0, -1.0])
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_plain_while_on_tensor_compiles():
    @paddle.jit.to_static
    def fn(x):
        with paddle.no_grad():
            i = paddle.to_tensor(np.float32(0.0))
            while i < 4:
                x = x * 2.0
                i = i + 1.0
        return x

    np.testing.assert_allclose(fn(_t([1.5])).numpy(), [24.0])
    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [16.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "plain while fell back"
    assert len(sf._cache) == 1


def test_plain_for_range_tensor_bound():
    @paddle.jit.to_static
    def fn(x, n):
        with paddle.no_grad():
            for _ in range(n):
                x = x + 1.0
        return x

    np.testing.assert_allclose(fn(_t([0.0]), _t(3)).numpy(), [3.0])
    np.testing.assert_allclose(fn(_t([0.0]), _t(5)).numpy(), [5.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "for range(tensor) fell back"
    assert len(sf._cache) == 1  # same program, different n


def test_bool_ops_in_predicate():
    @paddle.jit.to_static
    def fn(x, y):
        if x.sum() > 0 and y.sum() > 0:
            out = x + y
        else:
            out = x - y
        if not (x.sum() > 0):
            out = out * 10.0
        return out

    a, b = _t([1.0]), _t([2.0])
    np.testing.assert_allclose(fn(a, b).numpy(), [3.0])
    np.testing.assert_allclose(fn(a, _t([-2.0])).numpy(), [3.0])
    np.testing.assert_allclose(fn(_t([-1.0]), b).numpy(), [-30.0])
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_python_predicate_stays_python():
    # plain-Python condition: per-site python path, still compiles (the
    # branch is baked per cache key like before)
    @paddle.jit.to_static
    def fn(x, flag=True):
        if flag:
            return x * 2.0
        return x

    np.testing.assert_allclose(fn(_t([3.0])).numpy(), [6.0])
    sf = _sf(fn)
    assert not sf._fallback_keys


def test_grads_flow_through_converted_if():
    w = _t([2.0], stop_gradient=False)

    @paddle.jit.to_static
    def fn(x):
        w.clear_grad()
        if x.sum() > 0:
            y = (w * x).sum()
        else:
            y = (w * w * x).sum()
        y.backward()
        return y

    out = fn(_t([3.0]))
    np.testing.assert_allclose(out.numpy(), 6.0)
    np.testing.assert_allclose(w.grad.numpy(), [3.0])
    out = fn(_t([-3.0]))
    np.testing.assert_allclose(out.numpy(), -12.0)
    np.testing.assert_allclose(w.grad.numpy(), [-12.0])  # d(w^2 x)/dw=2wx
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_model_with_natural_branching_compiles():
    """The VERDICT acceptance shape: a model written with plain Python
    branching + a data-dependent loop compiles to one program."""

    class GatedNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 1)

        def forward(self, x):
            h = self.fc1(x)
            if h.mean() > 0:
                h = paddle.tanh(h)
            else:
                h = paddle.nn.functional.relu(h)
            return self.fc2(h)

    paddle.seed(0)
    net = GatedNet()
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=net.parameters())

    @paddle.jit.to_static
    def step(x, y):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)
    losses = []
    for _ in range(5):
        x = _t(rng.normal(size=(8, 4)))
        y = _t(rng.normal(size=(8, 1)))
        losses.append(float(step(x, y)))
    sf = _sf(step)
    assert not sf._fallback_keys, "model with natural branching fell back"
    assert len(sf._cache) == 1
    assert losses[-1] < losses[0]


def test_unconvertible_site_return_in_branch():
    # return inside a branch: site is left as plain Python. With a
    # python predicate everything still works end to end.
    @paddle.jit.to_static
    def fn(x, flag=True):
        if flag:
            return x + 1.0
        while x.sum() < 100:  # convertible site still converts
            x = x * 2.0
        return x

    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [2.0])


def test_nested_if_inside_while():
    @paddle.jit.to_static
    def fn(x):
        with paddle.no_grad():
            i = paddle.to_tensor(np.float32(0.0))
            while i < 3:
                if x.sum() > 0:
                    x = x + 1.0
                else:
                    x = x - 1.0
                i = i + 1.0
        return x

    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [4.0])
    np.testing.assert_allclose(fn(_t([-5.0])).numpy(), [-8.0])
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_eager_semantics_preserved():
    # the converted function must behave identically OUTSIDE capture
    from paddle_tpu.jit.dy2static import convert_function

    def orig(x, lo):
        total = 0.0
        for i in range(3):
            total = total + i
        if x > lo:
            y = "big"
        else:
            y = "small"
        while total < 10:
            total = total + 4
        return y, total

    conv = convert_function(orig)
    assert conv is not None
    assert conv(5, 1) == orig(5, 1) == ("big", 11.0)
    assert conv(0, 1) == orig(0, 1)


def test_convert_function_declines_gracefully():
    from paddle_tpu.jit.dy2static import convert_function

    def no_sites(x):
        return x + 1

    assert convert_function(no_sites) is None
    assert convert_function(len) is None  # builtin: no source

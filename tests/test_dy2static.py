"""Automatic dy2static conversion: plain Python ``if``/``while``/``for``
on tensor predicates must compile into ONE program (no eager fallback),
matching the reference's transformer stack
(``python/paddle/jit/dy2static/transformers/ifelse_transformer.py``,
``loop_transformer.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _t(v, stop_gradient=True):
    t = paddle.to_tensor(np.asarray(v, np.float32))
    t.stop_gradient = stop_gradient
    return t


def _sf(fn):
    return fn if hasattr(fn, "_fallback_keys") else fn.__wrapped__


def test_plain_if_on_tensor_compiles():
    @paddle.jit.to_static
    def fn(x):
        if x.mean() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    x = _t([1.0, 2.0])
    np.testing.assert_allclose(fn(x).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(fn(_t([-1.0, -2.0])).numpy(), [-2.0, -3.0])
    np.testing.assert_allclose(fn(x).numpy(), [2.0, 4.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "plain if fell back to eager"
    assert len(sf._cache) == 1  # one program serves both branches


def test_plain_if_elif_else_chain():
    @paddle.jit.to_static
    def fn(x):
        if x.sum() > 10:
            y = x * 100.0
        elif x.sum() > 0:
            y = x * 10.0
        else:
            y = x
        return y

    np.testing.assert_allclose(fn(_t([6.0, 6.0])).numpy(), [600.0, 600.0])
    np.testing.assert_allclose(fn(_t([1.0, 1.0])).numpy(), [10.0, 10.0])
    np.testing.assert_allclose(fn(_t([-1.0, -1.0])).numpy(), [-1.0, -1.0])
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_plain_while_on_tensor_compiles():
    @paddle.jit.to_static
    def fn(x):
        with paddle.no_grad():
            i = paddle.to_tensor(np.float32(0.0))
            while i < 4:
                x = x * 2.0
                i = i + 1.0
        return x

    np.testing.assert_allclose(fn(_t([1.5])).numpy(), [24.0])
    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [16.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "plain while fell back"
    assert len(sf._cache) == 1


def test_plain_for_range_tensor_bound():
    @paddle.jit.to_static
    def fn(x, n):
        with paddle.no_grad():
            for _ in range(n):
                x = x + 1.0
        return x

    np.testing.assert_allclose(fn(_t([0.0]), _t(3)).numpy(), [3.0])
    np.testing.assert_allclose(fn(_t([0.0]), _t(5)).numpy(), [5.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "for range(tensor) fell back"
    assert len(sf._cache) == 1  # same program, different n


def test_bool_ops_in_predicate():
    @paddle.jit.to_static
    def fn(x, y):
        if x.sum() > 0 and y.sum() > 0:
            out = x + y
        else:
            out = x - y
        if not (x.sum() > 0):
            out = out * 10.0
        return out

    a, b = _t([1.0]), _t([2.0])
    np.testing.assert_allclose(fn(a, b).numpy(), [3.0])
    np.testing.assert_allclose(fn(a, _t([-2.0])).numpy(), [3.0])
    np.testing.assert_allclose(fn(_t([-1.0]), b).numpy(), [-30.0])
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_python_predicate_stays_python():
    # plain-Python condition: per-site python path, still compiles (the
    # branch is baked per cache key like before)
    @paddle.jit.to_static
    def fn(x, flag=True):
        if flag:
            return x * 2.0
        return x

    np.testing.assert_allclose(fn(_t([3.0])).numpy(), [6.0])
    sf = _sf(fn)
    assert not sf._fallback_keys


def test_grads_flow_through_converted_if():
    w = _t([2.0], stop_gradient=False)

    @paddle.jit.to_static
    def fn(x):
        w.clear_grad()
        if x.sum() > 0:
            y = (w * x).sum()
        else:
            y = (w * w * x).sum()
        y.backward()
        return y

    out = fn(_t([3.0]))
    np.testing.assert_allclose(out.numpy(), 6.0)
    np.testing.assert_allclose(w.grad.numpy(), [3.0])
    out = fn(_t([-3.0]))
    np.testing.assert_allclose(out.numpy(), -12.0)
    np.testing.assert_allclose(w.grad.numpy(), [-12.0])  # d(w^2 x)/dw=2wx
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_model_with_natural_branching_compiles():
    """The VERDICT acceptance shape: a model written with plain Python
    branching + a data-dependent loop compiles to one program."""

    class GatedNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 1)

        def forward(self, x):
            h = self.fc1(x)
            if h.mean() > 0:
                h = paddle.tanh(h)
            else:
                h = paddle.nn.functional.relu(h)
            return self.fc2(h)

    paddle.seed(0)
    net = GatedNet()
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=net.parameters())

    @paddle.jit.to_static
    def step(x, y):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # ONE fixed batch stepped repeatedly: full-batch SGD descends
    # monotonically at this lr, so losses[-1] < losses[0] is a real
    # invariant (the old fresh-minibatch-per-step loop compared the
    # loss of two DIFFERENT random batches — a coin flip that failed
    # on this seed since the repo's seed commit)
    rng = np.random.default_rng(0)
    x = _t(rng.normal(size=(8, 4)))
    y = _t(rng.normal(size=(8, 1)))
    losses = [float(step(x, y)) for _ in range(5)]
    sf = _sf(step)
    assert not sf._fallback_keys, "model with natural branching fell back"
    assert len(sf._cache) == 1
    assert losses[-1] < losses[0]


def test_unconvertible_site_return_in_branch():
    # return inside a branch: site is left as plain Python. With a
    # python predicate everything still works end to end.
    @paddle.jit.to_static
    def fn(x, flag=True):
        if flag:
            return x + 1.0
        while x.sum() < 100:  # convertible site still converts
            x = x * 2.0
        return x

    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [2.0])


def test_nested_if_inside_while():
    @paddle.jit.to_static
    def fn(x):
        with paddle.no_grad():
            i = paddle.to_tensor(np.float32(0.0))
            while i < 3:
                if x.sum() > 0:
                    x = x + 1.0
                else:
                    x = x - 1.0
                i = i + 1.0
        return x

    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [4.0])
    np.testing.assert_allclose(fn(_t([-5.0])).numpy(), [-8.0])
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_eager_semantics_preserved():
    # the converted function must behave identically OUTSIDE capture
    from paddle_tpu.jit.dy2static import convert_function

    def orig(x, lo):
        total = 0.0
        for i in range(3):
            total = total + i
        if x > lo:
            y = "big"
        else:
            y = "small"
        while total < 10:
            total = total + 4
        return y, total

    conv = convert_function(orig)
    assert conv is not None
    assert conv(5, 1) == orig(5, 1) == ("big", 11.0)
    assert conv(0, 1) == orig(0, 1)


def test_convert_function_declines_gracefully():
    from paddle_tpu.jit.dy2static import convert_function

    def no_sites(x):
        return x + 1

    assert convert_function(no_sites) is None
    assert convert_function(len) is None  # builtin: no source


# ------------------------------------------------- escape conversion (r5) --

def test_while_with_break_compiles():
    # reference break_continue_transformer.py: break -> loop-condition
    # flag; the loop must still compile to ONE program
    @paddle.jit.to_static
    def fn(x):
        i = paddle.to_tensor(np.float32(0.0))
        while i < 10:
            x = x + 1.0
            i = i + 1.0
            if i >= 3:
                break
        return x

    np.testing.assert_allclose(fn(_t([0.0])).numpy(), [3.0])
    np.testing.assert_allclose(fn(_t([5.0])).numpy(), [8.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "while with break fell back"
    assert len(sf._cache) == 1


def test_while_with_continue_compiles():
    @paddle.jit.to_static
    def fn(x):
        total = x.sum() * 0.0
        i = paddle.to_tensor(np.float32(0.0))
        while i < 5:
            i = i + 1.0
            if i == 2:
                continue
            total = total + i
        return total

    # 1 + 3 + 4 + 5 (2 skipped)
    np.testing.assert_allclose(float(fn(_t([1.0]))), 13.0)
    sf = _sf(fn)
    assert not sf._fallback_keys, "while with continue fell back"


def test_for_range_with_break_compiles():
    @paddle.jit.to_static
    def fn(x):
        for i in range(10):
            x = x + 1.0
            if x.sum() > 4:
                break
        return x

    np.testing.assert_allclose(fn(_t([0.0])).numpy(), [5.0])
    np.testing.assert_allclose(fn(_t([100.0])).numpy(), [101.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "for-range with break fell back"


def test_early_return_in_branch_compiles():
    # reference return_transformer.py: early return -> retv/retf flags
    @paddle.jit.to_static
    def fn(x):
        if x.sum() > 0:
            return x * 2.0
        return x - 1.0

    np.testing.assert_allclose(fn(_t([1.0, 2.0])).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(fn(_t([-1.0, -2.0])).numpy(), [-2.0, -3.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "early return fell back"
    assert len(sf._cache) == 1


def test_return_inside_while_compiles():
    @paddle.jit.to_static
    def fn(x):
        i = paddle.to_tensor(np.float32(0.0))
        while i < 100:
            x = x * 2.0
            i = i + 1.0
            if x.sum() > 10:
                return x + 100.0
        return x

    # 1 -> 2 -> 4 -> 8 -> 16 (>10) -> +100
    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [116.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "return inside while fell back"


def test_python_pred_early_return_still_exact():
    # the round-4 decline case now converts; python flag predicates
    # must keep exact eager dispatch
    @paddle.jit.to_static
    def fn(x, flag=True):
        if flag:
            return x + 1.0
        while x.sum() < 100:
            x = x * 2.0
        return x

    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [2.0])
    np.testing.assert_allclose(fn(_t([3.0]), flag=False).numpy(), [192.0])
    sf = _sf(fn)
    assert not sf._fallback_keys


def test_tensor_iteration_compiles():
    # reference loop_transformer.py: `for x in tensor` iterates rows
    @paddle.jit.to_static
    def fn(x):
        acc = x.sum() * 0.0
        for row in x:
            acc = acc + row.max()
        return acc

    v = np.array([[1.0, 2.0], [30.0, 4.0], [5.0, 6.0]], np.float32)
    np.testing.assert_allclose(float(fn(paddle.to_tensor(v))), 38.0)
    sf = _sf(fn)
    assert not sf._fallback_keys, "tensor iteration fell back"


def test_for_each_python_iterable_unchanged():
    # the same syntax over a python list must stay plain python
    @paddle.jit.to_static
    def fn(x):
        for mult in [1.0, 2.0, 3.0]:
            x = x * mult
        return x

    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [6.0])


def test_eager_semantics_escape_forms():
    # converted functions must behave bit-for-bit eagerly, including
    # break/continue/early-return and loop-else-free mixes
    from paddle_tpu.jit.dy2static import convert_function

    def orig(n):
        total = 0
        for i in range(n):
            if i == 2:
                continue
            if i == 5:
                break
            total = total + i
        while total < 100:
            if total > 50:
                return ("mid", total)
            total = total + 30
        return ("end", total)

    conv = convert_function(orig)
    assert conv is not None
    for n in (0, 1, 3, 8, 100):
        assert conv(n) == orig(n), f"diverged at n={n}"


def test_eager_empty_tensor_style_loop_and_bare_return():
    from paddle_tpu.jit.dy2static import convert_function

    def orig(x):
        if x > 3:
            return
        return x * 2

    conv = convert_function(orig)
    assert conv is not None
    assert conv(5) is None and orig(5) is None
    assert conv(2) == orig(2) == 4


def test_for_range_with_return_and_continue_terminates():
    # code-review r5: the continue guard must not swallow the desugared
    # index increment on the return-elimination path (hang regression)
    from paddle_tpu.jit.dy2static import convert_function

    def orig(n, cap):
        total = 0
        for i in range(n):
            if i == 2:
                continue
            if total > cap:
                return ("cap", total)
            total = total + i
        return ("end", total)

    conv = convert_function(orig)
    assert conv is not None
    for n in (0, 3, 6, 10):
        for cap in (2, 100):
            assert conv(n, cap) == orig(n, cap)


def test_tensor_foreach_with_continue_terminates():
    @paddle.jit.to_static
    def fn(x):
        acc = x.sum() * 0.0
        for row in x:
            if row.max() > 10:
                continue
            acc = acc + row.max()
        return acc

    v = np.array([[1.0, 2.0], [30.0, 4.0], [5.0, 6.0]], np.float32)
    np.testing.assert_allclose(float(fn(paddle.to_tensor(v))), 8.0)
    sf = _sf(fn)
    assert not sf._fallback_keys


def test_fallthrough_under_traced_pred_returns_none():
    # code-review r5: `if cond: return y` with NO other return must give
    # None on the false path, not silently return zeros; the traced-pred
    # case falls back to eager (correct semantics beats compiledness)
    @paddle.jit.to_static
    def fn(x):
        if x.sum() > 0:
            return x * 2.0

    np.testing.assert_allclose(fn(_t([1.0])).numpy(), [2.0])
    assert fn(_t([-1.0])) is None


def test_tuple_target_for_with_return_stays_exact():
    # code-review r5 pass 2: a return inside a tuple-target for must not
    # be half-transformed (flags without prologue silently returned None)
    from paddle_tpu.jit.dy2static import convert_function

    def orig(items, base):
        acc = base + 0
        for k, v in items:
            if v > 6:
                return acc + v
        return acc

    conv = convert_function(orig)
    if conv is not None:
        assert conv([("a", 9)], 0) == orig([("a", 9)], 0) == 9
        assert conv([("a", 1)], 5) == orig([("a", 1)], 5) == 5


def test_return_in_try_inside_while_stays_exact():
    from paddle_tpu.jit.dy2static import convert_function

    def orig(n):
        i = 0
        while i < n:
            try:
                if i == 3:
                    return "found"
            except ValueError:
                pass
            i = i + 1
        return "end"

    conv = convert_function(orig)
    if conv is not None:
        assert conv(10) == orig(10) == "found"
        assert conv(2) == orig(2) == "end"


def test_tensor_foreach_with_continue_in_try_terminates():
    # code-review r5 pass 2: fragile continue must keep the original
    # python for (real continue + manual increment = infinite loop)
    @paddle.jit.to_static
    def fn(x):
        acc = 0.0
        for row in x:
            try:
                continue
            except ValueError:
                pass
            acc = acc + 1.0
        return paddle.to_tensor(np.float32(acc))

    v = np.array([[1.0], [2.0]], np.float32)
    assert float(fn(paddle.to_tensor(v))) == 0.0


# ------------------------------------------------- ADVICE r5 regressions --
def test_foreach_continue_with_later_return_terminates():
    """ADVICE r5 high: a tensor for-each whose body has BOTH `continue`
    and a later `return` hung — _eliminate_returns' continuation folding
    moved the desugared index increment inside the continue guard, so
    the index stopped advancing on continue iterations."""
    from paddle_tpu.jit.dy2static import convert_function

    def orig(xs):
        acc = paddle.to_tensor(0.0)
        for x in xs:
            if x.sum() < 0:
                continue
            acc = acc + x.sum()
            if acc > 100:
                return acc * 2
        return acc

    conv = convert_function(orig)
    assert conv is not None
    # continue fires on row 1, loop must still advance to row 2
    xs = paddle.to_tensor(np.array([[1., 2.], [-5., 1.], [3., 4.]],
                                   np.float32))
    np.testing.assert_allclose(conv(xs).numpy(), orig(xs).numpy())
    # the early-return path (and continue before it) stays correct
    xs2 = paddle.to_tensor(np.array([[60., 0.], [-1., -1.], [50., 0.]],
                                    np.float32))
    np.testing.assert_allclose(conv(xs2).numpy(), orig(xs2).numpy())
    # under capture too (the ADVICE repro hung both ways)
    st = paddle.jit.to_static(orig)
    np.testing.assert_allclose(st(xs).numpy(), orig(xs).numpy())


def test_run_while_counts_eager_prefix_against_trip_budget(monkeypatch):
    """ADVICE r5 low: iterations run eagerly before the predicate turns
    traced must be charged against max_trip_count — the lowered
    remainder gets the LEFTOVER budget, floored at 0."""
    from paddle_tpu.core import state
    from paddle_tpu.jit import dy2static as d2s
    from paddle_tpu.static import control_flow as cf

    seen = {}

    def fake_while(c, b, init, max_trip_count=None, _undef_fill=None):
        seen["mtc"] = max_trip_count
        return list(init)

    monkeypatch.setattr(cf, "while_loop", fake_while)
    monkeypatch.setattr(d2s, "_under_capture", lambda: True)

    def drive(mtc):
        st = {"i": 0}

        def cond():
            if st["i"] < 3:
                return True
            return paddle.to_tensor(True)     # predicate turns traced

        def body():
            st["i"] += 1

        d2s.run_while(cond, body, lambda: (st["i"],), lambda v: None,
                      max_trip_count=mtc)
        return st["i"]

    assert drive(10) == 3
    assert seen["mtc"] == 7                   # 10 - 3 eager trips
    assert drive(2) == 3                      # eager prefix still runs
    # floored at 1, NOT 0: static_while reads <= 0 as the scan-lowering
    # opt-out, which would UNBOUND the loop that exhausted its budget
    assert seen["mtc"] == 1
    drive(-1)
    assert seen["mtc"] == -1                  # explicit opt-out intact
    # implicit budget (the flag) is charged the same way
    drive(None)
    flag = int(state.get_flag("while_grad_max_trip_count"))
    assert seen["mtc"] == flag - 3

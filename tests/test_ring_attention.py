"""Ring attention (context parallelism) tests on the virtual 8-device mesh.

Parity model: ring attention is EXACT (online softmax), so outputs and
grads must match the single-program XLA attention to float tolerance —
the reference's sep-parallel tests assert the same loss-parity invariant
(test/collective/fleet pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn.functional as F


@pytest.fixture(scope="module")
def mesh():
    return dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "sp"])


def _rand_qkv(rng, B=4, S=32, H=4, D=16, HK=None):
    q = rng.normal(size=(B, S, H, D)).astype("float32")
    k = rng.normal(size=(B, S, HK or H, D)).astype("float32")
    v = rng.normal(size=(B, S, HK or H, D)).astype("float32")
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_forward_parity(mesh, causal):
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng)
    ref = F.scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        is_causal=causal, backend="xla")
    out = F.ring_flash_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        mesh=mesh, sp_axis="sp", batch_axes="dp", is_causal=causal)
    np.testing.assert_allclose(np.asarray(out._read()),
                               np.asarray(ref._read()), atol=2e-5)


def test_ring_gqa_parity(mesh):
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng, HK=2)
    ref = F.scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        is_causal=True, backend="xla")
    out = F.ring_flash_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        mesh=mesh, sp_axis="sp", batch_axes="dp", is_causal=True)
    np.testing.assert_allclose(np.asarray(out._read()),
                               np.asarray(ref._read()), atol=2e-5)


def test_ring_grad_parity(mesh):
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng)

    def run(fn):
        qt = paddle.to_tensor(q); qt.stop_gradient = False
        kt = paddle.to_tensor(k); kt.stop_gradient = False
        vt = paddle.to_tensor(v); vt.stop_gradient = False
        fn(qt, kt, vt).sum().backward()
        return [np.asarray(t.grad._read()) for t in (qt, kt, vt)]

    g_ring = run(lambda a, b, c: F.ring_flash_attention(
        a, b, c, mesh=mesh, sp_axis="sp", batch_axes="dp", is_causal=True))
    g_ref = run(lambda a, b, c: F.scaled_dot_product_attention(
        a, b, c, is_causal=True, backend="xla"))
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-5)


def test_gpt_context_parallel_step(mesh):
    """Full hybrid (dp x sp + ring attention) GPT training step under
    jit.to_static: loss must match the unsharded model's step."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, shard_gpt

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32, dropout=0.0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (4, 32)).astype(np.int32)
    labels = rng.integers(0, 64, (4, 32)).astype(np.int32)

    def steps(context_parallel):
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        if context_parallel:
            shard_gpt(model, mesh, dp_axis="dp", mp_axis="none",
                      sp_axis="sp", context_parallel=True)
        model.train()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())

        @paddle.jit.to_static
        def step(i, l):
            loss = model(i, l)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        if context_parallel:
            pl = [dist.Shard(0), dist.Shard(1)]
            mk = lambda a: dist.shard_tensor(a, mesh, pl)
        else:
            mk = paddle.to_tensor
        return [float(step(mk(ids), mk(labels))) for _ in range(3)]

    cp = steps(True)
    ref = steps(False)
    np.testing.assert_allclose(cp, ref, rtol=2e-4)

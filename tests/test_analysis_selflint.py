"""Self-lint gate: the graph-lint CLI runs over ``paddle_tpu/`` itself
in ``--strict`` mode (all registered checks are warn/note severity, so
the default error-only gate could never fire) and must come back with
zero warn-or-worse findings — the analyzer gates the repo's own code
from here on. The subsystem dirs that grew after the gate first landed
(``inference/``, ``resilience/``, ``observability/``) are pinned
explicitly so a future package re-layout cannot silently drop them from
the walk, and representative compiled programs are audited clean at the
IR level too (the whole-program analog of the source gate)."""
import os

import numpy as np
import pytest

from paddle_tpu.analysis import Severity, analyze_file
from paddle_tpu.analysis.__main__ import main

_PKG = os.path.join(os.path.dirname(__file__), os.pardir, "paddle_tpu")


def test_selflint_cli_strict_exits_zero(capsys):
    rc = main([_PKG, "--strict"])
    out = capsys.readouterr().out
    assert rc == 0, f"graph lint gates the repo:\n{out}"
    # the walk actually covered the package, not an empty dir
    summary = out.strip().splitlines()[-1]
    n_files = int(summary.split(" in ")[1].split()[0])
    assert n_files > 100, summary
    assert "(0 error, 0 warn," in summary, summary


def test_selflint_no_warn_or_error_findings_per_file():
    bad = []
    for root, dirs, files in os.walk(_PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            for d in analyze_file(path):
                if d.severity >= Severity.WARN:
                    bad.append(d.format())
    assert not bad, "\n".join(bad)


def test_readme_code_table_in_sync():
    """The README code table is generated from the registry — a stale
    block (new code registered, doc edited) fails here. Regenerate with
    ``python -m paddle_tpu.analysis --list-codes --format markdown``."""
    import re

    from paddle_tpu.analysis.__main__ import code_table_markdown
    readme = os.path.join(_PKG, os.pardir, "README.md")
    with open(readme) as f:
        text = f.read()
    m = re.search(r"<!-- BEGIN PDT CODE TABLE -->\n(.*?)\n"
                  r"<!-- END PDT CODE TABLE -->", text, re.S)
    assert m, "README PDT code-table markers missing"
    assert m.group(1) == code_table_markdown(), \
        "README code table is stale — regenerate from the registry"


@pytest.mark.parametrize("sub", ("inference", "resilience",
                                 "observability"))
def test_selflint_subsystem_dirs_covered_and_clean(sub, capsys):
    """The newer subsystem dirs stay under the strict gate in their own
    right — and the walk actually visits them (n_files > 0)."""
    rc = main([os.path.join(_PKG, sub), "--strict"])
    out = capsys.readouterr().out
    assert rc == 0, f"{sub}/ lint gates the repo:\n{out}"
    summary = out.strip().splitlines()[-1]
    assert int(summary.split(" in ")[1].split()[0]) > 0, summary
    assert "(0 error, 0 warn," in summary, summary


def test_program_audit_clean_on_representative_programs():
    """IR-level self-gate: a representative captured program (state
    capture + reduction, the train-step shape) audits with zero
    warn-or-worse whole-program findings."""
    import paddle_tpu as paddle
    from paddle_tpu import analysis

    w = paddle.to_tensor(np.ones((16,), np.float32))

    @paddle.jit.to_static
    def selflint_step(x):
        return (x * 2.0 + w.sum()).mean()

    with analysis.collect() as diags:
        selflint_step(paddle.to_tensor(np.ones((16,), np.float32)))
    bad = [d.format() for d in diags if d.severity >= Severity.WARN]
    assert not bad, "\n".join(bad)

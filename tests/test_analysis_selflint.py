"""Self-lint gate: the graph-lint CLI runs over ``paddle_tpu/`` itself
in ``--strict`` mode (all registered checks are warn/note severity, so
the default error-only gate could never fire) and must come back with
zero warn-or-worse findings — the analyzer gates the repo's own code
from here on."""
import os

from paddle_tpu.analysis import Severity, analyze_file
from paddle_tpu.analysis.__main__ import main

_PKG = os.path.join(os.path.dirname(__file__), os.pardir, "paddle_tpu")


def test_selflint_cli_strict_exits_zero(capsys):
    rc = main([_PKG, "--strict"])
    out = capsys.readouterr().out
    assert rc == 0, f"graph lint gates the repo:\n{out}"
    # the walk actually covered the package, not an empty dir
    summary = out.strip().splitlines()[-1]
    n_files = int(summary.split(" in ")[1].split()[0])
    assert n_files > 100, summary
    assert "(0 error, 0 warn," in summary, summary


def test_selflint_no_warn_or_error_findings_per_file():
    bad = []
    for root, dirs, files in os.walk(_PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            for d in analyze_file(path):
                if d.severity >= Severity.WARN:
                    bad.append(d.format())
    assert not bad, "\n".join(bad)

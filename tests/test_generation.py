"""KV-cache generation (greedy/temperature/nucleus) for the LM families.
Parity model: cached decoding must reproduce the no-cache full-forward
argmax sequence exactly."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import generate
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def _greedy_nocache(model, ids, steps):
    """Reference decoding: full forward each step, argmax last logits."""
    out = ids.copy()
    for _ in range(steps):
        with paddle.no_grad():
            logits = model(paddle.to_tensor(out)).numpy()
        nxt = logits[:, -1].argmax(-1).astype(out.dtype)
        out = np.concatenate([out, nxt[:, None]], axis=1)
    return out


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_cached_greedy_matches_full_forward(family):
    paddle.seed(0)
    if family == "gpt":
        model = GPTForCausalLM(GPTConfig(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=32, dropout=0.0))
    else:
        model = LlamaForCausalLM(LlamaConfig(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
            num_kv_heads=2, max_seq_len=32))
    model.eval()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 96, (2, 5)).astype(np.int32)

    got = generate(model, prompt, max_new_tokens=6).numpy()
    ref = _greedy_nocache(model, prompt, 6)
    np.testing.assert_array_equal(got, ref)


def test_generate_compiles_once():
    """The decode step must not retrace per token, and repeat calls with
    the same shapes must reuse the compiled program."""
    paddle.seed(1)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=32, dropout=0.0))
    model.eval()
    prompt = np.zeros((1, 3), np.int32)
    out = generate(model, prompt, max_new_tokens=8)
    assert tuple(out.shape) == (1, 11)
    step_fn = model._decode_step_cache[(1, 11, "dense", 0)]
    assert len(step_fn._cache) == 1  # one signature, one program
    exe = next(iter(step_fn._cache.values()))
    n = getattr(exe, "trace_count", 1)
    generate(model, prompt, max_new_tokens=8)  # second call: no retrace
    assert len(step_fn._cache) == 1
    assert getattr(exe, "trace_count", 1) == n


def test_top_p_and_eos():
    paddle.seed(2)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=32, dropout=0.0))
    model.eval()
    prompt = np.ones((2, 3), np.int32)
    out = generate(model, prompt, max_new_tokens=5, top_p=0.9,
                   seed=7).numpy()
    out2 = generate(model, prompt, max_new_tokens=5, top_p=0.9,
                    seed=7).numpy()
    np.testing.assert_array_equal(out, out2)  # seeded -> reproducible
    # eos stops early and pads with eos
    with paddle.no_grad():
        logits = model(paddle.to_tensor(prompt)).numpy()
    eos = int(logits[0, -1].argmax())  # first generated token = eos
    out3 = generate(model, prompt[:1], max_new_tokens=5,
                    eos_token_id=eos).numpy()
    assert out3.shape[1] <= 3 + 5
    assert out3[0, 3] == eos


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_paged_kv_cache_matches_dense(family):
    """Paged decode (page pool + block tables + Pallas paged kernel) must
    reproduce the dense-cache greedy sequence exactly."""
    paddle.seed(0)
    if family == "gpt":
        model = GPTForCausalLM(GPTConfig(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=64, dropout=0.0))
    else:
        model = LlamaForCausalLM(LlamaConfig(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
            num_kv_heads=2, max_seq_len=64))
    model.eval()
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 96, (2, 5)).astype(np.int32)

    dense = generate(model, prompt, max_new_tokens=9).numpy()
    # page_size=8 with max_len 14 -> 2 pages/seq, second partially filled
    paged = generate(model, prompt, max_new_tokens=9,
                     kv_cache="paged", page_size=8).numpy()
    np.testing.assert_array_equal(paged, dense)


@pytest.mark.parametrize("family,cache", [("gpt", "dense"),
                                          ("gpt", "paged"),
                                          ("llama", "dense"),
                                          ("llama", "paged")])
def test_batched_prefill_matches_token_by_token(family, cache):
    """One compiled whole-prompt prefill pass must reproduce the pure
    token-by-token sequence exactly, for both cache kinds.

    Numerics: on the CPU suite both paths run f32 XLA attention; the
    llama rope differs between f64-table (prefill, same as the training
    path) and traced-f32 (decode) angles — the identical low-order
    tolerance the long-standing cached-vs-full parity test relies on,
    so exact argmax equality holds at these scales."""
    paddle.seed(0)
    if family == "gpt":
        model = GPTForCausalLM(GPTConfig(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=64, dropout=0.0))
    else:
        model = LlamaForCausalLM(LlamaConfig(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
            num_kv_heads=2, max_seq_len=64))
    model.eval()
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, 96, (2, 13)).astype(np.int32)  # odd length:
    # paged pages (size 8) end mid-page after the prompt
    kw = dict(kv_cache=cache, page_size=8) if cache == "paged" else {}
    with_pf = generate(model, prompt, max_new_tokens=7, prefill=True,
                       **kw).numpy()
    without = generate(model, prompt, max_new_tokens=7, prefill=False,
                       **kw).numpy()
    np.testing.assert_array_equal(with_pf, without)


def test_decode_window_matches_scalar_dense_and_paged():
    """K-step scanned decode (one dispatch per K tokens, on-device
    sampling) must produce exactly the per-token greedy tokens, for both
    cache kinds (VERDICT r3 item 9)."""
    from paddle_tpu.models.generation import generate

    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=96, dropout=0.0)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, 97, (2, 7)).astype(np.int32)
    ref = generate(m, paddle.to_tensor(ids), max_new_tokens=21,
                   decode_window=1).numpy()
    for kv in ("dense", "paged"):
        win = generate(m, paddle.to_tensor(ids), max_new_tokens=21,
                       kv_cache=kv, decode_window=8).numpy()
        np.testing.assert_array_equal(win, ref)


def test_decode_window_eos_and_tail():
    from paddle_tpu.models.generation import generate

    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=96, dropout=0.0)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, 97, (2, 7)).astype(np.int32)
    ref = generate(m, paddle.to_tensor(ids), max_new_tokens=21,
                   decode_window=1).numpy()
    eos = int(ref[0, 8])
    re = generate(m, paddle.to_tensor(ids), max_new_tokens=21,
                  eos_token_id=eos, decode_window=1).numpy()
    we = generate(m, paddle.to_tensor(ids), max_new_tokens=21,
                  eos_token_id=eos, decode_window=8).numpy()
    # identical shape AND tokens: windowed eos truncation must land on
    # the same column as the scalar path
    assert we.shape == re.shape
    np.testing.assert_array_equal(re, we)
    # window larger than remaining tokens (tail window path)
    w = generate(m, paddle.to_tensor(ids), max_new_tokens=5,
                 decode_window=16).numpy()
    np.testing.assert_array_equal(w, ref[:, :12])

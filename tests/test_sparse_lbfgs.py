"""paddle.sparse + LBFGS tests (reference patterns:
``test/legacy_test/test_sparse_*_op.py``, ``test_lbfgs.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn

R = np.random.default_rng(13)


def _rand_sparse_dense(shape=(4, 6), density=0.3):
    dense = R.normal(size=shape).astype("float32")
    dense[R.uniform(size=shape) > density] = 0.0
    return dense


def test_coo_create_and_roundtrip():
    dense = _rand_sparse_dense()
    sp = paddle.sparse.to_sparse_coo(paddle.to_tensor(dense))
    assert sp.is_sparse_coo() and sp.nnz == int((dense != 0).sum())
    np.testing.assert_allclose(np.asarray(sp.to_dense()._read()), dense)
    # explicit construction
    idx = np.array([[0, 1, 2], [1, 2, 0]], "int64")
    vals = np.array([1.0, 2.0, 3.0], "float32")
    sp2 = paddle.sparse.sparse_coo_tensor(idx, vals, [3, 3])
    want = np.zeros((3, 3), "float32")
    want[idx[0], idx[1]] = vals
    np.testing.assert_allclose(np.asarray(sp2.to_dense()._read()), want)
    np.testing.assert_array_equal(np.asarray(sp2.indices()._read()), idx)
    np.testing.assert_allclose(np.asarray(sp2.values()._read()), vals)


def test_csr_create_and_convert():
    crows = np.array([0, 2, 3, 5], "int64")
    cols = np.array([1, 3, 2, 0, 1], "int64")
    vals = np.array([1, 2, 3, 4, 5], "float32")
    sp = paddle.sparse.sparse_csr_tensor(crows, cols, vals, [3, 4])
    assert sp.is_sparse_csr()
    want = np.zeros((3, 4), "float32")
    want[0, 1], want[0, 3], want[1, 2], want[2, 0], want[2, 1] = 1, 2, 3, 4, 5
    np.testing.assert_allclose(np.asarray(sp.to_dense()._read()), want)
    coo = sp.to_sparse_coo()
    np.testing.assert_allclose(np.asarray(coo.to_dense()._read()), want)
    back = coo.to_sparse_csr()
    np.testing.assert_allclose(np.asarray(back.to_dense()._read()), want)


def test_sparse_elementwise_and_unary():
    d = _rand_sparse_dense()
    a = paddle.sparse.to_sparse_coo(paddle.to_tensor(d))
    b = paddle.sparse.to_sparse_coo(paddle.to_tensor(d * 2))
    s = paddle.sparse.add(a, b)
    np.testing.assert_allclose(np.asarray(s.to_dense()._read()), d * 3,
                               atol=1e-6)
    m = paddle.sparse.multiply(a, b)
    np.testing.assert_allclose(np.asarray(m.to_dense()._read()),
                               d * d * 2, atol=1e-5)
    r = paddle.sparse.relu(a)
    np.testing.assert_allclose(np.asarray(r.to_dense()._read()),
                               np.maximum(d, 0), atol=1e-6)
    r2 = paddle.sparse.nn.ReLU()(a)
    np.testing.assert_allclose(np.asarray(r2.to_dense()._read()),
                               np.maximum(d, 0), atol=1e-6)


def test_sparse_matmul_and_masked_matmul():
    d = _rand_sparse_dense((5, 4))
    sp = paddle.sparse.to_sparse_coo(paddle.to_tensor(d))
    w = R.normal(size=(4, 3)).astype("float32")
    out = paddle.sparse.matmul(sp, paddle.to_tensor(w))
    np.testing.assert_allclose(np.asarray(out._read()), d @ w, atol=1e-5)

    mask = paddle.sparse.to_sparse_coo(paddle.to_tensor(
        _rand_sparse_dense((5, 3), 0.4)))
    x = R.normal(size=(5, 4)).astype("float32")
    y = R.normal(size=(4, 3)).astype("float32")
    got = paddle.sparse.masked_matmul(paddle.to_tensor(x),
                                      paddle.to_tensor(y), mask)
    full = x @ y
    want = np.where(np.asarray(mask.to_dense()._read()) != 0, full, 0)
    np.testing.assert_allclose(np.asarray(got.to_dense()._read()), want,
                               atol=1e-5)


def test_lbfgs_quadratic_converges():
    """LBFGS must solve a convex quadratic to high precision in a few
    steps (far beyond first-order SGD at the same budget)."""
    paddle.seed(0)
    A = R.normal(size=(6, 6)).astype("float32")
    A = (A @ A.T + 6 * np.eye(6)).astype("float32")
    b = R.normal(size=(6,)).astype("float32")
    x = paddle.to_tensor(np.zeros(6, "float32"))
    x.stop_gradient = False
    opt = paddle.optimizer.LBFGS(learning_rate=1.0, max_iter=30,
                                 line_search_fn="strong_wolfe",
                                 parameters=[x])

    At, bt = paddle.to_tensor(A), paddle.to_tensor(b)

    def closure():
        opt.clear_grad()
        loss = 0.5 * (x * (At @ x)).sum() - (bt * x).sum()
        loss.backward()
        return loss

    opt.step(closure)
    sol = np.linalg.solve(A, b)
    np.testing.assert_allclose(np.asarray(x._read()), sol, atol=1e-3)


def test_lbfgs_trains_small_net():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    xs = R.normal(size=(32, 4)).astype("float32")
    ys = (xs[:, :1] * 2 - xs[:, 1:2]).astype("float32")
    opt = paddle.optimizer.LBFGS(learning_rate=1.0, max_iter=10,
                                 line_search_fn="strong_wolfe",
                                 parameters=net.parameters())
    xt, yt = paddle.to_tensor(xs), paddle.to_tensor(ys)

    def closure():
        opt.clear_grad()
        loss = ((net(xt) - yt) ** 2).mean()
        loss.backward()
        return loss

    first = float(closure())
    for _ in range(3):
        last = opt.step(closure)
    assert last < first * 0.1, (first, last)

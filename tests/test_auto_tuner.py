"""Auto-tuner (SURVEY D21): candidate grid, pruning rules, trial loop
with real measured jit steps on the virtual 8-device mesh."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.auto_tuner import (AutoTuner,
                                               default_candidates, prune)


def test_candidate_grid_and_prune():
    cfg = {"num_gpus": 8, "global_batch_size": 16,
           "hidden_size": 64, "num_attention_heads": 4, "num_layers": 4,
           "sharding_stage": [0, 1], "use_recompute": [False]}
    cands = default_candidates(cfg)
    assert all(c["dp_degree"] * c["mp_degree"] * c["pp_degree"] == 8
               for c in cands)
    # mp=8 killed by heads%mp, pp=8 by layers%pp
    assert prune(cfg, {"dp_degree": 1, "mp_degree": 8, "pp_degree": 1,
                       "sharding_stage": 0, "micro_batch_size": 1}) == "mp"
    assert prune(cfg, {"dp_degree": 1, "mp_degree": 1, "pp_degree": 8,
                       "sharding_stage": 0, "micro_batch_size": 1}) == "pp"
    assert prune(cfg, {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                       "sharding_stage": 0, "micro_batch_size": 4}) is None


def test_memory_pruning():
    cfg = {"num_gpus": 8, "hidden_size": 2048, "num_layers": 24,
           "vocab_size": 50000, "max_mem_usage": 16e9}
    # pure dp: whole model + optimizer per chip -> far beyond 16GB
    assert prune(cfg, {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1,
                       "sharding_stage": 0,
                       "micro_batch_size": 1}) == "mem_estimation"
    # dp-sharded optimizer states fit
    assert prune(cfg, {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1,
                       "sharding_stage": 1,
                       "micro_batch_size": 1}) is None


def test_tuner_search_api():
    tuner = AutoTuner({"num_gpus": 4, "global_batch_size": 4,
                       "num_layers": 2, "hidden_size": 8})
    seen = []
    while (c := tuner.search_once()) is not None:
        c["step_time"] = 1.0 + len(seen) * 0.1
        tuner.add_cfg(c)
        seen.append(c)
    assert len(seen) == tuner.search_space_size
    assert tuner.best_cfg()["step_time"] == 1.0


def test_tune_measures_real_steps():
    """Trial-run a real jitted matmul train step per config; infeasible
    configs (simulated failure) are recorded, not fatal."""
    tuner = AutoTuner({"num_gpus": 8, "global_batch_size": 8,
                       "hidden_size": 32, "num_layers": 2,
                       "num_attention_heads": 4})

    def run_fn(cfg):
        if cfg["dp_degree"] == 8:
            raise MemoryError("simulated OOM")
        mesh = dist.ProcessMesh(
            np.arange(8).reshape(cfg["dp_degree"],
                                 8 // cfg["dp_degree"]), ["dp", "mp"])
        w = dist.shard_tensor(paddle.ones([32, 32]), mesh,
                              [dist.Replicate(), dist.Shard(1)])
        x = dist.shard_tensor(paddle.ones([8, 32]), mesh,
                              [dist.Shard(0), dist.Replicate()])

        @paddle.jit.to_static
        def step():
            return paddle.matmul(x, w).sum()

        return lambda: float(step())

    best = tuner.tune(run_fn, warmup=1, iters=2)
    assert best is not None and "step_time" in best
    errs = [h for h in tuner.history if "error" in h]
    assert errs and all("MemoryError" in h["error"] for h in errs)
    assert len(tuner.history) == tuner.search_space_size

"""nn.Layer / layers / functional tests.

Mirrors the reference test strategy (SURVEY §4): numpy-reference forward
checks + numeric gradient spot checks, run on the virtual CPU backend.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_matches_numpy():
    np.random.seed(0)
    x = np.random.randn(4, 8).astype("float32")
    l = nn.Linear(8, 3)
    out = l(pt.to_tensor(x))
    w = np.asarray(l.weight.numpy())
    b = np.asarray(l.bias.numpy())
    np.testing.assert_allclose(out.numpy(), x @ w + b, rtol=1e-5)


def test_conv2d_matches_scipy_style():
    x = np.random.randn(1, 2, 5, 5).astype("float32")
    conv = nn.Conv2D(2, 3, 3, padding=1)
    y = conv(pt.to_tensor(x))
    assert y.shape == [1, 3, 5, 5]
    # identity kernel check
    w = np.zeros((2, 2, 3, 3), dtype="float32")
    w[0, 0, 1, 1] = 1.0
    w[1, 1, 1, 1] = 1.0
    out = F.conv2d(pt.to_tensor(x), pt.to_tensor(w), None, 1, 1)
    np.testing.assert_allclose(out.numpy(), x, rtol=1e-5)


def test_conv2d_grad():
    x = pt.randn([2, 3, 8, 8])
    x.stop_gradient = False
    conv = nn.Conv2D(3, 4, 3)
    loss = conv(x).sum()
    loss.backward()
    assert x.grad.shape == [2, 3, 8, 8]
    assert conv.weight.grad.shape == [4, 3, 3, 3]


def test_conv_transpose_shape_inverts_conv():
    x = pt.randn([1, 4, 10, 10])
    down = nn.Conv2D(4, 8, 3, stride=2, padding=1)
    up = nn.Conv2DTranspose(8, 4, 3, stride=2, padding=1, output_padding=1)
    y = down(x)
    z = up(y)
    assert z.shape == [1, 4, 10, 10]


def test_pools():
    x = pt.to_tensor(np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    mp = F.max_pool2d(x, 2, 2)
    np.testing.assert_allclose(
        mp.numpy().ravel(), [5, 7, 13, 15])
    ap = F.avg_pool2d(x, 2, 2)
    np.testing.assert_allclose(
        ap.numpy().ravel(), [2.5, 4.5, 10.5, 12.5])
    aap = F.adaptive_avg_pool2d(x, 1)
    np.testing.assert_allclose(aap.numpy().ravel(), [7.5])


def test_batch_norm_train_and_eval():
    bn = nn.BatchNorm2D(3)
    x = pt.randn([8, 3, 4, 4])
    y = bn(x)
    m = y.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(3), atol=1e-5)
    # running stats moved toward batch stats
    assert not np.allclose(bn._mean.numpy(), np.zeros(3))
    bn.eval()
    y2 = bn(x)
    assert y2.shape == y.shape


def test_layer_norm_matches_numpy():
    x = np.random.randn(2, 3, 8).astype("float32")
    ln = nn.LayerNorm(8)
    y = ln(pt.to_tensor(x)).numpy()
    ref = (x - x.mean(-1, keepdims=True)) / np.sqrt(
        x.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_rms_norm():
    x = np.random.randn(2, 8).astype("float32")
    rn = nn.RMSNorm(8)
    y = rn(pt.to_tensor(x)).numpy()
    ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_cross_entropy_matches_numpy():
    logits = np.random.randn(5, 7).astype("float32")
    labels = np.random.randint(0, 7, 5)
    loss = F.cross_entropy(pt.to_tensor(logits),
                           pt.to_tensor(labels.astype("int64")))
    # numpy reference
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(5), labels]).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_cross_entropy_ignore_index_and_soft():
    logits = np.random.randn(6, 4).astype("float32")
    labels = np.array([0, 1, -100, 3, -100, 2])
    loss = F.cross_entropy(pt.to_tensor(logits),
                           pt.to_tensor(labels.astype("int64")),
                           ignore_index=-100)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    valid = labels != -100
    ref = -np.log(p[valid, labels[valid]]).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)
    # soft labels
    soft = np.random.dirichlet(np.ones(4), 6).astype("float32")
    loss2 = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(soft),
                            soft_label=True)
    ref2 = -(soft * np.log(p)).sum(-1).mean()
    np.testing.assert_allclose(float(loss2), ref2, rtol=1e-5)


def test_dropout_train_eval():
    x = pt.ones([1000])
    y = F.dropout(x, 0.5, training=True)
    # inverted dropout preserves expectation
    assert abs(float(y.numpy().mean()) - 1.0) < 0.2
    y2 = F.dropout(x, 0.5, training=False)
    np.testing.assert_allclose(y2.numpy(), np.ones(1000))


def test_activations():
    x = np.linspace(-3, 3, 13).astype("float32")
    t = pt.to_tensor(x)
    np.testing.assert_allclose(F.relu(t).numpy(), np.maximum(x, 0))
    np.testing.assert_allclose(F.sigmoid(t).numpy(), 1 / (1 + np.exp(-x)),
                               rtol=1e-5)
    np.testing.assert_allclose(F.silu(t).numpy(), x / (1 + np.exp(-x)),
                               rtol=1e-5)
    np.testing.assert_allclose(
        F.softmax(t).numpy(),
        np.exp(x - x.max()) / np.exp(x - x.max()).sum(), rtol=1e-5)


def test_embedding_and_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = pt.to_tensor(np.array([[1, 0, 3]], dtype="int64"))
    out = emb(idx)
    assert out.shape == [1, 3, 4]
    np.testing.assert_allclose(out.numpy()[0, 1], np.zeros(4))
    # gradient flows to weight
    loss = out.sum()
    loss.backward()
    assert emb.weight.grad is not None


def test_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(m1.state_dict())
    x = pt.randn([3, 4])
    np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_sequential_container_ops():
    m = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
    assert len(m) == 2
    assert isinstance(m[0], nn.Linear)
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(list(ll.parameters())) == 8


def test_hooks():
    l = nn.Linear(2, 2)
    calls = []
    h = l.register_forward_post_hook(
        lambda layer, inp, out: calls.append(1))
    l(pt.randn([1, 2]))
    assert calls == [1]
    h.remove()
    l(pt.randn([1, 2]))
    assert calls == [1]


def test_multihead_attention_and_transformer():
    mha = nn.MultiHeadAttention(16, 4)
    q = pt.randn([2, 5, 16])
    out = mha(q, q, q)
    assert out.shape == [2, 5, 16]
    enc = nn.TransformerEncoder(
        nn.TransformerEncoderLayer(16, 4, 32), 2)
    y = enc(q)
    assert y.shape == [2, 5, 16]


def test_sdpa_causal_matches_manual():
    np.random.seed(1)
    q = np.random.randn(1, 4, 2, 8).astype("float32")
    out = F.scaled_dot_product_attention(
        pt.to_tensor(q), pt.to_tensor(q), pt.to_tensor(q), is_causal=True)
    # manual reference
    qt = q.transpose(0, 2, 1, 3)  # b h s d
    logits = qt @ qt.transpose(0, 1, 3, 2) / np.sqrt(8)
    mask = np.tril(np.ones((4, 4))) > 0
    logits = np.where(mask, logits, -np.inf)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = (p @ qt).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_grad_clip_global_norm():
    from paddle_tpu.nn import ClipGradByGlobalNorm
    p = pt.Parameter(np.ones(4, dtype="float32"))
    g = pt.to_tensor(np.full(4, 10.0, dtype="float32"))
    clip = ClipGradByGlobalNorm(1.0)
    (_, g2), = clip([(p, g)])
    np.testing.assert_allclose(
        np.linalg.norm(g2.numpy()), 1.0, rtol=1e-5)


def test_interpolate():
    x = pt.to_tensor(np.arange(4, dtype="float32").reshape(1, 1, 2, 2))
    y = F.interpolate(x, size=[4, 4], mode="nearest")
    assert y.shape == [1, 1, 4, 4]
    y2 = F.interpolate(x, scale_factor=2, mode="bilinear")
    assert y2.shape == [1, 1, 4, 4]


def test_pad_modes():
    x = pt.to_tensor(np.arange(9, dtype="float32").reshape(1, 1, 3, 3))
    y = F.pad(x, [1, 1, 1, 1])
    assert y.shape == [1, 1, 5, 5]
    y2 = F.pad(x, [1, 1, 1, 1], mode="reflect")
    assert y2.shape == [1, 1, 5, 5]


def test_cross_entropy_fast_path_matches_logp_path():
    """The fused hard-label fast path (no [N, V] fp32 logp) must match the
    general log_softmax path in value AND gradient, incl. ignore_index."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    logits = rng.normal(size=(64, 37)).astype(np.float32)
    labels = rng.integers(0, 37, (64,))
    labels[::7] = -100  # ignore_index holes

    x = pt.to_tensor(logits, stop_gradient=False)
    y = pt.to_tensor(labels.astype(np.int64))
    loss = F.cross_entropy(x, y)   # fast path
    loss.backward()
    g_fast = x.grad.numpy()

    # reference: explicit log_softmax formulation
    def ref(lg):
        logp = jax.nn.log_softmax(lg, axis=-1)
        idx = jnp.where(labels == -100, 0, labels)
        nll = -jnp.take_along_axis(logp, idx[:, None], axis=-1)[:, 0]
        nll = jnp.where(labels == -100, 0.0, nll)
        return nll.sum() / jnp.maximum((labels != -100).sum(), 1)

    val_ref = float(ref(jnp.asarray(logits)))
    g_ref = np.asarray(jax.grad(ref)(jnp.asarray(logits)))
    np.testing.assert_allclose(float(loss), val_ref, rtol=1e-5)
    np.testing.assert_allclose(g_fast, g_ref, atol=1e-5)
    # smoothing/weights still take the general path and agree with it
    loss_s = F.cross_entropy(pt.to_tensor(logits), y,
                             label_smoothing=0.1)
    assert np.isfinite(float(loss_s))

"""Fused flash-attention BACKWARD parity suite (ISSUE 11).

Two contracts, the quant_matmul discipline:

1. BITWISE — the fused Pallas backward in interpret mode produces grads
   bit-identical to ``flash_attention_bwd_jnp``, the unjitted twin that
   replays the kernel's exact tile walk, on every tested geometry
   (causal x GQA x segment-ids x padded tails x rectangles x bf16).
2. ACCURATE — the same grads match ``jax.grad`` of the plain-XLA
   reference attention within tolerance (the twin being bit-faithful to
   a wrong kernel would pass contract 1 alone).

Everything is model-free and runs tiny shapes; the suite is pinned in
conftest's dense tier-1 window.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import flash_attention as fa


def _rand(shape, dtype=jnp.float32, seed=0, scale=0.3):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape) * scale, dtype)


def _pallas_bwd(q, k, v, do, causal, blocks, segment_ids=None):
    """Interpret-mode fused backward grads via the real custom_vjp, plus
    the forward residuals the twin needs."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    qt, kt, vt, dot = (jnp.swapaxes(x, 1, 2) for x in (q, k, v, do))
    seg_q = seg_k = None
    if segment_ids is not None:
        seg_q, seg_k = segment_ids
        seg_q = jnp.asarray(seg_q, jnp.int32)
        seg_k = jnp.asarray(seg_k, jnp.int32)
    o, vjp = jax.vjp(
        lambda a, b, c: fa._flash_bhsd(a, b, c, seg_q, seg_k, scale,
                                       causal, True, blocks, blocks),
        qt, kt, vt)
    dq, dk, dv = vjp(dot)
    _, lse = fa._fwd(qt, kt, vt, seg_q, seg_k, scale, causal, True, blocks)
    grads = tuple(jnp.swapaxes(g, 1, 2) for g in (dq, dk, dv))
    return grads, jnp.swapaxes(o, 1, 2), lse, scale


def _assert_bitwise(pallas_grads, twin_grads):
    for name, a, b in zip(("dq", "dk", "dv"), pallas_grads, twin_grads):
        assert a.dtype == b.dtype, name
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            f"{name} drifted from the jnp twin (max abs diff " \
            f"{np.abs(np.asarray(a, np.float64) - np.asarray(b, np.float64)).max():.3e})"


# geometry grid: (batch, hq, hk, sq, sk, d, causal, (bq, bk))
# - multi-block walks in both grid dims (the accumulate paths)
# - GQA head folding (rep > 1)
# - padded q and k tails (sq/sk not multiples of the blocks)
# - rectangles both ways (sk > sq streams extra k blocks; sq > sk has
#   rows that attend nothing — the hi<=0 flush clamp)
_GEOMETRIES = [
    pytest.param(2, 4, 4, 64, 64, 16, True, (16, 16), id="causal-multiblock"),
    pytest.param(2, 4, 4, 64, 64, 16, False, (16, 16), id="full-multiblock"),
    pytest.param(1, 4, 2, 50, 50, 8, True, (16, 16), id="gqa-padded-tail"),
    pytest.param(1, 6, 2, 40, 40, 8, False, (16, 16), id="gqa3-padded-full"),
    pytest.param(1, 2, 2, 32, 64, 8, True, (16, 32), id="rect-sk-long"),
    pytest.param(1, 2, 2, 64, 32, 8, True, (16, 16), id="rect-sq-long"),
    pytest.param(1, 2, 2, 48, 80, 8, True, (16, 32), id="asym-blocks"),
    pytest.param(1, 2, 2, 33, 47, 8, False, (16, 16), id="both-tails-padded"),
]


@pytest.mark.parametrize("b,hq,hk,sq,sk,d,causal,blocks", _GEOMETRIES)
def test_fused_bwd_bitwise_vs_twin(b, hq, hk, sq, sk, d, causal, blocks):
    q = _rand((b, sq, hq, d), seed=1)
    k = _rand((b, sk, hk, d), seed=2)
    v = _rand((b, sk, hk, d), seed=3)
    do = _rand((b, sq, hq, d), seed=4)
    grads, o, lse, scale = _pallas_bwd(q, k, v, do, causal, blocks)
    twin = fa.flash_attention_bwd_jnp(q, k, v, do, o, lse, scale=scale,
                                      causal=causal, blocks=blocks)
    _assert_bitwise(grads, twin)


@pytest.mark.parametrize("causal", [False, True])
def test_fused_bwd_bitwise_segments(causal):
    """Varlen/packed segments (q and kv id vectors differ in length)."""
    b, sq, sk, h, d = 1, 48, 64, 2, 8
    rng = np.random.default_rng(7)
    seg_q = np.sort(rng.integers(0, 3, (b, sq)), axis=1)
    seg_k = np.sort(rng.integers(0, 3, (b, sk)), axis=1)
    q = _rand((b, sq, h, d), seed=1)
    k = _rand((b, sk, h, d), seed=2)
    v = _rand((b, sk, h, d), seed=3)
    do = _rand((b, sq, h, d), seed=4)
    grads, o, lse, scale = _pallas_bwd(q, k, v, do, causal, (16, 16),
                                       segment_ids=(seg_q, seg_k))
    twin = fa.flash_attention_bwd_jnp(
        q, k, v, do, o, lse, scale=scale, causal=causal,
        segment_ids=(seg_q, seg_k), blocks=(16, 16))
    _assert_bitwise(grads, twin)


def test_fused_bwd_bitwise_bf16():
    """bf16 inputs: f32 in-kernel accumulation, one final cast — the
    cast order must match the twin bit-for-bit too."""
    q = _rand((1, 64, 2, 16), jnp.bfloat16, seed=1)
    k = _rand((1, 64, 2, 16), jnp.bfloat16, seed=2)
    v = _rand((1, 64, 2, 16), jnp.bfloat16, seed=3)
    do = _rand((1, 64, 2, 16), jnp.bfloat16, seed=4)
    grads, o, lse, scale = _pallas_bwd(q, k, v, do, True, (16, 16))
    assert grads[0].dtype == jnp.bfloat16
    twin = fa.flash_attention_bwd_jnp(q, k, v, do, o, lse, scale=scale,
                                      causal=True, blocks=(16, 16))
    _assert_bitwise(grads, twin)


def test_fused_bwd_bitwise_gqa_bf16_padded():
    """The union of the hard paths in one geometry: GQA head-sum, bf16
    casts, padded q tail, multi-k accumulation."""
    q = _rand((2, 50, 4, 8), jnp.bfloat16, seed=11)
    k = _rand((2, 50, 2, 8), jnp.bfloat16, seed=12)
    v = _rand((2, 50, 2, 8), jnp.bfloat16, seed=13)
    do = _rand((2, 50, 4, 8), jnp.bfloat16, seed=14)
    grads, o, lse, scale = _pallas_bwd(q, k, v, do, True, (16, 16))
    twin = fa.flash_attention_bwd_jnp(q, k, v, do, o, lse, scale=scale,
                                      causal=True, blocks=(16, 16))
    _assert_bitwise(grads, twin)


# ---------------------------------------------------------------- ref --
def _ref_sdpa(q, k, v, causal):
    from paddle_tpu.nn.functional.attention import _sdpa_xla
    return _sdpa_xla(q, k, v, causal=causal)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hq,hk", [(2, 2), (4, 2)])
def test_fused_bwd_matches_reference_grad(causal, hq, hk):
    """Fused backward vs jax.grad of the plain-XLA attention (the
    accuracy leg — bitwise-vs-twin alone can't catch a faithful replay
    of wrong math)."""
    q = _rand((1, 37, hq, 32), seed=4, scale=1.0)
    k = _rand((1, 37, hk, 32), seed=5, scale=1.0)
    v = _rand((1, 37, hk, 32), seed=6, scale=1.0)

    def loss_pl(q, k, v):
        o = fa.flash_attention(q, k, v, causal=causal, interpret=True,
                               blocks=(16, 16), bwd_blocks=(16, 16))
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_ref_sdpa(q, k, v, causal)))

    gp = jax.grad(loss_pl, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


def test_fused_bwd_distinct_blocks_same_values():
    """The bwd_blocks free parameter changes the tile walk, not the
    math: grads across block choices agree to accumulation-order
    tolerance, and each matches its own twin bitwise."""
    q = _rand((1, 64, 2, 16), seed=21)
    k = _rand((1, 64, 2, 16), seed=22)
    v = _rand((1, 64, 2, 16), seed=23)
    do = _rand((1, 64, 2, 16), seed=24)
    ref = None
    for blocks in ((16, 16), (32, 16), (16, 32), (64, 64)):
        grads, o, lse, scale = _pallas_bwd(q, k, v, do, True, blocks)
        twin = fa.flash_attention_bwd_jnp(q, k, v, do, o, lse,
                                          scale=scale, causal=True,
                                          blocks=blocks)
        _assert_bitwise(grads, twin)
        if ref is None:
            ref = grads
        else:
            for a, b in zip(ref, grads):
                np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_bwd_autotune_candidates_registered():
    """The flash_attention_bwd entry exists with backward-specific
    candidates bounded at 512 tiles (the vmem-footprint rationale) and
    the public API threads bwd_blocks through."""
    assert fa._TUNE_BWD_CANDIDATES
    assert max(c[0] for c in fa._TUNE_BWD_CANDIDATES) <= 512
    assert max(c[1] for c in fa._TUNE_BWD_CANDIDATES) <= 512
    # a cached winner under the entry is honored on a later call
    import paddle_tpu.ops.pallas.autotune as at
    key = f"{at._device_kind()}|flash_attention_bwd|b1h2sq512sk512d16c1"
    cache = at._load_cache()
    old = dict(cache)
    try:
        cache[key] = [256, 256]
        q = jnp.zeros((1, 2, 512, 16), jnp.float32)
        got = fa._autotuned_bwd_blocks(q, q, 0.25, True, None)
        assert got == (256, 256)
    finally:
        cache.clear()
        cache.update(old)

"""to_static capture/compile engine tests (jit module)."""
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer as opt


def _data(n=16, din=8, nclass=4, seed=0):
    rng = np.random.RandomState(seed)
    return (pt.to_tensor(rng.randn(n, din).astype("float32")),
            pt.to_tensor(rng.randint(0, nclass, n).astype("int64")))


def test_jit_matches_eager_training():
    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    optim = opt.Adam(1e-2, parameters=model.parameters())

    @pt.jit.to_static(full_graph=True)
    def step(x, y):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        optim.step()
        optim.clear_grad()
        return loss

    x, y = _data()
    jit_losses = [float(step(x, y)) for _ in range(10)]
    assert len(step._cache) == 1

    pt.seed(0)
    model2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    optim2 = opt.Adam(1e-2, parameters=model2.parameters())
    eager_losses = []
    for _ in range(10):
        loss = F.cross_entropy(model2(x), y)
        loss.backward()
        optim2.step()
        optim2.clear_grad()
        eager_losses.append(float(loss))
    np.testing.assert_allclose(jit_losses, eager_losses, rtol=2e-3,
                               atol=1e-6)


def test_jit_cache_per_shape():
    model = nn.Linear(4, 2)

    @pt.jit.to_static(full_graph=True)
    def fwd(x):
        return model(x)

    fwd(pt.randn([2, 4]))
    fwd(pt.randn([2, 4]))
    assert len(fwd._cache) == 1
    fwd(pt.randn([8, 4]))
    assert len(fwd._cache) == 2


def test_jit_graph_break_falls_back():
    @pt.jit.to_static
    def fn(x):
        if float(x.sum()) > 0:  # data-dependent python branch
            return x * 2
        return x * 3

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        a = fn(pt.ones([2]))
        b = fn(pt.ones([2]))
    np.testing.assert_allclose(a.numpy(), [2, 2])
    np.testing.assert_allclose(b.numpy(), [2, 2])
    # retry policy: counted, then pinned once the limit is exhausted
    assert fn._fallback_counts
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for _ in range(pt.jit.get_fallback_retry_limit()):
            fn(pt.ones([2]))
    assert fn._fallback_keys


def test_jit_rng_threads_through():
    """Dropout must produce different masks on each compiled call."""
    pt.seed(0)

    @pt.jit.to_static(full_graph=True)
    def f(x):
        return F.dropout(x, 0.5, training=True)

    x = pt.ones([64])
    a = f(x).numpy()
    b = f(x).numpy()
    c = f(x).numpy()
    assert not np.allclose(a, b) or not np.allclose(b, c)


def test_jit_lr_schedule_feeds_compiled_step():
    from paddle_tpu.optimizer.lr import StepDecay
    sched = StepDecay(0.1, step_size=1, gamma=0.1)
    w = pt.Parameter(np.zeros(1, dtype="float32"))
    o = opt.SGD(sched, parameters=[w])

    @pt.jit.to_static(full_graph=True)
    def step():
        loss = (w * 1.0).sum()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    step()
    np.testing.assert_allclose(w.numpy(), [-0.1], rtol=1e-6)
    sched.step()
    step()  # compiled path must see the NEW lr 0.01
    np.testing.assert_allclose(w.numpy(), [-0.11], rtol=1e-5)
    assert len(step._cache) == 1  # no recompilation for the lr change


def test_jit_train_eval_guard():
    model = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))

    @pt.jit.to_static(full_graph=True)
    def fwd(m, x):
        return m(x)

    x = pt.ones([4, 4])
    fwd(model, x)
    model.eval()
    out1 = fwd(model, x).numpy()
    out2 = fwd(model, x).numpy()
    np.testing.assert_allclose(out1, out2)  # eval: no dropout
    assert len(fwd._cache) == 2  # train + eval specializations


def test_jit_bn_stats_update():
    bn = nn.BatchNorm1D(4)

    @pt.jit.to_static(full_graph=True)
    def fwd(x):
        return bn(x)

    x = pt.randn([32, 4])
    m0 = bn._mean.numpy().copy()
    fwd(x)
    m1 = bn._mean.numpy().copy()
    fwd(x)
    m2 = bn._mean.numpy().copy()
    assert not np.allclose(m0, m1)
    assert not np.allclose(m1, m2)  # stats keep moving on compiled calls


def test_jit_multiple_outputs_and_nontensor():
    @pt.jit.to_static(full_graph=True)
    def f(x):
        return x + 1, x * 2, "tag"

    a, b, tag = f(pt.ones([3]))
    np.testing.assert_allclose(a.numpy(), [2, 2, 2])
    np.testing.assert_allclose(b.numpy(), [2, 2, 2])
    assert tag == "tag"
    a, b, tag = f(pt.zeros([3]))
    np.testing.assert_allclose(a.numpy(), [1, 1, 1])
    assert tag == "tag"


def test_jit_amp_step():
    import paddle_tpu.amp as amp
    pt.seed(3)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    optim = opt.AdamW(1e-2, parameters=model.parameters())

    @pt.jit.to_static(full_graph=True)
    def step(x, y):
        with amp.auto_cast(level="O1", dtype="bfloat16"):
            loss = F.cross_entropy(model(x), y)
        loss.backward()
        optim.step()
        optim.clear_grad()
        return loss

    x, y = _data(seed=3)
    losses = [float(step(x, y)) for _ in range(15)]
    assert losses[-1] < losses[0]


def test_no_silent_retrace_per_step():
    """Steady-state compiled steps must not retrace (VERDICT r1 weak #8):
    trace_count stays bounded while call count grows."""
    import paddle_tpu as paddle
    import numpy as np

    net = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())

    @paddle.jit.to_static
    def step(x):
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
    for _ in range(6):
        step(x)
    exe = list(step._cache.values())[0]
    # 1 capture trace (+1 tolerated sharding-stabilization retrace)
    assert exe.trace_count <= 2, f"retraced {exe.trace_count} times"


def test_multi_step_matches_sequential():
    """jit.multi_step: K steps in one scanned program == K dispatches."""
    import paddle_tpu.nn as nn
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    lossf = nn.CrossEntropyLoss()
    rng = np.random.default_rng(0)
    batches = [(pt.to_tensor(rng.normal(size=(4, 8)).astype("float32")),
                pt.to_tensor(rng.integers(0, 2, (4,)).astype("int64")))
               for _ in range(5)]
    sd = {k: np.asarray(v._read()).copy()
          for k, v in net.state_dict().items()}

    def make_step():
        optim = opt.Adam(learning_rate=1e-2,
                         parameters=net.parameters())

        @pt.jit.to_static
        def step(x, y):
            loss = lossf(net(x), y)
            loss.backward()
            optim.step()
            optim.clear_grad()
            return loss
        return step

    step = make_step()
    ref = [float(step(*b)) for b in batches]
    ref_params = {k: np.asarray(v._read()).copy()
                  for k, v in net.state_dict().items()}

    for k, v in net.state_dict().items():
        v._write(sd[k])
    outs = pt.jit.multi_step(make_step(), batches)
    np.testing.assert_allclose([float(o) for o in outs], ref, rtol=1e-5)
    for k, v in net.state_dict().items():
        np.testing.assert_allclose(np.asarray(v._read()), ref_params[k],
                                   atol=1e-6)


def test_window_runner_matches_sequential():
    """jit.WindowRunner: all K steps in ONE dispatch == K dispatches."""
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    lossf = nn.CrossEntropyLoss()
    rng = np.random.default_rng(0)
    warm, *batches = [
        (pt.to_tensor(rng.normal(size=(4, 8)).astype("float32")),
         pt.to_tensor(rng.integers(0, 2, (4,)).astype("int64")))
        for _ in range(6)]
    sd = {k: np.asarray(v._read()).copy()
          for k, v in net.state_dict().items()}

    def make_step():
        optim = opt.Adam(learning_rate=1e-2,
                         parameters=net.parameters())

        @pt.jit.to_static
        def step(x, y):
            loss = lossf(net(x), y)
            loss.backward()
            optim.step()
            optim.clear_grad()
            return loss
        return step

    step = make_step()
    step(*warm)
    ref = [float(step(*b)) for b in batches]
    ref_params = {k: np.asarray(v._read()).copy()
                  for k, v in net.state_dict().items()}
    # sequential continuation over the same batches again: the reference
    # for the second window launched below
    ref2 = [float(step(*b)) for b in batches]

    for k, v in net.state_dict().items():
        v._write(sd[k])
    step2 = make_step()
    step2(*warm)  # compile + the same warmup mutation as the ref run
    w = pt.jit.WindowRunner(step2, batches[0], length=len(batches))
    stacks = w.stage(batches)
    outs = w.run(*stacks)
    np.testing.assert_allclose([float(o) for o in outs], ref, rtol=1e-5)
    for k, v in net.state_dict().items():
        np.testing.assert_allclose(np.asarray(v._read()), ref_params[k],
                                   atol=1e-6)
    # outputs="last" on a fresh window continues from the updated state:
    # it must reproduce the final loss of the sequential continuation
    last = w.run(*stacks, outputs="last")
    np.testing.assert_allclose(float(last), ref2[-1], rtol=1e-5)


def test_window_runner_per_step_lr_matches_sequential():
    """A scheduler-driven LR fed per-step into the scanned window
    (WindowRunner per_step + optimizer.lr_window) reproduces sequential
    training where scheduler.step() runs after every batch — the case a
    per-launch host sync gets wrong (LR frozen across the window)."""
    rng = np.random.default_rng(0)
    warm, *batches = [
        (pt.to_tensor(rng.normal(size=(4, 8)).astype("float32")),
         pt.to_tensor(rng.integers(0, 2, (4,)).astype("int64")))
        for _ in range(7)]
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    lossf = nn.CrossEntropyLoss()
    sd = {k: np.asarray(v._read()).copy()
          for k, v in net.state_dict().items()}

    def make(sched_cls):
        sched = sched_cls(learning_rate=0.05, warmup_steps=4,
                          start_lr=0.001, end_lr=0.05)
        optim = opt.SGD(learning_rate=sched, parameters=net.parameters())

        @pt.jit.to_static
        def step(x, y):
            loss = lossf(net(x), y)
            loss.backward()
            optim.step()
            optim.clear_grad()
            return loss
        return step, optim, sched

    from paddle_tpu.optimizer.lr import LinearWarmup

    # reference: one dispatch per step, scheduler.step() after each
    step, optim, sched = make(LinearWarmup)
    step(*warm); sched.step()
    for b in batches:
        step(*b)
        sched.step()
    ref = {k: np.asarray(v._read()).copy()
           for k, v in net.state_dict().items()}

    # windowed: same schedule fed per-step into one scanned launch
    for k, v in net.state_dict().items():
        v._write(sd[k])
    step2, optim2, sched2 = make(LinearWarmup)
    step2(*warm); sched2.step()
    w = pt.jit.WindowRunner(step2, batches[0], length=len(batches),
                            per_step=[optim2.lr_var])
    lrs = optim2.lr_window(len(batches))
    assert lrs[0] != lrs[-1], "warmup should vary inside the window"
    w.run(*w.stage(batches), per_step_vals=[lrs], outputs="last")
    for k, v in net.state_dict().items():
        np.testing.assert_allclose(np.asarray(v._read()), ref[k],
                                   atol=1e-6, err_msg=k)


def test_window_runner_donate_false_reuses_carry():
    """donate=False keeps the pre-window state buffers valid — the same
    staged window can be re-run from a manually restored state."""
    pt.seed(1)
    net = nn.Linear(4, 2)
    optim = opt.SGD(learning_rate=0.1, parameters=net.parameters())

    @pt.jit.to_static
    def step(x, y):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        optim.step()
        optim.clear_grad()
        return loss

    rng = np.random.default_rng(1)
    batches = [(pt.to_tensor(rng.normal(size=(4, 4)).astype("float32")),
                pt.to_tensor(rng.normal(size=(4, 2)).astype("float32")))
               for _ in range(4)]
    step(*batches[0])  # compile/warm
    # retain the PRE-WINDOW device arrays themselves (no host copy):
    # with donation the window launch consumes these exact buffers and
    # reusing them afterwards raises a deleted-buffer error; donate=False
    # must keep them valid for restore-and-replay
    snap = {k: v._read() for k, v in net.state_dict().items()}

    w = pt.jit.WindowRunner(step, batches[0], length=4, donate=False)
    stacks = w.stage(batches)
    l1 = float(w.run(*stacks, outputs="last"))
    after1 = {k: np.asarray(v._read()).copy()
              for k, v in net.state_dict().items()}
    for k, v in net.state_dict().items():
        # through the write funnel: with the fused optimizer the params
        # are flat-bucket views and a raw _data poke would be invisible
        v._write(snap[k])
        v._node = None
    l2 = float(w.run(*stacks, outputs="last"))
    np.testing.assert_allclose(l1, l2, rtol=1e-6)
    for k, v in net.state_dict().items():
        np.testing.assert_allclose(np.asarray(v._read()), after1[k],
                                   rtol=1e-6)


def test_transformer_saveable_policy_grad_parity():
    # named-activation remat (ln_out/act_out saved) must give the same
    # loss and grads as full recompute
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.fleet.recompute import recompute

    paddle.seed(0)
    lin1 = paddle.nn.Linear(8, 16)
    ln = paddle.nn.LayerNorm(16)
    lin2 = paddle.nn.Linear(16, 8)

    def block(x):
        return lin2(F.gelu(ln(lin1(x)), approximate=True))

    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32))

    losses, grads = [], []
    for policy in (None, "transformer_saveable"):
        for p in [*lin1.parameters(), *ln.parameters(),
                  *lin2.parameters()]:
            p._grad = None

        @paddle.jit.to_static
        def step(v):
            out = recompute(block, v, policy=policy)
            loss = (out * out).mean()
            loss.backward()
            return loss

        losses.append(float(step(x)))
        grads.append(np.asarray(lin1.weight.grad._read()).copy())
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)
    np.testing.assert_allclose(grads[0], grads[1], rtol=1e-4, atol=1e-6)

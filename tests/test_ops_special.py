"""Op-surface completion batch 2 (reference ops.yaml rows): special
functions, sampling, linalg completions, sequence/beam ops, losses
(huber/hsigmoid/rnnt), max_unpool2d, metric.accuracy, detection ops."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_special_functions():
    assert abs(float(paddle.gammaln(paddle.to_tensor([5.0])).numpy()[0])
               - np.log(24)) < 1e-4
    assert abs(float(paddle.gammaincc(paddle.to_tensor([2.0]),
                                      paddle.to_tensor([0.5])).numpy()[0])
               - 0.9098) < 1e-3
    paddle.polygamma(paddle.to_tensor([2.0]), n=1)
    assert float(paddle.nanmedian(
        paddle.to_tensor([1.0, float("nan"), 3.0])).numpy()) == 2.0


def test_add_n_clip_by_norm():
    assert paddle.add_n(
        [paddle.ones([2])] * 3).numpy().tolist() == [3.0, 3.0]
    v = paddle.clip_by_norm(paddle.to_tensor([3.0, 4.0]), 1.0)
    np.testing.assert_allclose(np.linalg.norm(v.numpy()), 1.0, rtol=1e-5)


def test_sampling_ops():
    paddle.seed(0)
    g = paddle.standard_gamma(paddle.full([2000], 2.0))
    assert abs(float(g.numpy().mean()) - 2.0) < 0.15
    b = paddle.binomial(paddle.full([2000], 10.0), paddle.full([2000], 0.3))
    assert abs(float(b.numpy().mean()) - 3.0) < 0.25
    d = paddle.distribution.Binomial(paddle.to_tensor(10.0),
                                     paddle.to_tensor(0.3))
    from scipy.stats import binom
    np.testing.assert_allclose(float(d.log_prob(paddle.to_tensor(3.0))),
                               binom.logpmf(3, 10, 0.3), rtol=1e-5)


def test_linalg_completions():
    ev = paddle.eigvals(paddle.to_tensor(
        np.diag([1.0, 2.0]).astype("float32")))
    assert sorted(np.real(ev.numpy()).tolist()) == [1.0, 2.0]
    import scipy.linalg as sl
    A = np.random.default_rng(0).normal(size=(4, 4)).astype("float32")
    lu, piv = sl.lu_factor(A)
    P, L, U = paddle.lu_unpack(paddle.to_tensor(lu),
                               paddle.to_tensor((piv + 1).astype("int32")))
    np.testing.assert_allclose(P.numpy() @ L.numpy() @ U.numpy(), A,
                               atol=1e-5)


def test_gather_tree():
    # reference docstring example
    ids = paddle.to_tensor(np.array(
        [[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]], "int32"))
    par = paddle.to_tensor(np.array(
        [[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]], "int32"))
    out = paddle.gather_tree(ids, par).numpy()
    np.testing.assert_array_equal(
        out, [[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]])


def test_viterbi_decode_matches_brute_force():
    rng = np.random.default_rng(0)
    pot = rng.normal(size=(2, 5, 3)).astype("float32")
    trans = rng.normal(size=(3, 3)).astype("float32")
    score, path = paddle.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        include_bos_eos_tag=False)
    import itertools
    for b in range(2):
        best = max(itertools.product(range(3), repeat=5),
                   key=lambda t: pot[b][range(5), list(t)].sum()
                   + sum(trans[t[i], t[i + 1]] for i in range(4)))
        assert tuple(path.numpy()[b]) == best


def test_top_p_sampling():
    paddle.seed(1)
    logits = paddle.to_tensor(np.array([[10., 0., 0., 0.]], "float32"))
    s, tok = paddle.top_p_sampling(logits, paddle.to_tensor([0.5]))
    assert tok.numpy().tolist() == [[0]]


def test_huber_loss():
    out = F.huber_loss(paddle.to_tensor([0.5, 2.0]),
                       paddle.to_tensor([0.0, 0.0]),
                       delta=1.0, reduction="none").numpy()
    np.testing.assert_allclose(out, [0.125, 1.5])


def test_rnnt_loss_matches_brute_force():
    logits = np.random.default_rng(0).normal(
        size=(1, 2, 2, 2)).astype("float32")
    lp = np.log(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))
    # T=2, U=1, blank=0: two alignments (emit at t0 / emit at t1)
    a1 = lp[0, 0, 0, 1] + lp[0, 0, 1, 0] + lp[0, 1, 1, 0]
    a2 = lp[0, 0, 0, 0] + lp[0, 1, 0, 1] + lp[0, 1, 1, 0]
    got = float(F.rnnt_loss(
        paddle.to_tensor(logits),
        paddle.to_tensor(np.array([[1]], "int32")),
        paddle.to_tensor(np.array([2], "int32")),
        paddle.to_tensor(np.array([1], "int32"))).numpy())
    np.testing.assert_allclose(got, -np.logaddexp(a1, a2), rtol=1e-5)


def test_hsigmoid_trains():
    paddle.seed(0)
    x = paddle.to_tensor(np.random.default_rng(1).normal(
        size=(8, 6)).astype("float32"))
    lbl = paddle.to_tensor(np.arange(8, dtype="int32") % 4)
    w = paddle.to_tensor(np.random.default_rng(2).normal(
        size=(3, 6)).astype("float32") * 0.1)
    w.stop_gradient = False
    opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[w])
    losses = []
    for _ in range(20):
        per_sample = F.hsigmoid_loss(x, lbl, 4, w)
        assert tuple(per_sample.shape) == (8, 1)  # reference output shape
        loss = per_sample.mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_max_unpool2d_roundtrip():
    x = paddle.to_tensor(np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    pooled, mask = F.max_pool2d(x, 2, 2, return_mask=True)
    un = F.max_unpool2d(pooled, mask, 2, 2)
    expect = np.zeros((1, 1, 4, 4), "float32")
    for v in [5, 7, 13, 15]:
        expect.reshape(-1)[v] = v
    np.testing.assert_allclose(un.numpy(), expect)


def test_metric_accuracy():
    acc = paddle.metric.accuracy(
        paddle.to_tensor([[0.1, 0.9], [0.8, 0.2]]),
        paddle.to_tensor([1, 1]))
    assert float(acc.numpy()) == 0.5
    acc2 = paddle.metric.accuracy(
        paddle.to_tensor([[0.1, 0.9], [0.8, 0.2]]),
        paddle.to_tensor([1, 1]), k=2)
    assert float(acc2.numpy()) == 1.0


# --- detection ops --------------------------------------------------------

def test_prior_box():
    boxes, var = paddle.vision.ops.prior_box(
        paddle.zeros([1, 8, 4, 4]), paddle.zeros([1, 3, 32, 32]),
        min_sizes=[8.0], aspect_ratios=[1.0, 2.0], flip=True)
    assert tuple(boxes.shape) == (4, 4, 3, 4)
    assert tuple(var.shape) == (4, 4, 3, 4)


def test_yolo_box():
    x = paddle.to_tensor(np.random.default_rng(0).normal(
        size=(1, 3 * 7, 2, 2)).astype("float32"))
    img = paddle.to_tensor(np.array([[64, 64]], "int32"))
    b, s = paddle.vision.ops.yolo_box(
        x, img, anchors=[10, 13, 16, 30, 33, 23], class_num=2,
        conf_thresh=0.5, downsample_ratio=32)
    assert tuple(b.shape) == (1, 12, 4) and tuple(s.shape) == (1, 12, 2)
    # boxes stay inside the clipped image
    assert float(b.numpy().max()) <= 63.0 and float(b.numpy().min()) >= 0.0


def test_matrix_nms_decays_duplicates():
    bb = paddle.to_tensor(np.array(
        [[[0, 0, 10, 10], [0, 1, 10, 11], [20, 20, 30, 30]]], "float32"))
    sc = paddle.to_tensor(np.array(
        [[[0.0, 0.0, 0.0], [0.9, 0.8, 0.7]]], "float32"))
    out, idx, num = paddle.vision.ops.matrix_nms(
        bb, sc, score_threshold=0.1, post_threshold=0.0,
        background_label=0, return_index=True)
    assert out.shape[1] == 6 and int(num.numpy()[0]) == out.shape[0]
    got = {tuple(r[2:].astype(int)): r[1] for r in out.numpy()}
    # top box and the disjoint box keep their scores
    np.testing.assert_allclose(got[(0, 0, 10, 10)], 0.9, rtol=1e-6)
    np.testing.assert_allclose(got[(20, 20, 30, 30)], 0.7, rtol=1e-6)
    # near-duplicate decays by (1 - iou): iou ~ 0.8182 -> 0.8 * 0.1818
    iou = (10 * 9) / (2 * 100 - 10 * 9)
    np.testing.assert_allclose(got[(0, 1, 10, 11)], 0.8 * (1 - iou),
                               rtol=1e-4)


def test_matrix_nms_gaussian_decay():
    """Gaussian mode multiplies by sigma (ref matrix_nms_kernel.cc:70:
    exp((max_iou^2 - iou^2) * sigma)), it does NOT divide."""
    bb = paddle.to_tensor(np.array(
        [[[0, 0, 10, 10], [0, 1, 10, 11], [20, 20, 30, 30]]], "float32"))
    sc = paddle.to_tensor(np.array(
        [[[0.0, 0.0, 0.0], [0.9, 0.8, 0.7]]], "float32"))
    sigma = 2.0
    out, idx, num = paddle.vision.ops.matrix_nms(
        bb, sc, score_threshold=0.1, post_threshold=0.0,
        background_label=0, use_gaussian=True, gaussian_sigma=sigma,
        return_index=True)
    got = {tuple(r[2:].astype(int)): r[1] for r in out.numpy()}
    np.testing.assert_allclose(got[(0, 0, 10, 10)], 0.9, rtol=1e-6)
    np.testing.assert_allclose(got[(20, 20, 30, 30)], 0.7, rtol=1e-6)
    iou = (10 * 9) / (2 * 100 - 10 * 9)
    np.testing.assert_allclose(
        got[(0, 1, 10, 11)], 0.8 * np.exp(-(iou ** 2) * sigma), rtol=1e-4)


def test_yolo_box_coordinates_consistent():
    """Box coords must come from the same grid cell (layout regression:
    coords axis is already last — no transpose)."""
    x = np.zeros((1, 1 * 7, 2, 2), "float32")
    x[:, 4] = 10.0  # conf ~ 1 everywhere
    b, s = paddle.vision.ops.yolo_box(
        paddle.to_tensor(x), paddle.to_tensor(np.array([[64, 64]], "int32")),
        anchors=[16, 16], class_num=2, conf_thresh=0.1,
        downsample_ratio=32, clip_bbox=False)
    bn = b.numpy().reshape(2, 2, 4)  # grid [h, w, 4]
    # with zero tx/ty, centers sit at (col+0.5)/2, (row+0.5)/2 of the image
    for r in range(2):
        for c in range(2):
            cx = (bn[r, c, 0] + bn[r, c, 2]) / 2
            cy = (bn[r, c, 1] + bn[r, c, 3]) / 2
            np.testing.assert_allclose(cx, (c + 0.5) / 2 * 64, rtol=1e-4)
            np.testing.assert_allclose(cy, (r + 0.5) / 2 * 64, rtol=1e-4)


def test_viterbi_lengths_masking():
    rng = np.random.default_rng(1)
    pot = rng.normal(size=(2, 4, 3)).astype("float32")
    trans = rng.normal(size=(3, 3)).astype("float32")
    # batch entry 1 has length 2: its score must equal decoding just 2 steps
    score, path = paddle.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        lengths=paddle.to_tensor(np.array([4, 2], "int32")),
        include_bos_eos_tag=False)
    s_short, p_short = paddle.viterbi_decode(
        paddle.to_tensor(pot[1:, :2]), paddle.to_tensor(trans),
        include_bos_eos_tag=False)
    np.testing.assert_allclose(float(score.numpy()[1]),
                               float(s_short.numpy()[0]), rtol=1e-5)
    np.testing.assert_array_equal(path.numpy()[1, :2], p_short.numpy()[0])


def test_psroi_pool_constant():
    out = paddle.vision.ops.psroi_pool(
        paddle.to_tensor(np.ones((1, 8, 8, 8), "float32")),
        paddle.to_tensor(np.array([[0, 0, 8, 8]], "float32")),
        paddle.to_tensor(np.array([1], "int32")), 2)
    assert tuple(out.shape) == (1, 2, 2, 2)
    np.testing.assert_allclose(out.numpy(), 1.0)


def test_deform_conv2d_zero_offset_equals_conv():
    x = paddle.to_tensor(np.random.default_rng(1).normal(
        size=(1, 2, 6, 6)).astype("float32"))
    w = paddle.to_tensor(np.random.default_rng(2).normal(
        size=(3, 2, 3, 3)).astype("float32"))
    off = paddle.zeros([1, 2 * 3 * 3, 4, 4])
    out = paddle.vision.ops.deform_conv2d(x, off, w)
    ref = F.conv2d(x, w)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=2e-4)
    # grads flow through the bilinear sampling
    x.stop_gradient = False
    paddle.vision.ops.deform_conv2d(x, off, w).sum().backward()
    assert x.grad is not None


def test_distribute_fpn_proposals():
    rois = paddle.to_tensor(np.array(
        [[0, 0, 16, 16], [0, 0, 200, 200]], "float32"))
    outs, restore = paddle.vision.ops.distribute_fpn_proposals(
        rois, 2, 5, 4, 224)
    assert len(outs) == 4
    assert sum(o.shape[0] for o in outs) == 2
    assert sorted(restore.numpy().tolist()) == [0, 1]


def test_bilinear():
    rng = np.random.default_rng(0)
    x1 = paddle.to_tensor(rng.normal(size=(4, 3)).astype("float32"))
    x2 = paddle.to_tensor(rng.normal(size=(4, 5)).astype("float32"))
    bl = paddle.nn.Bilinear(3, 5, 2)
    out = bl(x1, x2)
    ref = np.einsum("ni,oij,nj->no", x1.numpy(), bl.weight.numpy(),
                    x2.numpy()) + bl.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-5)


def test_frobenius_norm_and_identity_loss():
    rng = np.random.default_rng(1)
    A = rng.normal(size=(2, 3, 4)).astype("float32")
    np.testing.assert_allclose(
        paddle.frobenius_norm(paddle.to_tensor(A)).numpy(),
        np.sqrt((A ** 2).sum((-2, -1))), rtol=1e-5)
    np.testing.assert_allclose(
        float(paddle.identity_loss(paddle.to_tensor([2.0, 4.0]),
                                   "mean").numpy()), 3.0)


def test_margin_cross_entropy():
    rng = np.random.default_rng(2)
    # logits are COSINES in this op's contract: keep them in [-1, 1]
    # (values outside get clipped before arccos, diverging from plain CE)
    lg = np.tanh(rng.normal(size=(6, 10))).astype("float32") * 0.9
    y = rng.integers(0, 10, (6,)).astype("int32")
    # zero margins at scale 1 == plain CE
    loss = F.margin_cross_entropy(paddle.to_tensor(lg), paddle.to_tensor(y),
                                  margin1=1.0, margin2=0.0, margin3=0.0,
                                  scale=1.0)
    ref = F.cross_entropy(paddle.to_tensor(lg), paddle.to_tensor(y))
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-4)
    # the ArcFace margin shrinks the target logit -> larger loss
    loss_m = F.margin_cross_entropy(paddle.to_tensor(lg),
                                    paddle.to_tensor(y),
                                    margin2=0.5, scale=1.0)
    assert float(loss_m) > float(loss)
    # softmax output shape
    _, sm = F.margin_cross_entropy(paddle.to_tensor(lg),
                                   paddle.to_tensor(y),
                                   return_softmax=True)
    assert tuple(sm.shape) == (6, 10)


def test_class_center_sample():
    paddle.seed(0)
    lbl = paddle.to_tensor(np.array([3, 7, 3, 1], "int64"))
    remap, sampled = F.class_center_sample(lbl, 100, 10)
    s = sampled.numpy()
    assert len(s) == 10 and {1, 3, 7} <= set(s.tolist())
    # remapped labels point back at the original classes
    assert (s[remap.numpy()] == np.array([3, 7, 3, 1])).all()

"""Elastic training recovery drills (ISSUE 15): FleetSupervisor buddy
in-memory snapshots, collective watchdog (PDT-E021), detector-driven
resume, plus the satellite regressions (elastic store-key GC, coded
StoreTimeoutError PDT-E022).

Rig: multi-threaded TCPStore agents exactly like tests/test_elastic.py
and tests/test_rpc_store.py — each "rank" is a thread with its own
model, optimizer, data shard and store connections; the DP sync is the
supervisor's store-backed parameter allreduce (the CPU stand-in for
the in-graph psum).  Everything here is deterministic modulo wall
time: loss-parity assertions are EXACT equality.
"""
import os
import json
import socket
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import state as core_state
from paddle_tpu.core.errors import StoreTimeoutError
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.observability import metrics as om
from paddle_tpu.resilience import FleetSupervisor, faults
from paddle_tpu.resilience.elastic_train import _shard_view

pytestmark = pytest.mark.resilience

# drill timing: heartbeats fast enough that death detection (hb_timeout)
# and the collective deadline both land in a couple of seconds, with
# margins wide enough for GIL load from W concurrent rank threads
HB_INT, HB_TMO, COLL_MS = 0.25, 2.5, 2500.0


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class _Data(paddle.io.Dataset):
    """Fixed regression set; global batch order is the contract every
    parity assertion leans on."""

    def __init__(self, n=128):
        rng = np.random.default_rng(0)
        self.x = rng.normal(size=(n, 4)).astype("float32")
        self.y = (self.x @ np.arange(1, 5, dtype="float32"))[:, None]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


DATA = _Data()
BS = 2


def _make_model():
    paddle.seed(7)
    net = paddle.nn.Linear(4, 1)
    m = paddle.Model(net)
    opt = paddle.optimizer.SGD(parameters=net.parameters(),
                               learning_rate=0.05)
    m.prepare(opt, paddle.nn.MSELoss())
    return m


class _LossCb(paddle.hapi.callbacks.Callback):
    def __init__(self):
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(float(logs["loss"]))


class _NoDisk:
    """CheckpointManager stand-in that PROVES zero disk reads on the
    buddy path: any consult is a test failure."""

    def latest_complete(self):
        raise AssertionError("disk consulted on the buddy path")

    def load(self, step=None):
        raise AssertionError("disk read on the buddy path")


def _run_fleet(port, W, num_iters, fault=(), snapshot_every=3,
               mgrs=None, timeout_ms=COLL_MS, join_s=90, close=True):
    """One fleet run: W rank threads against an externally hosted
    store.  Returns (models, sups, cbs, results).  Pass ``close=False``
    when the test still needs the supervisors' receiver threads (e.g.
    to wait for an async replica) — and close them itself."""
    models = [_make_model() for _ in range(W)]
    sups, cbs, results = [], [], {}
    faults.clear()
    for f in fault:
        faults.inject(*f)
    for r in range(W):
        sups.append(FleetSupervisor(
            "127.0.0.1", port, f"rank{r}", W, is_master=(r == 0),
            snapshot_every=snapshot_every,
            collective_timeout_ms=timeout_ms,
            heartbeat_interval=HB_INT, heartbeat_timeout=HB_TMO,
            recovery_timeout_s=45.0,
            checkpoint_manager=(mgrs[r] if mgrs else None)))
        cbs.append(_LossCb())

    def worker(r):
        try:
            results[r] = sups[r].fit(models[r], DATA, batch_size=BS,
                                     num_iters=num_iters,
                                     callbacks=[cbs[r]])
        except BaseException as e:  # surfaced by the caller's asserts
            results[r] = e

    ts = [threading.Thread(target=worker, args=(r,), daemon=True)
          for r in range(W)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(join_s)
        assert not t.is_alive(), \
            f"rank thread hung >{join_s}s: results={results}"
    if close:
        # close=False callers still have async replication in flight:
        # they clear faults + close in their own finally, AFTER
        # waiting for the replicas they assert on
        faults.clear()
        for s in sups:
            s.close()
    for r, res in results.items():
        assert not isinstance(res, BaseException), \
            f"rank {r} raised {type(res).__name__}: {res}"
    return models, sups, cbs, results


def _host():
    port = _free_port()
    return TCPStore("127.0.0.1", port, is_master=True), port


def _counter(name):
    return om.registry().counter(name).value


def _state_np(model):
    return {k: np.asarray(v.numpy())
            for k, v in model.network.state_dict().items()}


def _restart_reference(state, offset_batches, resume_step, num_iters):
    """The unfaulted restart: a fresh model carrying ``state`` fits the
    WORLD=1 remainder of the stream from ``offset_batches``, resuming
    the global step counter at ``resume_step`` — exactly what the
    recovered survivor does, minus every fault."""
    from paddle_tpu.core.tensor import Tensor
    m = _make_model()
    m.network.set_state_dict(
        {k: Tensor(np.asarray(v)) for k, v in state.items()})
    shard = _shard_view(DATA, BS, 0, 1, offset_batches)
    cb = _LossCb()
    m.fit(shard, batch_size=BS, epochs=1, shuffle=False, verbose=0,
          num_iters=num_iters, callbacks=[cb],
          resume=(0, 0, resume_step))
    return m, cb.losses


# --------------------------------------------------------------------------
# acceptance drill: rank death -> buddy restore -> loss parity
# --------------------------------------------------------------------------

def test_rank_dead_buddy_restore_loss_parity():
    """THE acceptance drill: rank1 dies at step 6 of a 2-rank fit
    (snapshots every 3).  The survivor gets a coded collective timeout,
    reshards to world 1, restores the buddy snapshot from step 3 with
    ZERO disk reads, fast-forwards the data position, and the
    post-recovery loss trajectory EQUALS an unfaulted restart at step 3
    on the same data order."""
    rec0 = _counter("elastic.recoveries")
    host, port = _host()
    try:
        models, sups, cbs, results = _run_fleet(
            port, 2, num_iters=12,
            fault=[("rank_dead", "1", 1, 6)],
            mgrs=[_NoDisk(), _NoDisk()])
    finally:
        host.close()
    assert results == {0: True, 1: False}
    assert sups[1].dead
    lr = sups[0].last_recovery
    assert lr is not None
    assert lr["source"] == "buddy"
    assert lr["step"] == 3          # newest snapshot before the death
    assert lr["consumed"] == 6      # 3 steps x world 2
    assert lr["dead"] == ["rank1"]
    assert lr["cause"] == "CollectiveTimeoutError"
    assert sups[0].world == 1 and sups[0].rank == 0
    assert _counter("elastic.recoveries") == rec0 + 1
    # 6 pre-fault losses + 9 post-recovery (global step resumes at 3,
    # num_iters=12)
    assert len(cbs[0].losses) == 15

    # unfaulted restart reference: 2-rank clean fleet to step 3 gives
    # the snapshot-consistent state (post-sync states are identical on
    # every rank), then a world-1 restart over the remaining stream
    host, port = _host()
    try:
        ref_models, _s, _c, ref_res = _run_fleet(port, 2, num_iters=3,
                                                 fault=())
    finally:
        host.close()
    assert ref_res == {0: True, 1: True}
    _m, ref_losses = _restart_reference(_state_np(ref_models[0]),
                                        offset_batches=6,
                                        resume_step=3, num_iters=12)
    assert cbs[0].losses[6:] == ref_losses
    # and the final parameters match bitwise, not just the losses
    end = _state_np(models[0])
    ref_end = _state_np(_m)
    assert set(end) == set(ref_end)
    for k in end:
        assert np.array_equal(end[k], ref_end[k]), k


def test_multi_survivor_resharding_stays_lockstep():
    """3 ranks, ONE death: the two survivors roll back together,
    reshard to world 2, and keep training IN LOCKSTEP — their
    parameters are bitwise-identical at every synced step, so at the
    end.  Regression for the rolled-back-step collective keys: re-run
    steps must not consume a peer's stale pre-crash contribution (the
    allreduce epoch namespace), or survivors silently diverge."""
    host, port = _host()
    try:
        models, sups, cbs, results = _run_fleet(
            port, 3, num_iters=9, snapshot_every=2,
            fault=[("rank_dead", "2", 1, 5)],
            mgrs=[_NoDisk()] * 3)
    finally:
        host.close()
    assert results == {0: True, 1: True, 2: False}
    for r in (0, 1):
        lr = sups[r].last_recovery
        assert lr is not None and lr["source"] == "buddy"
        assert lr["step"] == 4 and lr["dead"] == ["rank2"]
        assert sups[r].world == 2 and sups[r].rank == r
    s0, s1 = _state_np(models[0]), _state_np(models[1])
    for k in s0:
        assert np.array_equal(s0[k], s1[k]), \
            f"survivors diverged on {k}: {s0[k]} vs {s1[k]}"


def test_two_deaths_buddy_chain():
    """rank1 AND its buddy rank2 die together in a 3-rank fleet: the
    plan skips rank1 (its holder died with it) and restores from
    rank2's replica, held by the surviving rank0 — still no disk."""
    host, port = _host()
    try:
        models, sups, cbs, results = _run_fleet(
            port, 3, num_iters=10,
            fault=[("rank_dead", "1", 1, 5), ("rank_dead", "2", 1, 5)],
            mgrs=[_NoDisk()] * 3, snapshot_every=2)
    finally:
        host.close()
    assert results == {0: True, 1: False, 2: False}
    lr = sups[0].last_recovery
    assert lr is not None and lr["source"] == "buddy"
    assert set(lr["dead"]) == {"rank1", "rank2"}
    assert lr["step"] == 4
    assert sups[0].world == 1


def test_disk_fallback_when_no_buddy_replica(tmp_path):
    """Snapshots disabled (the no-surviving-replica limit case): the
    dead rank leaves nothing in peer memory, so recovery falls to the
    newest COMPLETE CheckpointManager version — and the post-recovery
    trajectory equals a from-scratch world-1 restart at that version's
    position."""
    from paddle_tpu.resilience.checkpoint import CheckpointManager

    seed_model = _make_model()
    mgr = CheckpointManager(tmp_path / "ckpt")
    # shape the checkpoint like Model._resilient_save does: rng as a
    # PLAIN ndarray (the restore path must not assume Tensor), and a
    # recorded epoch >= 1 (single-epoch stream semantics must restart
    # the remaining data at epoch 0, not skip fit's whole epoch range)
    core_state.default_rng.seed(0)
    rng_arr = np.asarray(core_state.default_rng._key_var._read())
    mgr.save({"model": seed_model.network.state_dict(),
              "rng": rng_arr}, 0,
             meta={"global_step": 0, "consumed": 0, "epoch": 1})
    host, port = _host()
    try:
        models, sups, cbs, results = _run_fleet(
            port, 2, num_iters=8, snapshot_every=0,
            fault=[("rank_dead", "1", 1, 4)], mgrs=[mgr, mgr])
    finally:
        host.close()
    assert results == {0: True, 1: False}
    lr = sups[0].last_recovery
    assert lr is not None and lr["source"] == "disk"
    assert lr["step"] == 0 and lr["consumed"] == 0
    # 4 pre-fault + 8 from-scratch world-1 steps
    assert len(cbs[0].losses) == 12
    _m, ref_losses = _restart_reference(_state_np(seed_model),
                                        offset_batches=0,
                                        resume_step=0, num_iters=8)
    assert cbs[0].losses[4:] == ref_losses


# --------------------------------------------------------------------------
# detector vs straggler separation
# --------------------------------------------------------------------------

def test_slow_rank_does_not_trigger_recovery():
    """A straggler stalls inside the collective deadline while its
    heartbeats keep flowing: peers absorb the wait, NO recovery runs,
    and the math is untouched (bitwise vs the uninjected run)."""
    rec0 = _counter("elastic.recoveries")
    host, port = _host()
    try:
        _m, sups, cbs, results = _run_fleet(
            port, 2, num_iters=5,
            fault=[("slow_rank", "1", 2, 2)])
    finally:
        host.close()
    assert results == {0: True, 1: True}
    assert all(s.last_recovery is None for s in sups)
    assert _counter("elastic.recoveries") == rec0
    host, port = _host()
    try:
        _m2, _s2, clean_cbs, _r2 = _run_fleet(port, 2, num_iters=5)
    finally:
        host.close()
    assert cbs[0].losses == clean_cbs[0].losses
    assert cbs[1].losses == clean_cbs[1].losses


# --------------------------------------------------------------------------
# collective watchdog: coded failure + exactly one flight dump
# --------------------------------------------------------------------------

def test_hung_collective_dumps_once_with_stacks(tmp_path, monkeypatch):
    """The dead peer's hang surfaces as PDT-E021 WITHIN the collective
    deadline (the drill completes in bounded wall time instead of
    hanging tier-1), with exactly ONE flight dump containing every
    thread's stack."""
    from paddle_tpu.observability import watchdog as wd

    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    host, port = _host()
    t0 = time.monotonic()
    try:
        _m, sups, cbs, results = _run_fleet(
            port, 2, num_iters=6, snapshot_every=2,
            fault=[("rank_dead", "1", 1, 4)], mgrs=[_NoDisk()] * 2)
    finally:
        host.close()
    wall = time.monotonic() - t0
    assert results == {0: True, 1: False}
    lr = sups[0].last_recovery
    assert lr["cause"] == "CollectiveTimeoutError"
    # bounded detection: heartbeat expiry + collective deadline + the
    # recovery itself, all inside a wall budget that an infinite hang
    # would blow immediately
    assert wall < 45.0
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("flight_") and f.endswith(".json")
             and not f.endswith(".trace.json")]
    assert len(dumps) == 1, dumps
    with open(tmp_path / dumps[0]) as f:
        rec = json.load(f)
    stacks = rec["extra"]["stacks"]
    assert stacks, "flight record carries no thread stacks"
    assert any("_allreduce_mean" in "".join(str(fr) for fr in frames)
               for frames in stacks.values())
    assert wd.armed() == []  # every token disarmed after the run


# --------------------------------------------------------------------------
# metrics-off: bitwise no-op, recovery still functions
# --------------------------------------------------------------------------

def test_metrics_off_bitwise_noop(tmp_path, monkeypatch):
    """PDTPU_METRICS=off restores pre-observability behavior bitwise:
    the same faulted drill produces the SAME losses and the SAME
    recovery (the supervisor's hard deadline replaces the watchdog), no
    flight dumps, and no counter movement."""
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    host, port = _host()
    try:
        _m, sups_on, cbs_on, res_on = _run_fleet(
            port, 2, num_iters=8, snapshot_every=2,
            fault=[("rank_dead", "1", 1, 4)], mgrs=[_NoDisk()] * 2)
    finally:
        host.close()

    old = core_state.get_flag("metrics")
    core_state.set_flags({"metrics": False})
    try:
        snaps0 = _counter("elastic.snapshots")
        rec0 = _counter("elastic.recoveries")
        host, port = _host()
        try:
            _m2, sups_off, cbs_off, res_off = _run_fleet(
                port, 2, num_iters=8, snapshot_every=2,
                fault=[("rank_dead", "1", 1, 4)], mgrs=[_NoDisk()] * 2)
        finally:
            host.close()
        assert _counter("elastic.snapshots") == snaps0
        assert _counter("elastic.recoveries") == rec0
    finally:
        core_state.set_flags({"metrics": old})

    assert res_on == res_off == {0: True, 1: False}
    assert cbs_on[0].losses == cbs_off[0].losses
    on, off = sups_on[0].last_recovery, sups_off[0].last_recovery
    assert off is not None
    assert (on["source"], on["step"], on["consumed"]) \
        == (off["source"], off["step"], off["consumed"])
    assert off["cause"] == "CollectiveTimeoutError"
    # observability off is observability off: no stray flight records
    dumps_off = [f for f in os.listdir(tmp_path)
                 if f.endswith(".json")
                 and not f.endswith(".trace.json")]
    assert len(dumps_off) == 1  # only the metrics-ON run's dump


# --------------------------------------------------------------------------
# snapshot machinery: cadence, counters, torn replicas, partition retry
# --------------------------------------------------------------------------

def _wait_replicas(sup, src, want_steps, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        held = {s for s, _m, _p in sup._replicas.get(src, [])}
        if want_steps <= held:
            return held
        time.sleep(0.05)
    return {s for s, _m, _p in sup._replicas.get(src, [])}


def test_snapshot_cadence_and_counters():
    """Clean run accounting: captures at every cadence boundary on
    every rank, replication wall time observed, nothing torn, nothing
    recovered, generation gauge at the initial rendezvous."""
    reg = om.registry()
    snaps0 = _counter("elastic.snapshots")
    torn0 = _counter("elastic.snapshots_torn")
    rec0 = _counter("elastic.recoveries")
    ms0 = reg.histogram("elastic.snapshot_ms").count
    host, port = _host()
    sups = []
    try:
        _m, sups, _c, results = _run_fleet(port, 2, num_iters=6,
                                           snapshot_every=3,
                                           close=False)
        # replication is async off the step path: wait for the buddies
        # to actually hold each other's generations before closing
        held0 = _wait_replicas(sups[0], "rank1", {3, 6})
        held1 = _wait_replicas(sups[1], "rank0", {3, 6})
    finally:
        faults.clear()
        for s in sups:
            s.close()
        host.close()
    assert results == {0: True, 1: True}
    assert _counter("elastic.snapshots") == snaps0 + 4  # 2 ranks x 2
    assert _counter("elastic.snapshots_torn") == torn0
    assert _counter("elastic.recoveries") == rec0
    pushed = reg.histogram("elastic.snapshot_ms").count - ms0
    assert 1 <= pushed <= 4  # latest-wins queue may skip, never grow
    assert held0 == {3, 6} and held1 == {3, 6}
    assert reg.gauge("elastic.generation").value == 1


def test_snapshot_torn_falls_back_to_previous_generation():
    """The snapshot_torn drill: rank1's step-6 replica is half-written
    (manifest records full size/CRC); the buddy's validation rejects it
    and keeps step 3 — which is exactly what recovery restores when
    rank1 dies at step 8."""
    torn0 = _counter("elastic.snapshots_torn")
    host, port = _host()
    try:
        _m, sups, cbs, results = _run_fleet(
            port, 2, num_iters=12,
            fault=[("snapshot_torn", "1", 1, 2),
                   ("rank_dead", "1", 1, 8)],
            mgrs=[_NoDisk()] * 2)
    finally:
        host.close()
    assert results == {0: True, 1: False}
    assert _counter("elastic.snapshots_torn") >= torn0 + 1
    lr = sups[0].last_recovery
    assert lr["source"] == "buddy"
    assert lr["step"] == 3  # torn 6 rejected, previous generation kept


def test_store_partition_bounded_retry():
    """store_partition exhausts the push budget on rank0's FIRST
    snapshot replication (3 injected failures vs 3 attempts): that
    generation is skipped, the failure counted, and the NEXT cadence
    boundary replicates fine — training never notices."""
    fail0 = _counter("elastic.snapshot_push_failures")
    host, port = _host()
    sups = []
    try:
        _m, sups, _c, results = _run_fleet(
            port, 2, num_iters=6, snapshot_every=3,
            fault=[("store_partition", "rank0", 3, 1)], close=False)
        held = _wait_replicas(sups[1], "rank0", {6})
    finally:
        faults.clear()
        for s in sups:
            s.close()
        host.close()
    assert results == {0: True, 1: True}
    assert all(s.last_recovery is None for s in sups)
    assert _counter("elastic.snapshot_push_failures") == fail0 + 1
    assert 6 in held  # the step-6 push survived the healed partition


# --------------------------------------------------------------------------
# satellite: elastic store-key GC across churn
# --------------------------------------------------------------------------

def test_elastic_store_keys_stable_across_churn(monkeypatch):
    """Departed nodes' elastic/* keys are GC'd by the master: N
    join/leave cycles leave the store key count flat instead of growing
    one key set per churn event."""
    from paddle_tpu.distributed.elastic import ElasticManager

    monkeypatch.setenv("PDTPU_NATIVE_STORE", "0")  # countable _data
    port = _free_port()
    host = TCPStore("127.0.0.1", port, is_master=True)
    try:
        master = ElasticManager(
            TCPStore("127.0.0.1", port), "anchor", True,
            heartbeat_interval=0.15, heartbeat_timeout=0.6,
            min_nodes=1)
        gen, members = master.start()
        assert members == ["anchor"]

        def elastic_keys():
            with host._server._cv:
                return sorted(k.decode() for k in host._server._data
                              if k.startswith(b"elastic/"))

        def churn(i, gen):
            st = TCPStore("127.0.0.1", port)
            m = ElasticManager(st, f"joiner{i}", False,
                               heartbeat_interval=0.15,
                               heartbeat_timeout=0.6, min_nodes=1)
            res = {}
            t = threading.Thread(
                target=lambda: res.update(g=m.start()), daemon=True)
            t.start()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                g, mem = master.wait_generation(gen, timeout=0.5)
                if g > gen and f"joiner{i}" in mem:
                    gen = g
                    break
            else:
                raise AssertionError(f"joiner{i} never admitted")
            t.join(10)
            m.stop()  # leaves: heartbeat expires, master evicts + GCs
            while time.monotonic() < deadline:
                g, mem = master.wait_generation(gen, timeout=0.5)
                if g > gen and mem == ["anchor"]:
                    gen = g
                    break
            else:
                raise AssertionError(f"joiner{i} never evicted")
            st.close()
            return gen

        counts = []
        for i in range(3):
            gen = churn(i, gen)
            time.sleep(0.5)  # one scan pass for the hb-key re-delete
            counts.append(len(elastic_keys()))
        # stable, not linear in churn: every cycle ends at the same
        # footprint once the departed joiner's keys are collected
        assert counts[0] == counts[1] == counts[2], \
            (counts, elastic_keys())
        keys = elastic_keys()
        assert not any(f"joiner{i}" in k for i in range(3)
                       for k in keys), keys
        # membership history bounded too
        assert sum(k.startswith("elastic/members/")
                   for k in keys) <= 4
        master.stop()
    finally:
        host.close()


def test_elastic_dropped_node_readmitted_after_slot_gc(monkeypatch):
    """Key GC must not strand a transiently-dropped node: once the
    master retires its registration slot, the healed agent re-registers
    itself (``_ensure_registered``) and is re-admitted — the pre-GC
    'dropped: wait to be re-seen' launcher contract still holds."""
    from paddle_tpu.distributed.elastic import ElasticManager

    port = _free_port()
    host = TCPStore("127.0.0.1", port, is_master=True)
    try:
        master = ElasticManager(
            TCPStore("127.0.0.1", port), "anchor", True,
            heartbeat_interval=0.15, heartbeat_timeout=0.6,
            min_nodes=1)
        gen, members = master.start()
        j = ElasticManager(
            TCPStore("127.0.0.1", port), "flapper", False,
            heartbeat_interval=0.15, heartbeat_timeout=0.6,
            min_nodes=1)
        jres = {}
        threading.Thread(target=lambda: jres.update(g=j.start()),
                         daemon=True).start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            g, mem = master.wait_generation(gen, timeout=0.5)
            if g > gen and "flapper" in mem:
                gen = g
                break
        else:
            raise AssertionError("flapper never admitted")

        # the launcher's dropped-node loop: keep watching generations
        # (this is also what refreshes j's cached membership, which
        # _ensure_registered keys off)
        seen = {"dropped": False, "back": False}

        def watch():
            wg = jres["g"][0] if "g" in jres else 0
            end = time.monotonic() + 30
            while time.monotonic() < end and not seen["back"]:
                try:
                    wg2, wm = j.wait_generation(wg, timeout=0.5)
                except Exception:
                    continue
                if wg2 > wg:
                    wg = wg2
                    if "flapper" not in wm:
                        seen["dropped"] = True
                    elif seen["dropped"]:
                        seen["back"] = True

        threading.Thread(target=watch, daemon=True).start()

        # simulate a partition: the flapper's beats stop flowing but
        # the agent stays alive
        real_beat = j._beat
        j._beat = lambda: None
        while time.monotonic() < deadline:
            g, mem = master.wait_generation(gen, timeout=0.5)
            if g > gen and mem == ["anchor"]:
                gen = g
                break
        else:
            raise AssertionError("flapper never evicted")
        time.sleep(0.6)  # a GC pass retires the slot + hb tombstone

        # partition heals: beats resume on the (now GC'd) identity
        j._beat = real_beat
        while time.monotonic() < deadline:
            g, mem = master.wait_generation(gen, timeout=0.5)
            if g > gen and "flapper" in mem:
                gen = g
                break
        else:
            raise AssertionError(
                "healed flapper never re-admitted after slot GC")
        # and the agent itself observed the round trip
        t_end = time.monotonic() + 10
        while time.monotonic() < t_end and not seen["back"]:
            time.sleep(0.1)
        assert seen["dropped"] and seen["back"], seen
        j.stop()
        master.stop()
    finally:
        host.close()


# --------------------------------------------------------------------------
# satellite: coded StoreTimeoutError (PDT-E022)
# --------------------------------------------------------------------------

def test_store_timeout_error_coded():
    """get/wait deadline expiry raises the coded StoreTimeoutError
    (PDT-E022), still a TimeoutError for old callers, and a timeout is
    a SERVED answer — never retried as a transport failure."""
    port = _free_port()
    host = TCPStore("127.0.0.1", port, is_master=True)
    try:
        client = TCPStore("127.0.0.1", port)
        with pytest.raises(StoreTimeoutError) as ei:
            client.get("never/appears", timeout=0.2)
        assert ei.value.error_code == "PDT-E022"
        assert "PDT-E022" in str(ei.value)
        assert isinstance(ei.value, TimeoutError)
        with pytest.raises(StoreTimeoutError):
            client.wait(["also/never"], timeout=0.2)
        # a timeout consumed no retry budget: the connection is fine
        client.set("k", b"v")
        assert client.get("k", timeout=1.0) == b"v"
        client.close()
    finally:
        host.close()


# --------------------------------------------------------------------------
# bench: the hybrid_bench recovery column computes with sane accounting
# --------------------------------------------------------------------------

def test_recovery_bench_column_smoke():
    """The ISSUE-15 ``recovery`` column of benchmarks/hybrid_bench.py:
    injected rank_dead -> buddy restore, with time-to-resume and
    snapshot-overhead accounting populated."""
    import sys
    sys.path.insert(0, "/root/repo/benchmarks")
    try:
        import hybrid_bench as hb
    finally:
        sys.path.pop(0)
    row = hb.measure_recovery()
    assert row["recovered"] and row["completed"]
    assert row["restore_source"] == "buddy"
    # the dying rank checks its fault BEFORE snapshotting, so a death
    # ON a cadence boundary restores the previous generation: newest
    # snapshot strictly below the death step
    assert row["restored_step"] == (row["death_at_step"] - 1) \
        // row["snapshot_every"] * row["snapshot_every"]
    assert row["recovery_ms"] > 0
    assert row["snapshots"] >= 1 and row["snapshot_ms_mean"] > 0
    assert row["drill_wall_s"] < 60


# --------------------------------------------------------------------------
# unit: batch-granular reshard reconstructs the exact remaining stream
# --------------------------------------------------------------------------

def test_shard_view_reshard_exact_stream():
    """Carrying the consumed-batch offset across a world-size change
    reconstructs exactly the remaining global batch stream — the
    property the loss-parity drills lean on."""
    n, bs = 48, 2
    data = [(np.float32(i), np.float32(i)) for i in range(n)]

    def batches(shard):
        return [tuple(float(shard[b * bs + r][0]) for r in range(bs))
                for b in range(len(shard) // bs)]

    # world 3 consumes 9 global batches (3 steps), then reshards to 2
    consumed = 9
    remaining = [tuple(float(data[g * bs + r][0]) for r in range(bs))
                 for g in range(consumed, n // bs)]
    got = [None] * len(remaining)
    for rank in range(2):
        sh = batches(_shard_view(data, bs, rank, 2, consumed))
        for b, item in enumerate(sh):
            got[b * 2 + rank] = item
    # trailing ragged batches (not divisible by the new world) stay
    # unconsumed by construction — strip the None tail
    while got and got[-1] is None:
        got.pop()
    assert got == remaining[:len(got)]
    assert len(remaining) - len(got) < 2  # at most world-1 dropped

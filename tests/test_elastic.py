"""Elastic membership + hang watchdog (VERDICT r2 missing #3 / weak #8;
reference capabilities: fleet/elastic/manager.py heartbeat membership and
rank re-map, comm_task_manager.h hang abort)."""
import os
import socket
import subprocess
import sys
import textwrap
import time

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def _env():
    return {**os.environ, "PYTHONPATH": "/root/repo",
            "JAX_PLATFORMS": "cpu"}


def test_progress_watchdog_restarts_hung_worker(tmp_path):
    """A worker that stops making progress (the desynced-collective
    symptom) is killed by the watchdog and restarted; the restarted run
    completes."""
    marker = tmp_path / "attempt"
    # writes the progress file directly (same thing report_progress does)
    # to keep the worker import-light: the 3s budget must time the HANG,
    # not a jax import
    script = _write(tmp_path, "hang.py", f"""
        import os, pathlib, time
        m = pathlib.Path({str(marker)!r})
        first = not m.exists()
        m.write_text("x")
        hb = os.environ["PADDLE_PROGRESS_FILE"]
        for step in range(3):
            pathlib.Path(hb).write_text(str(step))
            time.sleep(0.1)
        if first:
            time.sleep(3600)   # simulate a hung collective
    """)
    t0 = time.time()
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--progress_timeout", "3", "--max_restart_times", "1", script],
        capture_output=True, text=True, cwd="/root/repo", env=_env(),
        timeout=120)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "hang watchdog" in out.stderr
    assert time.time() - t0 < 60  # detected well within the hour "hang"


def test_progress_watchdog_gives_up_after_budget(tmp_path):
    script = _write(tmp_path, "alwayshang.py", """
        import os, pathlib, time
        pathlib.Path(os.environ["PADDLE_PROGRESS_FILE"]).write_text("0")
        time.sleep(3600)
    """)
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--progress_timeout", "2", script],
        capture_output=True, text=True, cwd="/root/repo", env=_env(),
        timeout=120)
    assert out.returncode != 0
    assert "hang watchdog" in out.stderr


def test_membership_scale_down_remaps_ranks(tmp_path):
    """Two node agents form a gen-1 world of 2; killing one agent expires
    its heartbeat, the master publishes a new generation, and the survivor
    respawns its worker with re-mapped nnodes=1 (reference ElasticManager
    scale-down)."""
    port = _free_port()
    script = _write(tmp_path, "work.py", f"""
        import os, pathlib, time
        n = os.environ["PADDLE_TRAINERS_NUM"]
        r = os.environ["PADDLE_TRAINER_ID"]
        d = pathlib.Path({str(tmp_path)!r})
        (d / f"pid_{{os.getpid()}}").write_text("")  # test cleanup list
        (d / f"seen_w{{n}}_r{{r}}").write_text("")
        # run "forever"; the gen-2 (world=1) incarnation exits promptly so
        # the surviving agent can finish with rc 0
        time.sleep(2 if n == "1" else 3600)
    """)

    def agent(rank):
        return subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--elastic", "1", "--nnodes", "2", "--node_rank", str(rank),
             "--master", f"127.0.0.1:{port}",
             "--heartbeat_interval", "0.3", "--heartbeat_timeout", "1.5",
             script],
            cwd="/root/repo", env={
                **_env(), "PADDLE_ELASTIC_NODE_ID": f"node{rank}"},
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

    a0 = agent(0)
    a1 = agent(1)
    try:
        # both workers saw the 2-node world
        deadline = time.time() + 60
        want = {f"seen_w2_r{r}" for r in (0, 1)}
        while time.time() < deadline:
            if want <= {p.name for p in tmp_path.iterdir()}:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(
                f"gen-1 world never formed: {list(tmp_path.iterdir())}")

        a1.kill()  # node1 agent dies -> heartbeat expires
        a1.wait()

        out, err = a0.communicate(timeout=90)
        assert a0.returncode == 0, (out, err)
        assert "re-rendezvous" in err
        # survivor respawned its worker as rank 0 of a 1-node world
        assert (tmp_path / "seen_w1_r0").exists()
    finally:
        for a in (a0, a1):
            if a.poll() is None:
                a.kill()
        # SIGKILLed agents can't reap their workers: kill any orphaned
        # sleeper (pid files written by work.py) so it doesn't outlive the
        # suite (see the repo's zombie-process pitfalls)
        for p in tmp_path.glob("pid_*"):
            try:
                os.kill(int(p.name[4:]), 9)
            except (OSError, ValueError):
                pass


def test_jit_step_reports_progress(tmp_path, monkeypatch):
    """Compiled-step invocations heartbeat automatically when the launcher
    set PADDLE_PROGRESS_FILE (no user code needed)."""
    import numpy as np

    import paddle_tpu as paddle

    path = tmp_path / "hb"
    monkeypatch.setenv("PADDLE_PROGRESS_FILE", str(path))

    @paddle.jit.to_static
    def f(x):
        return x * 2.0

    x = paddle.to_tensor(np.ones(4, np.float32))
    f(x)      # capture (step 0 runs eagerly — no compiled call yet)
    f(x)      # compiled call -> heartbeat
    assert path.exists()
    t1 = os.path.getmtime(path)
    time.sleep(0.05)
    f(x)
    assert os.path.getmtime(path) >= t1


def test_standby_master_takes_over_scan(tmp_path):
    """With the store hosted OUTSIDE the agents (external-etcd analog),
    killing the scanning master promotes the next registered alive agent,
    which publishes the post-failure generation (reference elastic
    re-rendezvous without a fixed master)."""
    import threading

    from paddle_tpu.distributed.elastic import ElasticManager
    from paddle_tpu.distributed.store import TCPStore

    port = _free_port()
    host_store = TCPStore("127.0.0.1", port, is_master=True, world_size=1)
    try:
        def mk(nid, is_master):
            st = TCPStore("127.0.0.1", port, is_master=False)
            return ElasticManager(st, nid, is_master,
                                  heartbeat_interval=0.2,
                                  heartbeat_timeout=0.6, min_nodes=2)

        a = mk("nodeA", True)
        b = mk("nodeB", False)
        ra = rb = None
        ta = threading.Thread(target=lambda: a.start(), daemon=True)
        results = {}

        def run_b():
            results["gen1"] = b.start()
        tb = threading.Thread(target=run_b, daemon=True)
        ta.start(); tb.start()
        ta.join(30); tb.join(30)
        assert not tb.is_alive(), "initial rendezvous never formed"
        gen1, members1 = results["gen1"]
        assert set(members1) == {"nodeA", "nodeB"}

        a.stop()  # master dies: node heartbeat AND master_hb go silent

        deadline = time.time() + 30
        while time.time() < deadline:
            gen, members = b.wait_generation(gen1, timeout=1.0)
            if gen > gen1 and members == ["nodeB"]:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("standby never published a new generation")
        assert b.is_master, "standby should have promoted itself"
        b.stop()
    finally:
        host_store.close()

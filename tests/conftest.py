"""Test fixture: force an 8-device virtual CPU mesh (the "fake backend"
pattern of the reference's fake_cpu_device.h plugin tests, SURVEY §4) so
single-host CI can exercise all sharding paths without TPU hardware.

Note: this image's sitecustomize registers the `axon` TPU platform and sets
jax_platforms="axon,cpu" via jax.config (which overrides env vars), so we
must update the config — not just JAX_PLATFORMS — before backends init.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if jax._src.xla_bridge.backends_are_initialized():
    from jax.extend.backend import clear_backends

    clear_backends()

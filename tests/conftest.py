"""Test fixture: force an 8-device virtual CPU mesh (the "fake backend"
pattern of the reference's fake_cpu_device.h plugin tests, SURVEY §4) so
single-host CI can exercise all sharding paths without TPU hardware.

Note: this image's sitecustomize registers the `axon` TPU platform and sets
jax_platforms="axon,cpu" via jax.config (which overrides env vars), so we
must update the config — not just JAX_PLATFORMS — before backends init.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if jax._src.xla_bridge.backends_are_initialized():
    from jax.extend.backend import clear_backends

    clear_backends()

import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# Tier-1 budget ordering (ISSUE 7 satellite).  The tier-1 gate runs the
# suite under a hard 870s timeout, so whatever collects LAST is what a
# slow machine silently drops.  Alphabetical order put the expensive
# serving/generation block and the vision model zoo right where the
# cutoff lands, clipping dozens of sub-second tests queued behind them.
# Order files by measured passing-tests-per-second instead (PR7 timing
# audit, full-suite --durations=0 run), with the acceptance-critical
# kernel/serving suites pinned in-window and the known-failing
# distributed/pipeline/scale5 classes (0 dots either way) at the very
# end: a timeout now costs the fewest, least-informative tests.  Files
# not listed (future suites) run right after the pinned block — inside
# the budget by default.  Regenerate the order from a --durations=0 run
# when the balance shifts.
# ---------------------------------------------------------------------------
_TIER1_ORDER = [
    # dense: hundreds of fast tests, ~270s total.  test_tracing is the
    # ISSUE-12 acceptance suite (trace export golden, fleet_snapshot
    # merge, rpc propagation) — model-free except the export acceptance
    # drill, which reuses the session serving_gpt
    # test_slo_watchdog is the ISSUE-14 acceptance suite (burn-rate
    # math, engine_stall drill, regress CLI) — model-free except the
    # engine drills, which reuse the session serving_gpt + the
    # serving-suite geometry
    "test_prefix_cache.py", "test_observability.py", "test_tracing.py",
    "test_slo_watchdog.py",
    # ISSUE-11 acceptance: fused-backward bitwise parity + overlap
    # grad-sync bitwise gates — model-free/tiny-model, ~80s combined
    "test_flash_bwd.py", "test_overlap.py",
    # ISSUE-19 acceptance: remat bitwise family, fused glue twin
    # parity, static-peak drop, prefetch overlap — tiny models, CPU
    "test_train_perf.py",
    "test_profiler_device.py",
    # ISSUE-16 acceptance: whole-program jaxpr analyzer (collective
    # schedule hash/verify, donation provenance, shape-fork PDT242) —
    # model-free tiny jaxprs, a few seconds total
    "test_native_io.py", "test_analysis.py", "test_analysis_program.py",
    "test_autograd.py",
    "test_tensor.py", "test_geometric_namespaces.py",
    "test_optimizer.py", "test_optimizer_fused.py",
    "test_control_flow.py", "test_resilience.py",
    # ISSUE-15 acceptance: elastic recovery drills (buddy restore loss
    # parity, PDT-E021 flight dump, store-key GC) — tiny-model thread
    # fleets over loopback TCPStores, ~2 min wall dominated by the
    # deliberate heartbeat/collective deadlines
    "test_elastic_train.py",
    "test_dist_checkpoint.py", "test_dy2static.py",
    "test_text_audio.py", "test_datasets_transforms_breadth.py",
    "test_autotune.py", "test_nn.py",
    "test_distribution_multivariate.py", "test_errors_static.py",
    "test_beam_decode.py", "test_ops_special.py", "test_incubate.py",
    "test_ps.py", "test_io_workers.py", "test_jit_save_load.py",
    "test_sparse_lbfgs.py", "test_advice_fixes.py",
    "test_ops_extra.py", "test_auto_tuner.py", "test_jit.py",
    "test_quantization.py", "test_auto_parallel.py",
    "test_sparse_breadth.py", "test_vision_ops_inference.py",
    "test_rnn.py",
    # pinned acceptance block: kernels + serving parity (fp, quant,
    # speculative — test_speculative reuses the session model and the
    # serving-engine geometries, so it rides the same compiled
    # programs; test_distserve is the ISSUE-13 TP/disagg acceptance
    # suite and reuses the session serving_gpt + the same geometry)
    "test_pallas.py", "test_quant_serving.py", "test_serving_engine.py",
    # test_decode_megakernel is the ISSUE-18 acceptance suite (fused
    # decode kernels bitwise vs twins, engine on/off bitwise over the
    # serving workloads); it reuses the session serving_gpt + the
    # serving-suite geometry, so the unfused halves of its comparisons
    # ride the already-compiled programs
    "test_decode_megakernel.py",
    "test_speculative.py", "test_distserve.py",
    # test_router is the ISSUE-17 fleet-routing acceptance suite; it
    # reuses the session serving_gpt + the same geometry, so every
    # replica engine rides the already-compiled serving programs
    "test_router.py",
    # test_migration is the ISSUE-20 acceptance suite (live request
    # migration & graceful drain); it reuses the session serving_gpt +
    # the serving-suite geometry, so every engine on both sides of a
    # move rides the already-compiled serving programs
    "test_migration.py",
    # <- unlisted files slot in here (rank _TIER1_DEFAULT)
    # medium density; the budget cutoff lands somewhere below
    "test_fft_signal_distribution.py", "test_op_tail.py",
    "test_rpc_store.py", "test_fleet.py", "test_generation.py",
    "test_ops_table.py", "test_llama.py", "test_analysis_selflint.py",
    "test_launch.py", "test_hapi_vision.py", "test_models.py",
    "test_lenet_e2e.py", "test_elastic.py", "test_moe.py",
    "test_bert.py", "test_vision_models_breadth.py",
    # ISSUE 11's jax<0.5 shard_map fallback (core/meshutil.py) flipped
    # the distributed/pipeline/ring classes green on this machine —
    # they stay tail-ordered (slow compiles, few tests each) but now
    # produce dots; test_scale5's partial-auto (TP-under-GSPMD) class
    # still fails on legacy shard_map and stays last
    "test_multihost.py", "test_distributed.py", "test_pipeline.py",
    "test_ring_attention.py", "test_pipeline_schedules.py",
    "test_scale5.py",
]
_TIER1_RANK = {name: i for i, name in enumerate(_TIER1_ORDER)}
_TIER1_DEFAULT = _TIER1_ORDER.index("test_fft_signal_distribution.py") \
    - 0.5  # unlisted files: right after the pinned acceptance block


def pytest_collection_modifyitems(config, items):
    items.sort(key=lambda it: _TIER1_RANK.get(
        it.fspath.basename, _TIER1_DEFAULT))  # stable: in-file order kept


@pytest.fixture(scope="session")
def serving_gpt():
    """ONE tiny GPT shared by the serving test modules
    (test_serving_engine, test_quant_serving): compiled generate/engine
    programs cache on the model instance, so suites that drive the same
    geometries and prompt lengths reuse each other's programs instead
    of recompiling — tier-1 budget, not semantics (the model is eval
    mode and seeded; sharing changes no numbers)."""
    import numpy as np  # noqa: F401  (keep heavy imports lazy)
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=64, dropout=0.0))
    m.eval()
    return m

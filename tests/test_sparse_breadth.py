"""Sparse op/nn breadth (reference ``python/paddle/sparse/unary.py``,
``binary.py``, ``multiary.py``, ``nn/layer/conv.py``, ``pooling.py``,
``norm.py``, ``activation.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.sparse as sp


def _coo2x2():
    return sp.to_sparse_coo(paddle.to_tensor(
        np.array([[0., 2.], [3., 0.]], np.float32)))


def test_unary_set_pattern_preserving():
    t = _coo2x2()
    for name in ["sin", "tan", "asin", "atan", "sinh", "asinh", "tanh",
                 "square", "sqrt", "abs", "neg", "log1p", "expm1",
                 "rad2deg", "deg2rad"]:
        fn = getattr(sp, name)
        out = fn(t)
        assert out.nnz == t.nnz
        ref = getattr(np, {"abs": "abs", "neg": "negative",
                           "asin": "arcsin", "atan": "arctan",
                           "asinh": "arcsinh"}.get(name, name))
        np.testing.assert_allclose(
            out.to_dense().numpy(),
            ref(np.array([[0., 2.], [3., 0.]], np.float32)),
            rtol=1e-5, atol=1e-6, equal_nan=True)


def test_pow_cast_isnan():
    t = _coo2x2()
    np.testing.assert_allclose(sp.pow(t, 2.0).to_dense().numpy(),
                               [[0., 4.], [9., 0.]])
    c = sp.cast(t, value_dtype="float64")
    assert "float" in str(c.dtype)
    n = sp.isnan(t)
    assert not bool(np.asarray(n.values().numpy()).any())


def test_structural_ops():
    t = _coo2x2()
    assert float(sp.sum(t).numpy()) == 5.0
    np.testing.assert_allclose(sp.sum(t, axis=0).to_dense().numpy(),
                               [3., 2.])
    np.testing.assert_allclose(
        sp.sum(t, axis=1, keepdim=True).to_dense().numpy(),
        [[2.], [3.]])
    np.testing.assert_allclose(sp.transpose(t, [1, 0]).to_dense().numpy(),
                               [[0., 3.], [2., 0.]])
    np.testing.assert_allclose(sp.reshape(t, [4]).to_dense().numpy(),
                               [0., 2., 3., 0.])
    np.testing.assert_allclose(sp.reshape(t, [-1, 1]).to_dense().numpy(),
                               [[0.], [2.], [3.], [0.]])
    np.testing.assert_allclose(sp.slice(t, [0], [1], [2])
                               .to_dense().numpy(), [[3., 0.]])


def test_binary_multiary():
    t = _coo2x2()
    v = sp.mv(t, paddle.to_tensor(np.array([1., 1.], np.float32)))
    np.testing.assert_allclose(v.numpy(), [2., 3.])
    am = sp.addmm(paddle.to_tensor(np.ones((2, 2), np.float32)), t,
                  paddle.to_tensor(np.eye(2, dtype=np.float32)),
                  beta=0.5, alpha=2.0)
    np.testing.assert_allclose(
        am.numpy(), 0.5 + 2.0 * np.array([[0., 2.], [3., 0.]]))
    assert sp.is_same_shape(t, sp.transpose(t, [1, 0]))


@pytest.fixture
def conv_setup():
    rng = np.random.default_rng(0)
    N, D, H, W, Cin, Cout = 2, 5, 6, 7, 3, 4
    dense = np.zeros((N, D, H, W, Cin), np.float32)
    nnz = 40
    coords = np.stack([rng.integers(0, s, nnz)
                       for s in (N, D, H, W)], axis=0)
    vals = rng.normal(size=(nnz, Cin)).astype(np.float32)
    for c, v in zip(coords.T, vals):
        dense[tuple(c)] += v
    x = sp.sparse_coo_tensor(coords, vals, shape=(N, D, H, W, Cin))
    w = rng.normal(size=(3, 3, 3, Cin, Cout)).astype(np.float32)
    b = rng.normal(size=(Cout,)).astype(np.float32)
    return dense, x, w, b


def test_conv3d_matches_dense_oracle(conv_setup):
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF
    dense, x, w, b = conv_setup
    out = sp.nn.functional.conv3d(x, paddle.to_tensor(w),
                                  paddle.to_tensor(b), stride=2,
                                  padding=1)
    got = out.to_dense().numpy()
    tw = torch.tensor(w).permute(4, 3, 0, 1, 2)
    tx = torch.tensor(dense).permute(0, 4, 1, 2, 3)
    ref = TF.conv3d(tx, tw, torch.tensor(b), stride=2, padding=1) \
        .permute(0, 2, 3, 4, 1).numpy()
    mask = np.abs(got).sum(-1) != 0  # sparse emits only active sites
    assert mask.sum() > 0
    np.testing.assert_allclose(got[mask], ref[mask], rtol=1e-4,
                               atol=1e-4)


def test_subm_conv3d_keeps_sites(conv_setup):
    _, x, w, _ = conv_setup
    out = sp.nn.functional.subm_conv3d(x, paddle.to_tensor(w), None,
                                       padding=1)
    oi = np.asarray(out._mat.sum_duplicates(nse=out._mat.nse).indices)
    ii = np.asarray(x._mat.sum_duplicates(nse=x._mat.nse).indices)
    assert set(map(tuple, oi)) == set(map(tuple, ii))


def test_conv2d_single_point():
    # one active site, 1x1 kernel: exact closed form
    x = sp.sparse_coo_tensor(np.array([[0], [1], [2]]),
                             np.array([[2.0, 3.0]], np.float32),
                             shape=(1, 4, 4, 2))
    w = np.array([[[[1.0], [10.0]]]], np.float32)  # 1x1x2x1
    out = sp.nn.functional.conv2d(x, paddle.to_tensor(w))
    d = out.to_dense().numpy()
    assert d.shape == (1, 4, 4, 1)
    np.testing.assert_allclose(d[0, 1, 2, 0], 32.0)
    assert np.abs(d).sum() == 32.0


def test_max_pool3d_active_max(conv_setup):
    _, x, _, _ = conv_setup
    out = sp.nn.functional.max_pool3d(x, 2, stride=2)
    # every output value is the max over its window's ACTIVE inputs
    m = x._mat.sum_duplicates(nse=x._mat.nse)
    in_idx = np.asarray(m.indices)
    vals = np.asarray(m.data)
    om = out._mat
    oi, ov = np.asarray(om.indices), np.asarray(om.data)
    for c, v in zip(oi, ov):
        sel = (in_idx[:, 0] == c[0])
        for d in range(3):
            sel &= (in_idx[:, 1 + d] // 2 == c[1 + d])
        assert sel.any()
        np.testing.assert_allclose(v, vals[sel].max(0), rtol=1e-6)


def test_sparse_layers():
    rng = np.random.default_rng(1)
    coords = np.stack([rng.integers(0, s, 20)
                       for s in (2, 4, 4, 4)], axis=0)
    vals = rng.normal(size=(20, 3)).astype(np.float32)
    x = sp.sparse_coo_tensor(coords, vals, shape=(2, 4, 4, 4, 3))
    paddle.seed(0)
    conv = sp.nn.Conv3D(3, 5, 3, padding=1)
    y = conv(x)
    assert y._shape[-1] == 5
    sub = sp.nn.SubmConv3D(3, 5, 3, padding=1)
    y2 = sub(x)
    assert y2._shape == (2, 4, 4, 4, 5)
    bn = sp.nn.BatchNorm(3)
    yb = bn(x)
    assert yb._shape == x._shape
    v = np.asarray(yb._mat.data)
    np.testing.assert_allclose(v.mean(0), 0.0, atol=1e-4)
    pool = sp.nn.MaxPool3D(2)
    yp = pool(x)
    assert yp._shape == (2, 2, 2, 2, 3)
    assert (sp.nn.ReLU()(x).values().numpy() >= 0).all()
    r6 = sp.nn.ReLU6()(x).values().numpy()
    assert ((r6 >= 0) & (r6 <= 6)).all()
    lr = sp.nn.LeakyReLU(0.1)(x).values().numpy()
    np.testing.assert_allclose(lr, np.where(vals >= 0, vals, 0.1 * vals),
                               rtol=1e-5)


def test_sparse_softmax_rows():
    mat = sp.to_sparse_csr(paddle.to_tensor(
        np.array([[1., 2., 0.], [0., 3., 4.]], np.float32)))
    s = sp.nn.Softmax()(mat)
    v = s.values().numpy()
    np.testing.assert_allclose(v[:2].sum(), 1.0, rtol=1e-5)
    np.testing.assert_allclose(v[2:].sum(), 1.0, rtol=1e-5)
    np.testing.assert_allclose(v[1] / v[0], np.e, rtol=1e-4)


def test_sparse_conv_rulebook_cached_across_calls():
    # static sparsity: the host rulebook must be built once and reused
    import paddle_tpu.sparse.nn.functional as SF

    from paddle_tpu import sparse as sp
    coords = np.array([[0, 0, 0], [0, 1, 3], [0, 2, 3], [0, 3, 3]])
    vals = np.array([[1, 2], [3, 4], [5, 6]], np.float32)
    x = sp.sparse_coo_tensor(coords, vals, shape=(1, 4, 4, 4, 2))
    w = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(3, 3, 3, 2, 4))
        .astype(np.float32))

    SF._RB_CACHE.clear()
    calls = {"n": 0}
    orig = SF._rulebook

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    SF._rulebook = counting
    try:
        y1 = SF.conv3d(x, w)
        y2 = SF.conv3d(x, w)          # same sites: cache hit
        _ = SF.subm_conv3d(x, paddle.to_tensor(
            np.random.default_rng(1).normal(size=(3, 3, 3, 2, 2))
            .astype(np.float32)))     # different geometry: new entry
    finally:
        SF._rulebook = orig
    assert calls["n"] == 2, calls
    np.testing.assert_allclose(y1.to_dense().numpy(),
                               y2.to_dense().numpy(), rtol=1e-6)

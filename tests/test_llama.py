"""LLaMA model family (BASELINE config 4 class): GQA + rope + swiglu +
rms_norm, training convergence under jit, TP sharding parity."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                     shard_llama)

CFG = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
           num_kv_heads=2, max_seq_len=32)


def test_config_defaults():
    cfg = LlamaConfig(hidden_size=4096, num_layers=32, num_heads=32)
    assert cfg.num_kv_heads == 32            # MHA default
    assert cfg.intermediate_size == 11008    # the LLaMA-7B sizing rule
    assert LlamaConfig(**CFG).num_kv_heads == 2  # GQA respected


def test_forward_shapes_and_gqa():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig(**CFG))
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, 128, (2, 16)).astype(np.int32))
    logits = model(ids)
    assert tuple(logits.shape) == (2, 16, 128)
    # kv projections emit num_kv_heads * head_dim features
    att = model.llama.layers[0].attn
    assert tuple(att.k_proj.weight.shape) == (32, 2 * 8)


def test_trains_under_jit():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig(**CFG))
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    rng = np.random.default_rng(1)
    ids = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int32))
    labels = paddle.to_tensor(rng.integers(0, 128, (4, 16))
                              .astype(np.int32))

    @paddle.jit.to_static
    def step(i, l):
        loss = model(i, l)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    losses = [float(step(ids, labels)) for _ in range(10)]
    assert losses[-1] < losses[0] * 0.9, losses


def test_causality():
    """Changing future tokens must not change past logits (rope +
    causal flash attention)."""
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig(**CFG))
    model.eval()
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 128, (1, 16)).astype(np.int32)
    ids2 = ids.copy()
    ids2[0, 10:] = (ids2[0, 10:] + 7) % 128
    with paddle.no_grad():
        a = model(paddle.to_tensor(ids)).numpy()
        b = model(paddle.to_tensor(ids2)).numpy()
    np.testing.assert_allclose(a[0, :10], b[0, :10], atol=1e-5)
    assert np.abs(a[0, 10:] - b[0, 10:]).max() > 1e-4


def test_tp_sharding_parity():
    mesh = dist.ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])
    paddle.seed(0)
    ref = LlamaForCausalLM(LlamaConfig(**CFG))
    paddle.seed(0)
    tp = LlamaForCausalLM(LlamaConfig(**CFG))
    shard_llama(tp, mesh)
    rng = np.random.default_rng(3)
    ids = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int32))
    labels = paddle.to_tensor(rng.integers(0, 128, (4, 16))
                              .astype(np.int32))
    np.testing.assert_allclose(float(ref(ids, labels)),
                               float(tp(ids, labels)), rtol=1e-4)
    # GQA TP constraint enforced
    bad = LlamaForCausalLM(LlamaConfig(vocab_size=64, hidden_size=32,
                                       num_layers=1, num_heads=4,
                                       num_kv_heads=1, max_seq_len=16))
    with pytest.raises(ValueError):
        shard_llama(bad, mesh)


def test_rope_theta_changes_frequencies():
    """rope_theta must actually reach the rotary tables (not dead
    config): different theta -> different logits for the same weights."""
    paddle.seed(0)
    m1 = LlamaForCausalLM(LlamaConfig(**CFG))
    paddle.seed(0)
    m2 = LlamaForCausalLM(LlamaConfig(**{**CFG, "rope_theta": 500000.0}))
    for (n1, p1), (_, p2) in zip(m1.named_parameters(),
                                 m2.named_parameters()):
        p2._write(p1._read())
    rng = np.random.default_rng(4)
    ids = paddle.to_tensor(rng.integers(0, 128, (1, 16)).astype(np.int32))
    with paddle.no_grad():
        a, b = m1(ids).numpy(), m2(ids).numpy()
    assert np.abs(a - b).max() > 1e-4

"""Quantization tests (reference patterns: ``test/quantization/test_qat.py``,
``test_ptq.py``, ``test_weight_only_linear.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (QAT, PTQ, AbsmaxObserver,
                                     FakeQuanterWithAbsMaxObserver,
                                     QuantConfig, QuantedLinear, fake_quant,
                                     weight_dequantize, weight_only_linear,
                                     weight_quantize)

R = np.random.default_rng(3)


def test_weight_quantize_roundtrip():
    w = paddle.to_tensor(R.normal(size=(16, 8)).astype("float32"))
    qw, scale = weight_quantize(w)
    assert str(qw.dtype).endswith("int8") and tuple(scale.shape) == (8,)
    deq = weight_dequantize(qw, scale)
    err = np.abs(np.asarray(deq._read()) - np.asarray(w._read())).max()
    # int8 per-channel: error bounded by scale/2
    assert err <= float(np.asarray(scale._read()).max()) * 0.51


def test_weight_only_linear_matches_dequant_matmul():
    x = paddle.to_tensor(R.normal(size=(4, 16)).astype("float32"))
    w = paddle.to_tensor(R.normal(size=(16, 8)).astype("float32"))
    b = paddle.to_tensor(R.normal(size=(8,)).astype("float32"))
    qw, scale = weight_quantize(w)
    y = weight_only_linear(x, qw, scale, b)
    ref = np.asarray(x._read()) @ np.asarray(
        weight_dequantize(qw, scale)._read()) + np.asarray(b._read())
    np.testing.assert_allclose(np.asarray(y._read()), ref, atol=1e-5)
    # quantization error vs full precision stays small
    full = np.asarray(x._read()) @ np.asarray(w._read())
    rel = np.abs(np.asarray(y._read()) - np.asarray(b._read()) - full)
    assert rel.mean() < 0.05


def test_fake_quant_ste_gradient():
    x = paddle.to_tensor(R.normal(size=(5, 5)).astype("float32"))
    x.stop_gradient = False
    y = fake_quant(x, 2.0)
    # values quantized
    q = np.asarray(y._read())
    steps = q / (2.0 / 127)
    np.testing.assert_allclose(steps, np.round(steps), atol=1e-3)
    y.sum().backward()
    # STE: gradient is identity (ones)
    np.testing.assert_allclose(np.asarray(x.grad._read()),
                               np.ones((5, 5)), atol=1e-6)


def test_qat_quantize_train_convert():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    q = QAT(QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                        weight=FakeQuanterWithAbsMaxObserver))
    net = q.quantize(net)
    assert isinstance(net[0], QuantedLinear)
    net.train()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    xs = R.normal(size=(32, 8)).astype("float32")
    ys = (xs.sum(-1) > 0).astype("int64")
    lossf = nn.CrossEntropyLoss()
    losses = []
    for _ in range(30):
        out = net(paddle.to_tensor(xs))
        loss = lossf(out, paddle.to_tensor(ys))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses[::10]
    net = q.convert(net)
    assert isinstance(net[0], nn.Linear)
    out = net(paddle.to_tensor(xs))
    assert tuple(out.shape) == (32, 2)


def test_ptq_observer_calibration():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 4))
    p = PTQ(QuantConfig(activation=AbsmaxObserver, weight=None))
    net = p.quantize(net)
    net.eval()
    big = np.zeros((2, 4), "float32")
    big[0, 0] = 6.35
    net(paddle.to_tensor(big))
    obs = net[0].act_q
    np.testing.assert_allclose(obs.scale(), 6.35 / 127, rtol=1e-5)
    p.convert(net)
    assert isinstance(net[0], nn.Linear)


def test_weight_only_int4_pack_roundtrip():
    # reference weight_quantize(algo="weight_only_int4"): 2 nibbles/byte
    from paddle_tpu.quantization import (weight_dequantize, weight_quantize,
                                         weight_only_linear)

    rng = np.random.default_rng(0)
    w = paddle.to_tensor(rng.normal(size=(16, 8)).astype(np.float32))
    qw, scale = weight_quantize(w, algo="weight_only_int4")
    assert list(qw.shape) == [8, 8]          # packed: in/2 rows
    assert str(qw.dtype).endswith("int8")
    deq = weight_dequantize(qw, scale, algo="weight_only_int4",
                            in_features=16)
    # int4 grid: max error is scale/2 per element
    err = np.abs(deq.numpy() - w.numpy())
    assert (err <= scale.numpy()[None, :] * 0.5 + 1e-6).all()

    x = paddle.to_tensor(rng.normal(size=(4, 16)).astype(np.float32))
    y = weight_only_linear(x, qw, scale, weight_dtype="int4")
    ref = x.numpy() @ deq.numpy()
    np.testing.assert_allclose(y.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_weight_only_int4_odd_in_features():
    from paddle_tpu.quantization import weight_dequantize, weight_quantize

    rng = np.random.default_rng(1)
    w = paddle.to_tensor(rng.normal(size=(7, 5)).astype(np.float32))
    qw, scale = weight_quantize(w, algo="weight_only_int4")
    assert list(qw.shape) == [4, 5]          # ceil(7/2) packed rows
    deq = weight_dequantize(qw, scale, algo="weight_only_int4",
                            in_features=7)
    assert list(deq.shape) == [7, 5]
    err = np.abs(deq.numpy() - w.numpy())
    assert (err <= scale.numpy()[None, :] * 0.5 + 1e-6).all()


# ----------------------------------------------------------------------
# int4 pack/unpack hardening (ISSUE 7 satellite): odd lengths, negative
# nibbles, end-to-end quantize/dequantize parity, misuse guards
# ----------------------------------------------------------------------

def test_int4_pack_unpack_property_roundtrip():
    """Every nibble value (-8..7) through every odd/even row count:
    _unpack_int4(_pack_int4(q)) must be the identity.  Negative values
    exercise the arithmetic-shift sign extension and the two's-
    complement low-nibble mask."""
    import jax.numpy as jnp

    from paddle_tpu.quantization import _pack_int4, _unpack_int4

    rng = np.random.default_rng(0)
    # exhaustive value sweep in one column
    q = jnp.asarray(np.arange(-8, 8, dtype=np.int8).reshape(16, 1))
    np.testing.assert_array_equal(
        np.asarray(_unpack_int4(_pack_int4(q), 16)), np.asarray(q))
    for rows in (1, 2, 3, 7, 8, 17):
        for cols in (1, 3, 8):
            qv = rng.integers(-8, 8, size=(rows, cols)).astype(np.int8)
            got = np.asarray(_unpack_int4(_pack_int4(jnp.asarray(qv)),
                                          rows))
            np.testing.assert_array_equal(got, qv)


def test_int4_unpack_rejects_wrong_in_features():
    """The old silent truncation/padding is now a coded refusal: an
    ``in_features`` that cannot belong to the packed rows raises
    instead of returning a wrong-shaped weight."""
    import jax.numpy as jnp

    from paddle_tpu.quantization import _pack_int4, _unpack_int4

    p = _pack_int4(jnp.asarray(np.zeros((7, 3), np.int8)))  # 4 rows
    for bad in (0, 5, 9, 100):
        with pytest.raises(ValueError, match="in_features"):
            _unpack_int4(p, bad)
    with pytest.raises(ValueError, match="in_features"):
        weight_dequantize(paddle.to_tensor(np.asarray(p)),
                          paddle.to_tensor(np.ones(3, np.float32)),
                          algo="weight_only_int4", in_features=20)


def test_int4_weight_quantize_dequantize_e2e_parity():
    """quantize -> dequantize -> re-quantize is a FIXED POINT (same
    int codes, same scales): the pack/unpack and the scale arithmetic
    are mutually consistent end to end, negatives included."""
    rng = np.random.default_rng(1)
    for shape in ((7, 5), (16, 8), (1, 1), (2, 3)):
        w = paddle.to_tensor(rng.normal(size=shape).astype(np.float32))
        qw, s = weight_quantize(w, algo="weight_only_int4")
        deq = weight_dequantize(qw, s, algo="weight_only_int4",
                                in_features=shape[0])
        assert tuple(deq.shape) == shape
        qw2, s2 = weight_quantize(deq, algo="weight_only_int4")
        np.testing.assert_array_equal(qw.numpy(), qw2.numpy())
        np.testing.assert_allclose(s.numpy(), s2.numpy(), rtol=1e-6)


# ----------------------------------------------------------------------
# fused weight-only int8 matmul (ISSUE 7 tentpole): kernel-vs-twin
# bitwise in interpret mode, fused routing of weight_only_linear
# ----------------------------------------------------------------------

def test_quant_matmul_kernel_bitwise_vs_jnp_twin():
    """Interpret-mode kernel == the unjitted jnp twin replaying the
    kernel's exact tile walk, BITWISE, across aligned, padded and
    K-gridded geometries (the fused-optimizer parity contract)."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import quant_matmul as qm

    rng = np.random.default_rng(2)
    for (m, k, n) in ((8, 128, 128), (32, 256, 384), (24, 384, 640),
                      (16, 130, 200), (3, 70, 33)):
        x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
        w = jnp.asarray(rng.integers(-127, 128, size=(k, n)), jnp.int8)
        s = jnp.asarray(rng.uniform(0.01, 0.1, size=(n,)), jnp.float32)
        mp = qm._round_up(m, 8)
        kp = qm._round_up(k, 128)
        npad = qm._round_up(n, 128)
        xp = jnp.pad(x, ((0, mp - m), (0, kp - k)))
        wp = jnp.pad(w, ((0, kp - k), (0, npad - n)))
        sp = jnp.pad(s, (0, npad - n))
        blocks = qm.pick_blocks(mp, kp, npad)
        ref = qm.quant_matmul_jnp(xp, wp, sp, blocks=blocks)[:m, :n]
        got = qm.weight_only_matmul(x, w, s, impl="pallas_interpret")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # K-grid accumulation path (bk < K): force a small bk bound
    old = qm._MAX_BK
    qm._MAX_BK = 128
    try:
        x = jnp.asarray(rng.normal(size=(8, 512)), jnp.float32)
        w = jnp.asarray(rng.integers(-127, 128, size=(512, 128)),
                        jnp.int8)
        s = jnp.ones((128,), jnp.float32)
        blocks = qm.pick_blocks(8, 512, 128)
        assert blocks[2] < 512            # really multi-step over K
        ref = qm.quant_matmul_jnp(x, w, s, blocks=blocks)
        got = qm.weight_only_matmul(x, w, s, impl="pallas_interpret")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    finally:
        qm._MAX_BK = old


def test_quant_matmul_blocks_divide_padded_problem():
    from paddle_tpu.ops.pallas import quant_matmul as qm

    for (m, k, n) in ((8, 128, 128), (24, 384, 640), (256, 2176, 512),
                      (8, 2048, 512), (8, 4096, 50304 // 128 * 128)):
        bm, bn, bk = qm.default_blocks(m, k, n)
        assert m % bm == 0 and n % bn == 0 and k % bk == 0
        # x + w(int8 + f32 cast) + acc tiles honor the cap (except the
        # bn=128 floor, which is the minimum legal lane tile)
        assert bn == 128 or (bm * bk + bk * bn * 2 + bm * bn) * 4 \
            <= qm._VMEM_CAP_BYTES


def test_weight_only_linear_routes_through_fused_matmul():
    """The primitive's int8 path is the fused kernel's jnp twin on CPU:
    (x @ q) * s with f32 accumulation — equal to the dequant-then-
    matmul reference within fp rounding, bias and 3-D x included."""
    rng = np.random.default_rng(3)
    x = paddle.to_tensor(rng.normal(size=(2, 5, 16)).astype(np.float32))
    w = paddle.to_tensor(rng.normal(size=(16, 8)).astype(np.float32))
    b = paddle.to_tensor(rng.normal(size=(8,)).astype(np.float32))
    qw, s = weight_quantize(w)
    y = weight_only_linear(x, qw, s, b)
    assert tuple(y.shape) == (2, 5, 8)
    ref = (x.numpy() @ (qw.numpy().astype(np.float32) * s.numpy())
           + b.numpy())
    np.testing.assert_allclose(y.numpy(), ref, atol=1e-5)


# ----------------------------------------------------------------------
# int8 KV quantization helpers (serving write path's one home)
# ----------------------------------------------------------------------

def test_kv_quantize_roundtrip_and_determinism():
    import jax.numpy as jnp

    from paddle_tpu.quantization import kv_dequantize, kv_quantize

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2, 9, 16)), jnp.float32)
    q, s = kv_quantize(x)
    assert str(q.dtype) == "int8" and s.shape == (2, 9)
    # absmax symmetric: error bounded by scale/2 per element
    err = np.abs(np.asarray(kv_dequantize(q, s)) - np.asarray(x))
    assert (err <= np.asarray(s)[..., None] * 0.5 + 1e-7).all()
    # pure per-vector function: bytes independent of batching/order
    q2, s2 = kv_quantize(x[:, 3:4])
    np.testing.assert_array_equal(np.asarray(q[:, 3:4]), np.asarray(q2))
    np.testing.assert_array_equal(np.asarray(s[:, 3:4]), np.asarray(s2))
    # zero vectors: scale 1, exact zeros back
    qz, sz = kv_quantize(jnp.zeros((3, 4)))
    assert (np.asarray(sz) == 1.0).all()
    assert (np.asarray(kv_dequantize(qz, sz)) == 0.0).all()

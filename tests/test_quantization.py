"""Quantization tests (reference patterns: ``test/quantization/test_qat.py``,
``test_ptq.py``, ``test_weight_only_linear.py``)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (QAT, PTQ, AbsmaxObserver,
                                     FakeQuanterWithAbsMaxObserver,
                                     QuantConfig, QuantedLinear, fake_quant,
                                     weight_dequantize, weight_only_linear,
                                     weight_quantize)

R = np.random.default_rng(3)


def test_weight_quantize_roundtrip():
    w = paddle.to_tensor(R.normal(size=(16, 8)).astype("float32"))
    qw, scale = weight_quantize(w)
    assert str(qw.dtype).endswith("int8") and tuple(scale.shape) == (8,)
    deq = weight_dequantize(qw, scale)
    err = np.abs(np.asarray(deq._read()) - np.asarray(w._read())).max()
    # int8 per-channel: error bounded by scale/2
    assert err <= float(np.asarray(scale._read()).max()) * 0.51


def test_weight_only_linear_matches_dequant_matmul():
    x = paddle.to_tensor(R.normal(size=(4, 16)).astype("float32"))
    w = paddle.to_tensor(R.normal(size=(16, 8)).astype("float32"))
    b = paddle.to_tensor(R.normal(size=(8,)).astype("float32"))
    qw, scale = weight_quantize(w)
    y = weight_only_linear(x, qw, scale, b)
    ref = np.asarray(x._read()) @ np.asarray(
        weight_dequantize(qw, scale)._read()) + np.asarray(b._read())
    np.testing.assert_allclose(np.asarray(y._read()), ref, atol=1e-5)
    # quantization error vs full precision stays small
    full = np.asarray(x._read()) @ np.asarray(w._read())
    rel = np.abs(np.asarray(y._read()) - np.asarray(b._read()) - full)
    assert rel.mean() < 0.05


def test_fake_quant_ste_gradient():
    x = paddle.to_tensor(R.normal(size=(5, 5)).astype("float32"))
    x.stop_gradient = False
    y = fake_quant(x, 2.0)
    # values quantized
    q = np.asarray(y._read())
    steps = q / (2.0 / 127)
    np.testing.assert_allclose(steps, np.round(steps), atol=1e-3)
    y.sum().backward()
    # STE: gradient is identity (ones)
    np.testing.assert_allclose(np.asarray(x.grad._read()),
                               np.ones((5, 5)), atol=1e-6)


def test_qat_quantize_train_convert():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    q = QAT(QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                        weight=FakeQuanterWithAbsMaxObserver))
    net = q.quantize(net)
    assert isinstance(net[0], QuantedLinear)
    net.train()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    xs = R.normal(size=(32, 8)).astype("float32")
    ys = (xs.sum(-1) > 0).astype("int64")
    lossf = nn.CrossEntropyLoss()
    losses = []
    for _ in range(30):
        out = net(paddle.to_tensor(xs))
        loss = lossf(out, paddle.to_tensor(ys))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses[::10]
    net = q.convert(net)
    assert isinstance(net[0], nn.Linear)
    out = net(paddle.to_tensor(xs))
    assert tuple(out.shape) == (32, 2)


def test_ptq_observer_calibration():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 4))
    p = PTQ(QuantConfig(activation=AbsmaxObserver, weight=None))
    net = p.quantize(net)
    net.eval()
    big = np.zeros((2, 4), "float32")
    big[0, 0] = 6.35
    net(paddle.to_tensor(big))
    obs = net[0].act_q
    np.testing.assert_allclose(obs.scale(), 6.35 / 127, rtol=1e-5)
    p.convert(net)
    assert isinstance(net[0], nn.Linear)


def test_weight_only_int4_pack_roundtrip():
    # reference weight_quantize(algo="weight_only_int4"): 2 nibbles/byte
    from paddle_tpu.quantization import (weight_dequantize, weight_quantize,
                                         weight_only_linear)

    rng = np.random.default_rng(0)
    w = paddle.to_tensor(rng.normal(size=(16, 8)).astype(np.float32))
    qw, scale = weight_quantize(w, algo="weight_only_int4")
    assert list(qw.shape) == [8, 8]          # packed: in/2 rows
    assert str(qw.dtype).endswith("int8")
    deq = weight_dequantize(qw, scale, algo="weight_only_int4",
                            in_features=16)
    # int4 grid: max error is scale/2 per element
    err = np.abs(deq.numpy() - w.numpy())
    assert (err <= scale.numpy()[None, :] * 0.5 + 1e-6).all()

    x = paddle.to_tensor(rng.normal(size=(4, 16)).astype(np.float32))
    y = weight_only_linear(x, qw, scale, weight_dtype="int4")
    ref = x.numpy() @ deq.numpy()
    np.testing.assert_allclose(y.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_weight_only_int4_odd_in_features():
    from paddle_tpu.quantization import weight_dequantize, weight_quantize

    rng = np.random.default_rng(1)
    w = paddle.to_tensor(rng.normal(size=(7, 5)).astype(np.float32))
    qw, scale = weight_quantize(w, algo="weight_only_int4")
    assert list(qw.shape) == [4, 5]          # ceil(7/2) packed rows
    deq = weight_dequantize(qw, scale, algo="weight_only_int4",
                            in_features=7)
    assert list(deq.shape) == [7, 5]
    err = np.abs(deq.numpy() - w.numpy())
    assert (err <= scale.numpy()[None, :] * 0.5 + 1e-6).all()

"""Disaggregated prefill/decode + tensor-parallel serving (ISSUE 13).

Acceptance model: a TP-sharded engine (``mesh=``/``tp_axis=``) and a
``DisaggServer`` prefill->handoff->decode run must both produce EXACTLY
the greedy token streams of the single-device colocated engine — TP is
a layout, disaggregation a transport; neither may move a token — with
the allocator's pool conservation holding on every engine involved.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (ContinuousBatchingEngine, DisaggServer,
                                  KVPageTransport)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.resilience import faults

from test_serving_engine import _assert_pool_conserved

# ONE geometry for the whole module (matches test_serving_engine's, so
# single-device programs come off the session model's cache; the TP
# programs cache on the model per (geometry, mesh) too, so every test
# here reuses the first one's compiles)
KW = dict(max_slots=2, page_size=8, max_seq_len=32, decode_window=4,
          prefill_chunk=8, q_block=2)


@pytest.fixture(scope="module")
def gpt(serving_gpt):
    return serving_gpt


@pytest.fixture(scope="module")
def mesh2():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:2]), ("tp",))


@pytest.fixture(scope="module")
def mesh4():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:4]), ("tp",))


def _workload(seed=0, sizes=(5, 9, 3, 12), new=(6, 4, 7, 5)):
    rng = np.random.default_rng(seed)
    return ([rng.integers(0, 96, (n,)).astype(np.int32)
             for n in sizes], list(new))


def _drive(model, mesh=None, prompts=None, new=None, **kw):
    eng = ContinuousBatchingEngine(model, mesh=mesh, **{**KW, **kw})
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    return [done[r].sequence for r in rids], eng


@pytest.fixture(scope="module")
def refs(gpt):
    """Single-device engine streams for the shared workload — the bar
    every TP/disagg variant must hit bitwise."""
    prompts, new = _workload()
    seqs, eng = _drive(gpt, None, prompts, new)
    _assert_pool_conserved(eng)
    return prompts, new, seqs


# ======================================================== TP engine ==

def test_tp2_matches_single_device_slot_contention(gpt, mesh2, refs):
    """4 ragged requests through 2 slots on a TP=2 mesh: admission,
    chunked prefill, decode windows and retirement all run over
    head-sharded pools with one psum per layer pair — token streams
    must be EXACTLY the single-device engine's."""
    prompts, new, seqs = refs
    out, eng = _drive(gpt, mesh2, prompts, new)
    for a, b in zip(out, seqs):
        np.testing.assert_array_equal(a, b)
    assert eng.tp == 2
    _assert_pool_conserved(eng)


def test_tp2_shared_prefix_and_cow(gpt, mesh2):
    """Prefix cache + COW on sharded pools: same-prefix twins map the
    radix index over TP pools (the COW page copy is one donated
    sharded dispatch) — bitwise vs the single-device engine, with
    cache hits actually happening."""
    rng = np.random.default_rng(11)
    shared = rng.integers(0, 96, (16,)).astype(np.int32)
    tail = rng.integers(0, 96, (3,)).astype(np.int32)
    prompts = [shared, shared,                      # full-page COW hit
               np.concatenate([shared[:8], tail])]  # partial hit
    new = [4, 4, 4]

    def run(mesh):
        eng = ContinuousBatchingEngine(gpt, mesh=mesh, **KW)
        r0 = eng.add_request(prompts[0], new[0])
        first = eng.run()                 # publish, then hit the cache
        rs = [eng.add_request(p, n)
              for p, n in zip(prompts[1:], new[1:])]
        done = eng.run()
        seqs = [first[r0].sequence] + [done[r].sequence for r in rs]
        return seqs, eng

    ref, _ = run(None)
    out, eng = run(mesh2)
    for a, b in zip(out, ref):
        np.testing.assert_array_equal(a, b)
    assert eng.stats["cache_hits"] >= 2
    _assert_pool_conserved(eng)


def test_tp2_kv_quant(gpt, mesh2, refs):
    """int8 KV pages under TP: data AND scale side-pools shard by
    kv-head; per-(head, slot) absmax quantization is head-local, so
    quantized bytes match the single-device engine's and greedy
    streams are token-identical."""
    prompts, new, _ = refs
    ref, _ = _drive(gpt, None, prompts, new, kv_quant=True)
    out, eng = _drive(gpt, mesh2, prompts, new, kv_quant=True)
    for a, b in zip(out, ref):
        np.testing.assert_array_equal(a, b)
    assert eng.kv_quant
    _assert_pool_conserved(eng)


def test_tp2_spec_decode(gpt, mesh2, refs):
    """Speculative decoding through the TP verify program (n-gram
    proposer): greedy spec on a TP mesh is bitwise vs BOTH the
    single-device spec engine and the plain stream."""
    prompts, new, seqs = refs
    out, eng = _drive(gpt, mesh2, prompts, new, spec_decode=True,
                      spec_k=3)
    for a, b in zip(out, seqs):
        np.testing.assert_array_equal(a, b)
    assert eng.stats["spec_accepted"] >= 0  # counters wired
    assert eng.stats["decode_dispatches"] > 0
    _assert_pool_conserved(eng)


def test_tp_llama_gqa_both_regimes(mesh2, mesh4):
    """GQA awareness: Hk=2 heads shard over tp=2 (Hk % tp == 0) and
    REPLICATE over tp=4 (each pair of shards attends a 1-head slice
    of the replicated pools) — both bitwise vs single-device."""
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64))
    m.eval()
    prompts, new = _workload(seed=3, sizes=(7, 4, 11), new=(5, 6, 4))
    ref, _ = _drive(m, None, prompts, new)
    for mesh in (mesh2, mesh4):
        out, eng = _drive(m, mesh, prompts, new)
        for a, b in zip(out, ref):
            np.testing.assert_array_equal(a, b)
        assert eng._tpp.meta["shard_kv"] == (mesh is mesh2)
        _assert_pool_conserved(eng)


def test_tp_validation(gpt, mesh2):
    """Head counts the Megatron cut cannot serve fail EAGERLY with a
    clear error, and a multi-axis mesh demands an explicit tp_axis."""
    import jax
    from jax.sharding import Mesh
    paddle.seed(0)
    bad = LlamaForCausalLM(LlamaConfig(
        vocab_size=96, hidden_size=48, num_layers=1, num_heads=3,
        num_kv_heads=3, max_seq_len=64))
    bad.eval()
    with pytest.raises(ValueError, match="num_heads"):
        ContinuousBatchingEngine(bad, mesh=mesh2, **KW)
    two_axis = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                    ("a", "b"))
    with pytest.raises(ValueError, match="tp_axis"):
        ContinuousBatchingEngine(gpt, mesh=two_axis, **KW)


# ================================================= pool export/import ==

def test_export_import_roundtrip(gpt, refs):
    """Engine-level handoff substrate: prefill on one engine, export
    at the first token, import into a FRESH engine, finish decoding
    there — the stitched stream is bitwise the uninterrupted one, and
    both pools conserve."""
    prompts, new, seqs = refs
    src = ContinuousBatchingEngine(gpt, **KW)
    rid = src.add_request(prompts[0], 1)
    src.run()                      # slot retires after its one token —
    # re-admit and step until the first token is resident instead
    src2 = ContinuousBatchingEngine(gpt, **KW)
    rid = src2.add_request(prompts[0], 1)
    payload = None
    for _ in range(100):
        src2.step()
        try:
            payload = src2.export_request(rid)
            break
        except (KeyError, ValueError):
            continue
    assert payload is not None
    dst = ContinuousBatchingEngine(gpt, **KW)
    got = dst.import_request(payload, new[0])
    assert got == rid
    done = dst.run()
    np.testing.assert_array_equal(done[rid].sequence, seqs[0])
    src2.run()
    _assert_pool_conserved(src2)
    _assert_pool_conserved(dst)
    # layout validation: mismatched page_size must refuse
    other = ContinuousBatchingEngine(gpt, **{**KW, "page_size": 16,
                                             "max_seq_len": 32})
    with pytest.raises(ValueError, match="page_size"):
        other.import_request(payload, new[0])


# ======================================================= DisaggServer ==

def _disagg_run(gpt, prompts, new, **srv_kw):
    srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                       decode_kwargs=dict(KW), **srv_kw)
    rids = [srv.add_request(p, n) for p, n in zip(prompts, new)]
    done = srv.run()
    return [done[r] for r in rids], srv


def test_disagg_bitwise_vs_colocated(gpt, refs):
    """The acceptance run: prefill group -> KV-page handoff -> decode
    group, bitwise vs the colocated engine, pool conservation holding
    on BOTH groups after the drain."""
    prompts, new, seqs = refs
    out, srv = _disagg_run(gpt, prompts, new)
    for c, ref in zip(out, seqs):
        np.testing.assert_array_equal(c.sequence, ref)
        assert c.ok
    st = srv.stats
    assert st["handoffs"] == len(prompts)
    assert st["handoff_bytes"] > 0
    for eng in srv.prefill_group + srv.decode_group:
        _assert_pool_conserved(eng)
    # handoff observability: histogram counted every transfer
    node = srv.metrics()["serving"]["handoff_ms"]
    assert node["count"] == len(prompts)


def test_disagg_handoff_transient_drill(gpt, refs):
    """Two injected ConnectionErrors on the transport are absorbed by
    the bounded retry; outputs stay bitwise and the retry counter
    records exactly two."""
    prompts, new, seqs = refs
    faults.clear()
    faults.inject("engine_handoff_transient", "*", times=2)
    try:
        out, srv = _disagg_run(gpt, prompts, new)
    finally:
        faults.clear()
    for c, ref in zip(out, seqs):
        np.testing.assert_array_equal(c.sequence, ref)
    assert srv.stats["handoff_retries"] == 2
    assert srv.stats["handoffs"] == len(prompts)


def test_disagg_decode_worker_lost_drill(gpt, refs):
    """A decode worker lost at handoff time: the payload is discarded,
    the request requeues to the prefill group and re-prefills from
    token zero — outputs bitwise, only ``requeues`` moves."""
    prompts, new, seqs = refs
    faults.clear()
    faults.inject("engine_decode_worker_lost", "1", times=1)
    try:
        out, srv = _disagg_run(gpt, prompts, new)
    finally:
        faults.clear()
    for c, ref in zip(out, seqs):
        np.testing.assert_array_equal(c.sequence, ref)
    assert srv.stats["requeues"] == 1
    req = srv._reqs[1]
    assert req.requeues == 1
    for eng in srv.prefill_group + srv.decode_group:
        _assert_pool_conserved(eng)


def test_disagg_eos_at_first_token(gpt, refs):
    """An eos produced by the prefill itself completes on the prefill
    side — no handoff ships, the result is reason='stop'."""
    prompts, new, seqs = refs
    eos = int(seqs[0][prompts[0].size])       # its first generated tok
    srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                       decode_kwargs=dict(KW))
    rid = srv.add_request(prompts[0], new[0], eos_token_id=eos)
    done = srv.run()
    assert done[rid].finish_reason == "stop"
    np.testing.assert_array_equal(done[rid].tokens, [eos])
    assert srv.stats["handoffs"] == 0


def test_disagg_prefix_cache_survives_handoff(gpt):
    """Decode-side publish: after the first request retires on the
    decode group, a second identical-prompt request's import RETAINS
    the decode cache's pages instead of re-scattering, and the
    prefill side's own cache cuts its recomputed prefill tokens."""
    rng = np.random.default_rng(21)
    prompt = rng.integers(0, 96, (16,)).astype(np.int32)
    ref, _ = _drive(gpt, None, [prompt], [4])
    srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                       decode_kwargs=dict(KW))
    r1 = srv.add_request(prompt, 4)
    d1 = srv.run()
    r2 = srv.add_request(prompt, 4)
    d2 = srv.run()
    np.testing.assert_array_equal(d1[r1].sequence, ref[0])
    np.testing.assert_array_equal(d2[r2].sequence, ref[0])
    dec = srv.decode_group[0]
    pre = srv.prefill_group[0]
    assert dec.stats["cache_hits"] >= 1           # import retained
    assert pre.stats["cache_hits"] >= 1           # prefill-side reuse
    assert pre.stats["prefill_tokens_computed"] \
        < pre.stats["prefill_tokens_requested"]
    for eng in (pre, dec):
        _assert_pool_conserved(eng)


def test_disagg_tp_decode_group(gpt, mesh2, refs):
    """Groups compose with TP: a single-device prefill group handing
    off to a TP=2-sharded decode group stays bitwise (the payload is
    layout-neutral — import scatters into sharded pools)."""
    prompts, new, seqs = refs
    srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                       decode_kwargs={**KW, "mesh": mesh2})
    rids = [srv.add_request(p, n) for p, n in zip(prompts, new)]
    done = srv.run()
    for r, ref in zip(rids, seqs):
        np.testing.assert_array_equal(done[r].sequence, ref)
    assert srv.decode_group[0].tp == 2
    for eng in srv.prefill_group + srv.decode_group:
        _assert_pool_conserved(eng)


def test_disagg_rpc_transport(gpt, refs):
    """The handoff bytes cross a real rpc agent (loopback worker):
    same payload, same retry envelope, bitwise output."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.inference import register_decode_worker
    prompts, new, seqs = refs
    rpc.init_rpc("disagg_w0", rank=0, world_size=1)
    try:
        srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                           decode_kwargs=dict(KW),
                           transport=KVPageTransport(to="disagg_w0"))
        register_decode_worker("disagg_w0", srv.decode_group[0])
        rids = [srv.add_request(p, n) for p, n in zip(prompts, new)]
        done = srv.run()
        for r, ref in zip(rids, seqs):
            np.testing.assert_array_equal(done[r].sequence, ref)
        assert srv.stats["handoffs"] == len(prompts)
    finally:
        rpc.shutdown()


def test_disagg_worker_lost_two_prefill_workers(gpt, refs):
    """Worker-lost requeue with prefill_workers=2: the in-flight
    guard unions BOTH engines, so the requeued rid cannot be
    double-admitted on the other worker while its old slot drains
    (review regression: a truncated duplicate 1-token result)."""
    prompts, new, seqs = refs
    faults.clear()
    faults.inject("engine_decode_worker_lost", "*", times=1)
    try:
        srv = DisaggServer(gpt, prefill_workers=2,
                           prefill_kwargs=dict(KW),
                           decode_kwargs=dict(KW))
        rids = [srv.add_request(p, n) for p, n in zip(prompts, new)]
        done = srv.run()
    finally:
        faults.clear()
    assert sorted(done) == sorted(rids)         # no duplicates/losses
    for r, ref in zip(rids, seqs):
        np.testing.assert_array_equal(done[r].sequence, ref)
        assert done[r].ok
    assert srv.stats["requeues"] == 1


def test_disagg_single_token_budget(gpt, refs):
    """max_new_tokens=1: the prefill result IS the final result — no
    handoff ships, and the one token matches the colocated engine's
    (review regression: this used to crash import_request with
    'request already complete')."""
    prompts, new, seqs = refs
    srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                       decode_kwargs=dict(KW))
    rid = srv.add_request(prompts[0], 1)
    done = srv.run()
    np.testing.assert_array_equal(done[rid].tokens,
                                  seqs[0][prompts[0].size:
                                          prompts[0].size + 1])
    assert done[rid].finish_reason == "length"
    assert srv.stats["handoffs"] == 0


def test_disagg_oversize_rejected_eagerly(gpt):
    """A request the DECODE group can never hold fails at
    add_request, not mid-handoff (review regression: the prefill
    group's 1-token budget used to let it admit and crash step())."""
    from paddle_tpu.core.errors import PageBudgetError
    srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                       decode_kwargs=dict(KW))
    with pytest.raises(ValueError, match="decode-group max_seq_len"):
        srv.add_request(np.zeros(8, np.int32), 100)
    small = {**KW, "total_pages": 3}
    srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                       decode_kwargs=small)
    with pytest.raises(PageBudgetError):
        srv.add_request(np.zeros(8, np.int32), 20)


def test_disagg_deadline_spans_handoff(gpt, refs):
    """The deadline is ONE budget armed at coordinator admission:
    a request whose TTL expires while parked between prefill and
    decode times out instead of getting a fresh deadline on the
    decode side (review regression)."""
    prompts, new, _ = refs
    t = [0.0]
    clock = lambda: t[0]
    srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                       decode_kwargs=dict(KW), clock=clock)
    rid = srv.add_request(prompts[0], new[0], deadline_ms=50.0)
    # run prefill up to the export, then let the clock blow the TTL
    # while the payload sits in the handoff queue
    for _ in range(50):
        srv._submit_pending()
        for eng in srv.prefill_group:
            eng.step()
            srv._export_first_tokens(eng)
        if srv._ready:
            break
    assert srv._ready, "first token never exported"
    t[0] = 1.0                                  # 1000 ms >> 50 ms TTL
    done = srv.run()
    assert done[rid].finish_reason == "timeout"
    assert srv.stats["handoffs"] == 0


def test_disagg_handoff_retries_exhausted_keeps_payloads(gpt, refs):
    """A handoff whose transient never clears raises out of step()
    after the bounded retries — but the payload (and every other
    parked payload) stays in the handoff queue, so clearing the fault
    and stepping again completes everything (review regression: the
    queue used to be lost mid-loop)."""
    prompts, new, seqs = refs
    faults.clear()
    faults.inject("engine_handoff_transient", "*", times=0)  # forever
    try:
        srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                           decode_kwargs=dict(KW))
        rids = [srv.add_request(p, n) for p, n in zip(prompts, new)]
        with pytest.raises(ConnectionError):
            for _ in range(100):
                srv.step()
        assert srv._ready, "failed payload must stay queued"
    finally:
        faults.clear()
    done = srv.run()                        # fault gone: self-heals
    for r, ref in zip(rids, seqs):
        np.testing.assert_array_equal(done[r].sequence, ref)


def test_disagg_prefill_pool_validated_eagerly(gpt):
    """A prompt the PREFILL pool can never hold fails at add_request
    instead of poisoning _submit_pending forever (review
    regression)."""
    from paddle_tpu.core.errors import PageBudgetError
    srv = DisaggServer(gpt,
                       prefill_kwargs={**KW, "total_pages": 2},
                       decode_kwargs=dict(KW))
    with pytest.raises(PageBudgetError):
        srv.add_request(np.zeros(16, np.int32), 4)
    # and the server still serves admissible requests afterwards
    rid = srv.add_request(np.zeros(4, np.int32), 2)
    assert rid in srv.run()


def test_import_failure_releases_pages(gpt, refs):
    """An import whose scatter dispatch exhausts its retries releases
    every acquired/retained page before propagating — repeated caller
    retries must not drain the pool (review regression)."""
    prompts, new, _ = refs
    src = ContinuousBatchingEngine(gpt, **KW)
    rid = src.add_request(prompts[1], 1)
    payload = None
    for _ in range(100):
        src.step()
        try:
            payload = src.export_request(rid)
            break
        except (KeyError, ValueError):
            continue
    assert payload is not None
    dst = ContinuousBatchingEngine(gpt, **KW, dispatch_retries=0)
    faults.clear()
    faults.inject("engine_dispatch", "import", times=0)   # every time
    try:
        for _ in range(3):                  # caller retry loop
            with pytest.raises(ConnectionError):
                dst.import_request(payload, new[1])
    finally:
        faults.clear()
    _assert_pool_conserved(dst)             # nothing leaked
    # fault gone: the same import now succeeds and decodes bitwise
    got = dst.import_request(payload, new[1])
    assert got == rid
    src.run()


def test_import_advances_auto_rid(gpt, refs):
    """An imported integer rid advances the auto counter so a later
    request_id=None add_request cannot collide with the resident
    import (review regression)."""
    prompts, new, _ = refs
    src = ContinuousBatchingEngine(gpt, **KW)
    rid = src.add_request(prompts[2], 1, request_id=5)
    payload = None
    for _ in range(100):
        src.step()
        try:
            payload = src.export_request(rid)
            break
        except (KeyError, ValueError):
            continue
    dst = ContinuousBatchingEngine(gpt, **KW)
    assert dst.import_request(payload, new[2]) == 5
    auto = dst.add_request(prompts[0], 2)
    assert auto == 6                        # not 0..5
    src.run()
    dst.run()
    _assert_pool_conserved(dst)


# ====================================================== bench smoke ==

def test_serving_bench_rows_smoke(gpt):
    """The tp2/disagg serving_bench rows run on the CPU mesh with the
    suite's tiny geometry and report sane accounting (absolute times
    are TPU claims; the gates here are outputs_equal, byte counts and
    pool conservation)."""
    import sys
    sys.path.insert(0, "/root/repo/benchmarks")
    import serving_bench as sb
    cfg = gpt.cfg
    row = sb._measure_tp(cfg, gpt, 819.0, 2, slots=2, prompt_len=10,
                         new_tokens=5, page_size=8, decode_window=4,
                         prefill_chunk=8, q_block=2, max_seq_len=32,
                         warm=False)
    assert row["outputs_equal"] and row["pages_leaked"] == 0
    assert row["roofline_ms"] < row["roofline_ms_1dev"]
    row = sb._measure_disagg(cfg, gpt, slots=2, prompt_len=10,
                             new_tokens=6, storm_prompt=20,
                             storm_new=2, n_latency=2, n_storm=3,
                             page_size=8, decode_window=4,
                             prefill_chunk=8, max_seq_len=32,
                             q_block=2, warm=False)
    assert row["handoffs"] >= 2
    assert row["transfer_bytes"] > 0
    assert row["pages_leaked"] == 0

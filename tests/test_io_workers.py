"""Multiprocess DataLoader workers: fork + shared-memory handoff
(reference io/reader.py:216, io/dataloader/worker.py; VERDICT r4
missing #5)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class _SquareDS(paddle.io.Dataset):
    def __len__(self):
        return 23

    def __getitem__(self, i):
        return (np.full((3,), i, np.float32), np.int64(i * i))


def test_mp_workers_match_inprocess_order_and_values():
    from paddle_tpu.io import DataLoader

    a = list(DataLoader(_SquareDS(), batch_size=4, shuffle=False,
                        num_workers=0))
    b = list(DataLoader(_SquareDS(), batch_size=4, shuffle=False,
                        num_workers=3, use_shared_memory=True))
    assert len(a) == len(b) == 6
    for (xa, ya), (xb, yb) in zip(a, b):
        np.testing.assert_array_equal(xa.numpy(), xb.numpy())
        np.testing.assert_array_equal(ya.numpy(), yb.numpy())


class _WorkerProbeDS(paddle.io.Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        from paddle_tpu.io import get_worker_info
        info = get_worker_info()
        assert info is not None and 0 <= info.id < info.num_workers
        return np.full((2,), info.id, np.float32)


def test_mp_workers_expose_worker_info():
    from paddle_tpu.io import DataLoader, get_worker_info

    assert get_worker_info() is None  # trainer process
    out = list(DataLoader(_WorkerProbeDS(), batch_size=2, shuffle=False,
                          num_workers=2))
    assert len(out) == 4


class _CrashDS(paddle.io.Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            import os
            os._exit(3)          # simulated segfault in user data code
        return np.float32(i)


def test_mp_worker_crash_is_isolated():
    from paddle_tpu.io import DataLoader

    loader = DataLoader(_CrashDS(), batch_size=2, shuffle=False,
                        num_workers=2, timeout=60)
    with pytest.raises(RuntimeError, match="died|failed"):
        list(loader)


class _ShardedIterable(paddle.io.IterableDataset):
    """Shards itself via get_worker_info — the reference/torch contract
    (the loader must NOT also stride, or data would be lost)."""

    def __iter__(self):
        from paddle_tpu.io import get_worker_info
        info = get_worker_info()
        wid = info.id if info else 0
        n = info.num_workers if info else 1
        for i in range(12):
            if i % n == wid:
                yield np.full((2,), i, np.float32)


def test_mp_workers_iterable_dataset_shards_itself():
    from paddle_tpu.io import DataLoader

    out = list(DataLoader(_ShardedIterable(), batch_size=3,
                          num_workers=2))
    got = sorted(int(b.numpy()[r, 0]) for b in out
                 for r in range(b.shape[0]))
    assert got == list(range(12))


class _TensorDS(paddle.io.Dataset):
    """Dataset returning framework Tensors (worked via the threaded
    path pre-r5; must keep working through forked workers)."""

    def __len__(self):
        return 6

    def __getitem__(self, i):
        return paddle.to_tensor(np.full((2,), i, np.float32))


def test_mp_workers_accept_tensor_datasets():
    from paddle_tpu.io import DataLoader

    out = list(DataLoader(_TensorDS(), batch_size=2, shuffle=False,
                          num_workers=2))
    assert len(out) == 3
    np.testing.assert_array_equal(out[0].numpy()[:, 0], [0.0, 1.0])


def test_mp_workers_early_break_leaks_no_shm():
    import glob

    from paddle_tpu.io import DataLoader

    before = set(glob.glob("/dev/shm/psm_*"))
    loader = DataLoader(_SquareDS(), batch_size=2, shuffle=False,
                        num_workers=2)
    for step, _batch in enumerate(loader):
        if step == 1:
            break
    import time
    time.sleep(0.5)
    leaked = set(glob.glob("/dev/shm/psm_*")) - before
    assert not leaked, f"leaked shm segments: {leaked}"


def test_mp_workers_large_dataset_no_deadlock():
    # code-review r5: enqueue-all-then-drain deadlocked once the task
    # pipe filled; the bounded in-flight window must stream any size
    from paddle_tpu.io import DataLoader

    class Big(paddle.io.Dataset):
        def __len__(self):
            return 4000

        def __getitem__(self, i):
            return np.full((8,), i, np.float32)

    n = 0
    for batch in DataLoader(Big(), batch_size=8, shuffle=False,
                            num_workers=2, timeout=120):
        n += 1
    assert n == 500


def test_consumer_shm_attach_untracked(monkeypatch):
    """ADVICE r5 low: attaching (create=False) registers the segment
    with the CONSUMER's resource_tracker on CPython <= 3.12; since
    _decode immediately unlinks, that registration must be dropped or
    the tracker reports 'leaked shared_memory' at shutdown.  The
    register/unregister calls seen by this process must balance."""
    from multiprocessing import resource_tracker

    from paddle_tpu.io import worker as w

    calls = {"register": [], "unregister": []}
    orig_reg = resource_tracker.register
    orig_unreg = resource_tracker.unregister

    def reg(name, rtype):
        if rtype == "shared_memory":
            calls["register"].append(name)
        return orig_reg(name, rtype)

    def unreg(name, rtype):
        if rtype == "shared_memory":
            calls["unregister"].append(name)
        return orig_unreg(name, rtype)

    monkeypatch.setattr(resource_tracker, "register", reg)
    monkeypatch.setattr(resource_tracker, "unregister", unreg)
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    # in-process round trip exercises BOTH sides' tracker bookkeeping:
    # _encode (creator) and _decode (consumer attach + unlink)
    desc = w._encode({"x": arr, "n": 3})
    out = w._decode(desc)
    np.testing.assert_array_equal(out["x"], arr)
    assert sorted(calls["register"]) == sorted(calls["unregister"])
    # the abandoned-batch path unlinks AND untracks too
    desc2 = w._encode([arr])
    w._unlink_desc(desc2)
    assert sorted(calls["register"]) == sorted(calls["unregister"])

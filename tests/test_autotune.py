"""Kernel autotune tests (reference pattern:
``test/legacy_test/test_switch_autotune.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.pallas import autotune as at


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path, monkeypatch):
    monkeypatch.setattr(at, "_CACHE_PATH", str(tmp_path / "autotune.json"))
    monkeypatch.setattr(at, "_cache", None)
    yield
    at._config["kernel"]["enable"] = False


def test_set_config_and_enabled():
    assert not at.enabled()
    paddle.incubate.autotune.set_config({"kernel": {"enable": True}})
    assert at.enabled()
    paddle.incubate.autotune.set_config({"kernel": {"enable": False}})
    assert not at.enabled()


def test_autotune_picks_fastest_and_caches():
    import time
    calls = []

    def run(cand):
        calls.append(cand)
        time.sleep(0.02 if cand == "slow" else 0.001)

    best = at.autotune("myop", "sig1", ["slow", "fast"], run, repeats=2)
    assert best == "fast"
    n = len(calls)
    # cached: second query runs nothing
    best2 = at.autotune("myop", "sig1", ["slow", "fast"], run)
    assert best2 == "fast" and len(calls) == n
    # persisted: fresh in-memory cache reads the file
    at._cache = None
    best3 = at.autotune("myop", "sig1", ["slow", "fast"], run)
    assert best3 == "fast" and len(calls) == n


def test_autotune_skips_failing_candidates():
    def run(cand):
        if cand == "bad":
            raise RuntimeError("vmem overflow")

    assert at.autotune("op2", "s", ["bad", "good"], run) == "good"
    with pytest.raises(RuntimeError):
        at.autotune("op3", "s", ["bad"], lambda c: run("bad"))


def test_flash_attention_block_override_parity():
    """Explicit block sizes must not change numerics (interpret mode)."""
    from paddle_tpu.ops.pallas import flash_attention as fa
    rng = np.random.default_rng(0)
    q = rng.normal(size=(1, 128, 2, 16)).astype("float32")
    k = rng.normal(size=(1, 128, 2, 16)).astype("float32")
    v = rng.normal(size=(1, 128, 2, 16)).astype("float32")
    import jax.numpy as jnp
    base = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=True, interpret=True)
    alt = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), causal=True, interpret=True,
                             blocks=(64, 32))
    np.testing.assert_allclose(np.asarray(base), np.asarray(alt),
                               atol=2e-5)

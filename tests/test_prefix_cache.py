"""Prefix-cache allocator invariants (ISSUE 6).

The radix index layers per-page refcounts onto the serving engine's
free list; these tests pin the conservation law the whole design rests
on — every page is in exactly ONE of {free, in-use (ref>0), cached
(ref-0, indexed)}, the null page 0 never circulates, nothing leaks and
nothing double-frees — plus the index semantics (page-granular
matching, incumbent-wins publication, leaf-first LRU eviction, the
``engine_cache_evict`` drill, PDT-E019 on corruption).

The randomized property test replays the engine's exact allocation
discipline (admit with match/retain/acquire + the COW divergence-page
rule, decode growth, retire-with-publish, preempt, cancel, forced
eviction) for >1000 mixed steps with ``PrefixCache.check()`` after
every mutation — no model dispatches, so it runs in milliseconds.
"""
from collections import deque

import numpy as np
import pytest

from paddle_tpu.core import errors
from paddle_tpu.inference.prefix_cache import PrefixCache
from paddle_tpu.resilience import faults

PS = 4          # page_size
TOTAL = 33      # total_pages (32 usable; page 0 reserved null)


def _mk(enabled=True, total=TOTAL):
    free = deque(range(1, total))
    return PrefixCache(PS, free, enabled=enabled), free


def _ids(rng, n):
    return rng.integers(0, 50, (n,)).astype(np.int32)


# ======================================================================
# unit semantics
# ======================================================================

def test_match_publish_roundtrip():
    cache, free = _mk()
    rng = np.random.default_rng(0)
    ids = _ids(rng, 11)                       # 2 full pages + tail
    pages = [cache.acquire() for _ in range(3)]
    assert cache.publish(ids, pages, 11) == 2  # only FULL pages indexed
    cache.release(pages)
    assert cache.cached_pages == 2 and len(free) == TOTAL - 1 - 2
    # longest-prefix walk: full match, then divergence at page 2
    assert cache.match(ids) == pages[:2]
    other = ids.copy()
    other[PS] += 1                            # diverge inside page 2
    assert cache.match(other) == pages[:1]
    assert cache.match(_ids(rng, 20)) == []   # cold prefix
    cache.check()


def test_publish_incumbent_wins():
    """Two residents prefilling the same prefix concurrently: the first
    publication owns the index, the twin's duplicate pages stay private
    and return to the free list on release."""
    cache, free = _mk()
    ids = np.arange(PS, dtype=np.int32)
    a = [cache.acquire()]
    b = [cache.acquire()]
    assert cache.publish(ids, a, PS) == 1
    assert cache.publish(ids, b, PS) == 0     # incumbent keeps the node
    cache.release(a)
    cache.release(b)
    assert cache.cached_pages == 1
    assert cache.match(ids) == a and b[0] in free
    cache.check()


def test_retain_pins_against_eviction():
    """A matched-and-retained path is ref>0: not evictable even under
    the forced-eviction drill; releasing it re-enters the LRU pool."""
    cache, free = _mk()
    ids = np.arange(2 * PS, dtype=np.int32)
    pages = [cache.acquire(), cache.acquire()]
    cache.publish(ids, pages, 2 * PS)
    cache.release(pages)
    assert cache.cached_pages == 2
    got = cache.match(ids)
    cache.retain(got)
    assert cache.cached_pages == 0            # pinned, off the LRU
    faults.clear()
    try:
        faults.inject("engine_cache_evict", times=0)
        pg = cache.acquire()                  # drill: nothing evictable
        assert cache.evictions == 0 and pg is not None
        cache.release([pg])
        cache.release(got)
        assert cache.cached_pages == 2        # back to evictable
        pg = cache.acquire()                  # drill: now it evicts
        assert cache.evictions == 1
        cache.release([pg])
    finally:
        faults.clear()
    cache.check()


def test_eviction_leaf_first_lru():
    """Eviction takes trie LEAVES oldest-first: an interior page waits
    until its subtree drains (children would become unreachable), so a
    chain evicts tip-to-root."""
    cache, _free = _mk(total=1 + 3)           # 3 usable pages
    ids = np.arange(3 * PS, dtype=np.int32)
    pages = [cache.acquire() for _ in range(3)]
    cache.publish(ids, pages, 3 * PS)
    cache.release(pages)                      # chain p0 -> p1 -> p2
    assert cache.available() == 3 and not _free
    got = cache.acquire()                     # must evict to serve
    assert got == pages[2]                    # leaf first, not the root
    assert cache.match(ids) == pages[:2]      # prefix remnant survives
    got2 = cache.acquire()
    assert got2 == pages[1]
    cache.release([got, got2])
    cache.check()


def test_double_release_raises_coded():
    cache, _ = _mk()
    pg = cache.acquire()
    cache.release([pg])
    with pytest.raises(errors.CacheIntegrityError, match="PDT-E019"):
        cache.release([pg])
    assert errors.CacheIntegrityError.error_code == "PDT-E019"


def test_check_catches_corruption():
    cache, free = _mk()
    free.appendleft(0)                        # null page in circulation
    with pytest.raises(errors.CacheIntegrityError, match="page 0"):
        cache.check()
    free.popleft()
    cache.check()
    pg = cache.acquire()
    free.append(pg)                           # free while referenced
    with pytest.raises(errors.CacheIntegrityError):
        cache.check()


def test_disabled_mode_is_plain_free_list():
    """enabled=False (serving_prefix_cache off): never indexes, never
    matches, never evicts — every release goes straight back to the
    free list, which is exactly the uncached engine's allocator."""
    cache, free = _mk(enabled=False)
    ids = np.arange(2 * PS, dtype=np.int32)
    pages = [cache.acquire(), cache.acquire()]
    assert cache.publish(ids, pages, 2 * PS) == 0
    cache.release(pages)
    assert cache.cached_pages == 0 and len(free) == TOTAL - 1
    assert cache.match(ids) == []
    assert cache.evictions == 0
    cache.check()


# ======================================================================
# randomized property test: the engine's allocation discipline
# ======================================================================

def test_prefix_cache_randomized_invariants():
    """>1000 mixed admit/grow/retire/preempt/cancel/evict steps with
    page conservation audited after EVERY mutation: no leaked pages, no
    double-free, null page never referenced, and
    ``in_use + free + cached == total - 1`` throughout and after the
    final drain."""
    rng = np.random.default_rng(1234)
    cache, free = _mk()
    # shared prefix templates so matching/sharing actually happens
    prefixes = [_ids(rng, PS * k) for k in (1, 2, 3, 4)]
    slots = []      # resident: {"ids", "pages", "written"}

    def conserve():
        cache.check()
        held = {p for s in slots for p in s["pages"]}
        assert 0 not in held
        assert (len(held) + len(free) + cache.cached_pages
                == TOTAL - 1)

    def publish_release(s):
        cache.publish(s["ids"], s["pages"], s["written"])
        cache.release(s["pages"])

    steps = 0
    for _ in range(1200):
        op = int(rng.integers(0, 100))
        if op < 35 and len(slots) < 4:
            # ADMIT: longest-prefix match, retain, COW rule, acquire
            pre = prefixes[int(rng.integers(0, len(prefixes)))]
            tail = _ids(rng, int(rng.integers(0, 9)))
            ids = np.concatenate([pre, tail])
            matched = cache.match(ids)
            if matched and len(matched) * PS >= ids.size:
                matched.pop()                 # COW: copy, don't share
            cache.retain(matched)
            n_alloc = max(1, -(-(ids.size + 1) // PS)) - len(matched)
            if n_alloc > cache.available():
                cache.release(matched)        # head-of-line unwind
            else:
                got = [cache.acquire(key="prop") for _ in range(n_alloc)]
                assert None not in got        # available() promised
                slots.append({"ids": ids, "pages": matched + got,
                              "written": int(ids.size)})
        elif op < 60 and slots:
            # GROW: one decode token; page on demand; dry -> preempt
            s = slots[int(rng.integers(0, len(slots)))]
            s["ids"] = np.append(s["ids"],
                                 np.int32(rng.integers(0, 50)))
            s["written"] += 1
            if -(-s["written"] // PS) > len(s["pages"]):
                pg = cache.acquire(key="prop")
                if pg is None:                # pool dry: preempt self
                    publish_release(s)
                    slots.remove(s)
                else:
                    s["pages"].append(pg)
        elif op < 80 and slots:
            # RETIRE: publish full pages, drop the residency
            publish_release(slots.pop(int(rng.integers(0, len(slots)))))
        elif op < 90 and slots:
            # CANCEL/FAIL: release without publishing
            cache.release(
                slots.pop(int(rng.integers(0, len(slots))))["pages"])
        else:
            # EVICT drill: forced reclaim while free pages remain
            faults.clear()
            faults.inject("engine_cache_evict", match="prop")
            pg = cache.acquire(key="prop")
            faults.clear()
            if pg is not None:
                cache.release([pg])
        steps += 1
        conserve()
    assert steps >= 1000
    for s in slots:                           # final drain
        publish_release(s)
    slots.clear()
    conserve()
    assert len(free) + cache.cached_pages == TOTAL - 1
    assert cache.evictions > 0                # the drill really drilled


def test_prefix_cache_pool_never_deadlocks_when_cached():
    """Everything cached, nothing free: acquire still serves by
    evicting — a fully-cached pool is never mistaken for an exhausted
    one (the engine's step() backstop stays unreachable)."""
    cache, free = _mk(total=1 + 4)
    ids = np.arange(4 * PS, dtype=np.int32)
    pages = [cache.acquire() for _ in range(4)]
    cache.publish(ids, pages, 4 * PS)
    cache.release(pages)
    assert not free and cache.available() == 4
    got = [cache.acquire() for _ in range(4)]
    assert None not in got and cache.evictions == 4
    assert cache.acquire() is None            # now genuinely dry
    cache.release(got)
    cache.check()

"""Scale-5 validation (VERDICT r3 item 5): the 3-axis dp x mp x pp
hybrid in one mesh, and the GPT-13B GSPMD train step AOT-lowered on a
32-device virtual mesh with a v5e HBM fit check (reference bar:
``test/auto_parallel/hybrid_strategy/
semi_auto_parallel_simple_net_dp_mp_pp.py`` and the 13B milestone of
BASELINE.md)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle


def test_dp_mp_pp_single_mesh():
    """GPipe over pp + Megatron TP over mp (GSPMD inside the pipeline
    shard_map via auto axes) + dp batch sharding, one mesh, full train
    step."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0)
    dp, mp, pp = 2, 2, 2
    mesh = dist.ProcessMesh(np.arange(8).reshape(dp, mp, pp),
                            ["dp", "mp", "pp"])
    paddle.seed(0)
    model = GPTForCausalLMPipe(cfg, mesh, pp_axis="pp", dp_axis="dp",
                               num_microbatches=2)
    model.blocks.shard(mesh, "pp", tp_axis="mp", tp_rules={
        "attn.qkv.weight": 2, "attn.qkv.bias": 1,
        "mlp.fc1.weight": 2, "mlp.fc1.bias": 1,
        "attn.proj.weight": 1, "mlp.fc2.weight": 1,
    })
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    @paddle.jit.to_static
    def train_step(ids, labels):
        loss = model(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)
    pl = [dist.Shard(0), dist.Replicate(), dist.Replicate()]
    losses = []
    for _ in range(3):
        ids = dist.shard_tensor(
            rng.integers(0, 256, (2 * dp, 16)).astype(np.int32), mesh,
            pl)
        labels = dist.shard_tensor(
            rng.integers(0, 256, (2 * dp, 16)).astype(np.int32), mesh,
            pl)
        losses.append(float(train_step(ids, labels)))
    assert all(np.isfinite(l) for l in losses)
    # stacked qkv must carry BOTH pp (dim 0) and mp (dim 2) sharding
    w = model.blocks.stacked_parameter("attn.qkv.weight")._read()
    spec = str(getattr(w.sharding, "spec", ""))
    assert "pp" in spec and "mp" in spec, spec


def test_dp_mp_pp_matches_dp_only():
    """The 3-axis hybrid must compute the same losses as plain dp on the
    same seed/data (parallelism is an implementation detail)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=32, dropout=0.0)
    rng0 = np.random.default_rng(7)
    # batch 8: divisible by microbatches(2) x dp for both meshes
    data = [(rng0.integers(0, 128, (8, 16)).astype(np.int32),
             rng0.integers(0, 128, (8, 16)).astype(np.int32))
            for _ in range(2)]

    def run(mesh_shape, names, tp, pl):
        mesh = dist.ProcessMesh(
            np.arange(8).reshape(*mesh_shape), names)
        paddle.seed(0)
        model = GPTForCausalLMPipe(cfg, mesh, pp_axis="pp",
                                   dp_axis="dp", num_microbatches=2)
        if tp:
            model.blocks.shard(mesh, "pp", tp_axis="mp", tp_rules={
                "attn.qkv.weight": 2, "attn.qkv.bias": 1,
                "mlp.fc1.weight": 2, "mlp.fc1.bias": 1,
                "attn.proj.weight": 1, "mlp.fc2.weight": 1,
            })
        model.train()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())

        @paddle.jit.to_static
        def step(ids, labels):
            loss = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        out = []
        for ids, labels in data:
            out.append(float(step(
                dist.shard_tensor(ids, mesh, pl),
                dist.shard_tensor(labels, mesh, pl))))
        return out

    ref = run((4, 2), ["dp", "pp"], False,
              [dist.Shard(0), dist.Replicate()])
    got = run((2, 2, 2), ["dp", "mp", "pp"], True,
              [dist.Shard(0), dist.Replicate(), dist.Replicate()])
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_gpt13b_aot_lowering_fits_v5e():
    """Lower + compile the 13B train step on a 32-device virtual mesh in
    a fresh process (needs 32 devices; the suite mesh has 8) and assert
    the per-device resident memory fits v5e HBM."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks",
                                      "aot_gpt13b.py")],
        env=env, cwd=root, capture_output=True, text=True, timeout=1500)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "AOT 13B OK" in r.stdout
    assert "tiny equivalence" in r.stdout


def test_zero_mp_pp_1f1b_single_layout():
    """ZeRO-2 (sharding axis = batch axis) composed with Megatron TP and
    the FUSED 1F1B pipeline schedule in one device layout (VERDICT r4
    item 7; reference bar: semi_auto_llama dp+mp+pp with sharding
    stages + pipeline_parallel.py:663 train_batch)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.sharding_optimizer import \
        DygraphShardingOptimizer
    from paddle_tpu.distributed.fleet.topology import \
        HybridCommunicateGroup
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0)
    pp, shd, mp = 2, 2, 2
    hcg = HybridCommunicateGroup(dp_degree=1, pp_degree=pp,
                                 sharding_degree=shd, sep_degree=1,
                                 mp_degree=mp)
    mesh = dist.ProcessMesh(np.arange(8).reshape(pp, shd, mp),
                            ["pp", "sharding", "mp"])
    paddle.seed(0)
    model = GPTForCausalLMPipe(cfg, mesh, pp_axis="pp",
                               dp_axis="sharding", num_microbatches=2)
    model.blocks.shard(mesh, "pp", tp_axis="mp", tp_rules={
        "attn.qkv.weight": 2, "attn.qkv.bias": 1,
        "mlp.fc1.weight": 2, "mlp.fc1.bias": 1,
        "attn.proj.weight": 1, "mlp.fc2.weight": 1,
    })
    model.train()
    inner = paddle.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=model.parameters())
    opt = DygraphShardingOptimizer(inner, hcg, stage=2)

    @paddle.jit.to_static
    def train_step(ids, labels):
        loss = model.train_batch(ids, labels)   # fused 1F1B
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)
    pl = [dist.Replicate(), dist.Shard(0), dist.Replicate()]
    losses = []
    for _ in range(3):
        ids = dist.shard_tensor(
            rng.integers(0, 256, (4, 16)).astype(np.int32), mesh, pl)
        labels = dist.shard_tensor(
            rng.integers(0, 256, (4, 16)).astype(np.int32), mesh, pl)
        losses.append(float(train_step(ids, labels)))
    assert all(np.isfinite(l) for l in losses), losses

    # ZeRO: moments sharded over `sharding`; TP: stacked qkv keeps mp;
    # and the stacked weights keep their pp sharding through updates
    accs = inner._accumulators["moment1"]
    assert any("sharding" in str(getattr(a._read().sharding, "spec", ""))
               for a in accs.values())
    w = model.blocks.stacked_parameter("attn.qkv.weight")._read()
    spec = str(getattr(w.sharding, "spec", ""))
    assert "mp" in spec and "pp" in spec, spec


@pytest.mark.slow
def test_gpt13b_capture_path_aot_lowering():
    """VERDICT r4 item 9: the framework's OWN capture path — LazyGuard
    GPTForCausalLM + shard_gpt + AMP O2 + ZeRO-1 + jit.aot_lower — must
    lower and compile at the 13B config on 32 virtual devices with the
    same HBM fit (fresh process: needs 32 devices).

    ``slow``: a 13B lowering in a fresh 32-device CPU subprocess is the
    single most expensive test in the repo (~6 min alone — nearly half
    the tier-1 870s budget, which was clipping the trailing vision
    files; PR7 budget audit); run it with ``-m slow``."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks",
                                      "aot_capture_13b.py")],
        env=env, cwd=root, capture_output=True, text=True, timeout=2400)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "AOT CAPTURE 13B OK" in r.stdout

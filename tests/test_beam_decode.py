"""BeamSearchDecoder + dynamic_decode (reference nn/decode.py:153,994).
Checks: beam_size=1 == stepwise greedy; scores ordered; EOS lock."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _build(vocab=12, hidden=16, seed=0):
    paddle.seed(seed)
    cell = nn.GRUCell(hidden, hidden)
    emb = nn.Embedding(vocab, hidden)
    head = nn.Linear(hidden, vocab)
    return cell, emb, head


def _greedy(cell, emb, head, h0, start, steps):
    """Reference decode: argmax per step through the same cell."""
    h = paddle.to_tensor(h0)
    tok = np.full((h0.shape[0],), start, np.int64)
    outs = []
    for _ in range(steps):
        out, h = cell(emb(paddle.to_tensor(tok)), h)
        tok = head(out).numpy().argmax(-1).astype(np.int64)
        outs.append(tok)
    return np.stack(outs, axis=1)  # [B, T]


def test_beam1_matches_greedy():
    cell, emb, head = _build()
    h0 = np.random.default_rng(0).normal(size=(3, 16)).astype("float32")
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=0,
                               beam_size=1, embedding_fn=emb,
                               output_fn=head)
    outs, scores = nn.dynamic_decode(dec, paddle.to_tensor(h0),
                                     max_step_num=6)
    ref = _greedy(cell, emb, head, h0, 1, 6)
    got = outs.numpy()[:, :, 0]  # [B, T] best beam
    # greedy may stop early on eos; compare up to first eos per row
    for b in range(3):
        row = ref[b]
        stop = np.argmax(row == 0) + 1 if (row == 0).any() else len(row)
        np.testing.assert_array_equal(got[b, :stop], row[:stop])


def test_beam_scores_ordered_and_eos_lock():
    cell, emb, head = _build(seed=3)
    h0 = np.random.default_rng(1).normal(size=(2, 16)).astype("float32")
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=0,
                               beam_size=4, embedding_fn=emb,
                               output_fn=head)
    outs, scores, lens = nn.dynamic_decode(
        dec, paddle.to_tensor(h0), max_step_num=8, return_length=True)
    s = scores.numpy()
    assert (np.diff(s, axis=-1) <= 1e-5).all()   # best beam first
    seq = outs.numpy()                           # [B, T, beam]
    # after the first end_token, a beam emits only end_token
    for b in range(seq.shape[0]):
        for k in range(seq.shape[2]):
            row = seq[b, :, k]
            if (row == 0).any():
                first = np.argmax(row == 0)
                assert (row[first:] == 0).all()
    assert lens.numpy().shape == (2, 4)


def test_beam_finds_better_than_greedy():
    """Crafted distribution where greedy is trapped: first step has a
    slightly-better token leading to a low-prob continuation."""
    import paddle_tpu.nn.functional as F

    class TrapCell(nn.Layer):
        """State = last token (one-hot); logits crafted so greedy picks
        token 1 then gets stuck; beam finds 2 -> 3 with higher total."""

        def forward(self, inputs, states):
            # inputs: one-hot of last token [N, 4]
            last = inputs.numpy().argmax(-1)
            lg = np.full((len(last), 4), -10.0, np.float32)
            for i, t in enumerate(last):
                if t == 1:   # start: 1 slightly beats 2
                    lg[i] = [-10, 0.0, -0.1, -10]
                elif t == 0:
                    lg[i] = [0, -10, -10, -10]
                else:        # from 1: everything bad; from 2: 3 is great
                    lg[i] = ([-1, -1, -1, -1] if t == 1
                             else [-10, -10, -10, 5.0])
            out = paddle.to_tensor(lg)
            return out, states

    emb = lambda toks: paddle.nn.functional.one_hot(
        toks, num_classes=4).astype("float32")
    cell = TrapCell()
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=0,
                               beam_size=2, embedding_fn=emb,
                               output_fn=None)
    h0 = np.zeros((1, 4), "float32")
    outs, scores = nn.dynamic_decode(dec, paddle.to_tensor(h0),
                                     max_step_num=2)
    best = outs.numpy()[0, :, 0]
    assert best[0] == 2 and best[1] == 3, best  # beam escaped the trap

"""MoE / expert parallelism tests (reference test pattern:
``test/collective/collective_global_scatter.py`` + moe_layer tests —
routing correctness, capacity semantics, and distributed-vs-dense parity)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.incubate.distributed.models.moe import (MoELayer, MoEMLP,
                                                        moe_dispatch_combine)


def test_dispatch_combine_topk():
    import jax.numpy as jnp
    gates = jnp.asarray([[0.7, 0.2, 0.1],
                         [0.1, 0.8, 0.1],
                         [0.45, 0.1, 0.45]], jnp.float32)
    disp, comb, aux = moe_dispatch_combine(gates, k=2, capacity=2)
    # token 0 -> experts 0 (w .7/.9) and 1; token 1 -> 1, 0; token 2 -> 0/2
    assert disp.shape == (3, 3, 2)
    # every token got its top-1 slot
    assert float(disp[0, 0].sum()) == 1.0
    assert float(disp[1, 1].sum()) == 1.0
    assert float(disp[2, 0].sum()) == 1.0
    # combine weights renormalized over the chosen k
    np.testing.assert_allclose(float(comb[0, 0].sum()), 0.7 / 0.9,
                               rtol=1e-5)
    assert float(aux) > 0.0


def test_capacity_drops_overflow():
    import jax.numpy as jnp
    # all 4 tokens want expert 0; capacity 2 keeps the first two
    gates = jnp.asarray([[0.9, 0.1]] * 4, jnp.float32)
    disp, comb, _ = moe_dispatch_combine(gates, k=1, capacity=2)
    kept = disp[:, 0].sum(axis=-1)
    np.testing.assert_allclose(np.asarray(kept), [1, 1, 0, 0])


def test_moe_mlp_forward_and_grads():
    paddle.seed(0)
    moe = MoEMLP(16, 32, num_experts=4, top_k=2, capacity_factor=2.0)
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(2, 8, 16)).astype("float32"))
    x.stop_gradient = False
    out = moe(x)
    assert tuple(out.shape) == (2, 8, 16)
    assert moe.aux_loss is not None
    (out.sum() + moe.aux_loss).backward()
    for p in (moe.w1, moe.w2, moe.gate.weight):
        assert p.grad is not None
        assert np.isfinite(np.asarray(p.grad._read())).all()
    assert np.isfinite(np.asarray(x.grad._read())).all()


def test_moe_capacity_passthrough_parity():
    """With ample capacity and top_k == num_experts the MoE must compute
    the full convex combination — compare against a dense evaluation of
    every expert."""
    paddle.seed(1)
    moe = MoEMLP(8, 16, num_experts=2, top_k=2, capacity_factor=4.0)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(6, 8)).astype("float32")
    out = np.asarray(moe(paddle.to_tensor(x))._read())

    import jax
    import jax.numpy as jnp
    xf = jnp.asarray(x)
    gates = jax.nn.softmax(xf @ moe.gate.weight._read(), axis=-1)
    dense = 0
    for e in range(2):
        h = jax.nn.gelu(xf @ moe.w1._read()[e] + moe.b1._read()[e])
        y = h @ moe.w2._read()[e] + moe.b2._read()[e]
        dense = dense + gates[:, e:e + 1] * y
    np.testing.assert_allclose(out, np.asarray(dense), atol=1e-5)


def test_gpt_moe_expert_parallel_step():
    """MoE-GPT trains under jit on a (dp, ep) mesh; expert weights keep
    their ep sharding through the compiled update."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, shard_gpt

    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "ep"])
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=16, dropout=0.0,
                    num_experts=4, moe_top_k=2)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    shard_gpt(model, mesh, dp_axis="dp", mp_axis="none", ep_axis="ep")
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    @paddle.jit.to_static
    def step(i, l):
        loss = model(i, l)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)
    pl = [dist.Shard(0), dist.Replicate()]
    losses = []
    for _ in range(3):
        ids = dist.shard_tensor(
            rng.integers(0, 64, (4, 16)).astype(np.int32), mesh, pl)
        labels = dist.shard_tensor(
            rng.integers(0, 64, (4, 16)).astype(np.int32), mesh, pl)
        losses.append(float(step(ids, labels)))
    assert all(np.isfinite(l) for l in losses)
    w1 = model.gpt.blocks[0].mlp.w1._read()
    assert "ep" in str(getattr(w1.sharding, "spec", "")), w1.sharding


def test_moe_layer_api():
    layer = MoELayer(d_model=8, d_hidden=16, num_experts=2, gate="switch")
    out = layer(paddle.to_tensor(np.ones((4, 8), "float32")))
    assert tuple(out.shape) == (4, 8)
    assert layer.moe.top_k == 1
    with pytest.raises(ValueError):
        MoELayer(d_model=8, d_hidden=16, num_experts=2, gate="bogus")

"""Custom-device plugin registry (SURVEY C5): register a device type,
surface it through the paddle.device API, place tensors on it.

Reference surface: ``python/paddle/device/__init__.py``
``is_compiled_with_custom_device`` (:62) / ``core.CustomPlace`` (:196) /
``set_device("npu:0")`` (:191); plugin loading
``paddle/phi/backends/device_manager.cc``. The TPU-native plugin ABI is
PJRT — the test binds a custom type onto the live cpu platform (the
``alias_of`` path); the ``library_path`` path hands a vendor PJRT .so to
jax's plugin loader and cannot run here without one.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.device.custom import (CustomPlace,
                                      is_compiled_with_custom_device,
                                      register_custom_device,
                                      registered_types)


@pytest.fixture()
def mychip():
    register_custom_device("mychip", alias_of="cpu")
    yield "mychip"
    from paddle_tpu.device import custom
    custom._registry.pop("mychip", None)


def test_register_and_query(mychip):
    assert is_compiled_with_custom_device("mychip")
    assert not is_compiled_with_custom_device("notachip")
    assert "mychip" in registered_types()
    assert "mychip" in paddle.device.get_all_custom_device_type()
    assert paddle.device.device_count("mychip") >= 1


def test_custom_place_resolves(mychip):
    p = CustomPlace("mychip", 0)
    assert p.get_device_type() == "mychip"
    assert p.get_device_id() == 0
    assert p.device.platform == "cpu"  # the aliased platform
    assert "mychip" in repr(p)


def test_set_device_accepts_custom_type(mychip):
    before = paddle.device.get_device()
    got = paddle.device.set_device("mychip:0")
    try:
        assert got.startswith("cpu")  # resolved through the alias
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        assert float(x.sum()) == 4.0
    finally:
        paddle.device.set_device(before)


def test_custom_place_unknown_type_raises():
    with pytest.raises(ValueError, match="register_custom_device"):
        CustomPlace("definitely_not_registered")


def test_register_validates_arguments():
    with pytest.raises(ValueError, match="exactly one"):
        register_custom_device("x")
    with pytest.raises(ValueError, match="exactly one"):
        register_custom_device("x", alias_of="cpu", library_path="/y.so")
    with pytest.raises(ValueError, match="not initialized"):
        register_custom_device("x", alias_of="nonexistent_platform")

"""1F1B and interleaved-VPP pipeline schedules (SURVEY D15; reference
pipeline_parallel.py:663 train_batch 1F1B, :912 interleaved). Parity model:
same outputs/grads/losses as the identical weights run sequentially."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.fleet.pipeline import PipelinedBlocks


@pytest.fixture(scope="module")
def mesh():
    return dist.ProcessMesh(np.arange(8).reshape(4, 2), ["pp", "dp"])


class Block(nn.Layer):
    def __init__(self, width=16):
        super().__init__()
        self.fc1 = nn.Linear(width, 2 * width)
        self.fc2 = nn.Linear(2 * width, width)

    def forward(self, x):
        return x + self.fc2(F.gelu(self.fc1(x)))


def _eager_clone(pipe, n_blocks):
    blocks = [Block() for _ in range(n_blocks)]
    names = [n for n, _ in blocks[0].named_parameters()]
    for n in names:
        vals = pipe.layer_values(n)
        for li, b in enumerate(blocks):
            dict(b.named_parameters())[n]._write(vals[li])
    return blocks


def test_interleaved_forward_parity(mesh):
    """VPP (interleave=2) computes the same function as sequential."""
    paddle.seed(0)
    pipe = PipelinedBlocks(Block, 8, mesh=mesh, pp_axis="pp",
                           num_microbatches=4, interleave=2)
    # storage order is the round-robin chunk permutation, not identity
    assert not np.array_equal(pipe.layer_order, np.arange(8))
    x = np.random.default_rng(0).normal(size=(8, 4, 16)).astype("float32")

    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    out = pipe(xt, batch_axes="dp")
    out.sum().backward()

    blocks = _eager_clone(pipe, 8)
    ref = paddle.to_tensor(x)
    ref.stop_gradient = False
    h = ref
    for b in blocks:
        h = b(h)
    h.sum().backward()

    np.testing.assert_allclose(np.asarray(out._read()),
                               np.asarray(h._read()), atol=1e-5)
    np.testing.assert_allclose(np.asarray(xt.grad._read()),
                               np.asarray(ref.grad._read()), atol=1e-5)
    # stacked grads match the eager per-layer grads through layer_values
    # ordering: compare via the inverse permutation
    for n in dict(blocks[0].named_parameters()):
        gs = np.asarray(pipe.stacked_parameter(n).grad._read())
        inv = np.argsort(pipe.layer_order)
        ge = np.stack([np.asarray(dict(b.named_parameters())[n]
                                  .grad._read()) for b in blocks])
        np.testing.assert_allclose(gs, ge[pipe.layer_order], atol=1e-4)


@pytest.mark.parametrize("M", [2, 4, 6, 8])
def test_interleaved_any_microbatch_count(mesh, M):
    """Banking must cover M < pp, M == pp, partial and full groups (the
    scan-length boundary: v*M + pp ticks only suffices when pp | M)."""
    paddle.seed(3)
    pipe = PipelinedBlocks(Block, 8, mesh=mesh, pp_axis="pp",
                           num_microbatches=M, interleave=2)
    x = np.random.default_rng(3).normal(size=(M * 2, 2, 16)) \
        .astype("float32")
    out = pipe(paddle.to_tensor(x), batch_axes="dp")

    blocks = _eager_clone(pipe, 8)
    h = paddle.to_tensor(x)
    for b in blocks:
        h = b(h)
    np.testing.assert_allclose(np.asarray(out._read()),
                               np.asarray(h._read()), atol=1e-5)


def test_interleaved_requires_divisibility(mesh):
    with pytest.raises(ValueError):
        PipelinedBlocks(Block, 6, mesh=mesh, pp_axis="pp", interleave=2)
    with pytest.raises(ValueError):
        PipelinedBlocks(Block, 8, interleave=2)  # mesh required


def test_1f1b_train_batch_parity(mesh):
    """Fused 1F1B loss + grads == sequential fwd/bwd with the same
    weights (the reference's hybrid_parallel_pp loss-parity pattern)."""
    paddle.seed(1)
    M = 4
    pipe = PipelinedBlocks(Block, 4, mesh=mesh, pp_axis="pp",
                           num_microbatches=M)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 4, 16)).astype("float32")
    y = rng.normal(size=(8, 4, 16)).astype("float32")

    def loss_fn(out, tgt):
        return ((out - tgt) ** 2).mean()

    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    loss = pipe.train_batch(xt, paddle.to_tensor(y), loss_fn,
                            batch_axes="dp")
    loss.backward()

    # sequential reference: same weights, same per-microbatch mean loss
    blocks = _eager_clone(pipe, 4)
    ref = paddle.to_tensor(x)
    ref.stop_gradient = False
    h = ref
    for b in blocks:
        h = b(h)
    # microbatch mean-of-means == full-batch mean here (equal mb sizes)
    ref_loss = ((h - paddle.to_tensor(y)) ** 2).mean()
    ref_loss.backward()

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(xt.grad._read()),
                               np.asarray(ref.grad._read()), atol=1e-5)
    for n in dict(blocks[0].named_parameters()):
        gs = np.asarray(pipe.stacked_parameter(n).grad._read())
        ge = np.stack([np.asarray(dict(b.named_parameters())[n]
                                  .grad._read()) for b in blocks])
        np.testing.assert_allclose(gs, ge, atol=1e-4)


def test_1f1b_trains_under_jit(mesh):
    """jit-compiled 1F1B train step drives the loss down."""
    paddle.seed(2)
    pipe = PipelinedBlocks(Block, 4, mesh=mesh, pp_axis="pp",
                           num_microbatches=2)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=pipe.parameters())
    rng = np.random.default_rng(2)
    x = paddle.to_tensor(rng.normal(size=(4, 2, 16)).astype("float32"))
    y = paddle.to_tensor(rng.normal(size=(4, 2, 16)).astype("float32") * .1)

    def loss_fn(out, tgt):
        return ((out - tgt) ** 2).mean()

    @paddle.jit.to_static
    def step(x, y):
        loss = pipe.train_batch(x, y, loss_fn, batch_axes="dp")
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    losses = [float(step(x, y)) for _ in range(6)]
    assert losses[-1] < losses[0] * 0.9, losses


def test_gpt_pipe_1f1b_train_batch_parity(mesh):
    """GPT 1F1B train_batch (epilogue inside the schedule via post_params,
    tied embeddings getting BOTH grad paths) matches the plain GPT."""
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTForCausalLMPipe)

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=16, dropout=0.0)
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 64, (4, 16)).astype(np.int32)
    labels = rng.integers(0, 64, (4, 16)).astype(np.int32)

    paddle.seed(0)
    pipe = GPTForCausalLMPipe(cfg, mesh, pp_axis="pp", dp_axis="dp",
                              num_microbatches=2)
    paddle.seed(0)
    ref = GPTForCausalLM(cfg)
    ref.gpt.wte.weight._write(pipe.wte.weight._read())
    ref.gpt.wpe.weight._write(pipe.wpe.weight._read())
    ref.gpt.ln_f.weight._write(pipe.ln_f.weight._read())
    ref.gpt.ln_f.bias._write(pipe.ln_f.bias._read())
    for li, blk in enumerate(ref.gpt.blocks):
        for n, p in blk.named_parameters():
            p._write(pipe.blocks.stacked_parameter(n)._read()[li])

    loss = pipe.train_batch(paddle.to_tensor(ids), paddle.to_tensor(labels))
    loss.backward()
    ref_loss = ref(paddle.to_tensor(ids), paddle.to_tensor(labels))
    ref_loss.backward()

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    # tied embedding grad = embedding path + head path
    np.testing.assert_allclose(
        np.asarray(pipe.wte.weight.grad._read()),
        np.asarray(ref.gpt.wte.weight.grad._read()), atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(pipe.ln_f.weight.grad._read()),
        np.asarray(ref.gpt.ln_f.weight.grad._read()), atol=2e-4)
    for n in [n for n, _ in ref.gpt.blocks[0].named_parameters()]:
        gs = np.asarray(pipe.blocks.stacked_parameter(n).grad._read())
        ge = np.stack([np.asarray(dict(b.named_parameters())[n]
                                  .grad._read())
                       for b in ref.gpt.blocks])
        np.testing.assert_allclose(gs, ge, atol=2e-4)


def test_gpt_pipe_1f1b_trains(mesh):
    """jit-compiled GPT 1F1B steps drive the loss down."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=16, dropout=0.0)
    paddle.seed(1)
    pipe = GPTForCausalLMPipe(cfg, mesh, pp_axis="pp", dp_axis="dp",
                              num_microbatches=2)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=pipe.parameters())
    rng = np.random.default_rng(6)
    ids = paddle.to_tensor(rng.integers(0, 64, (4, 16)).astype(np.int32))
    labels = paddle.to_tensor(rng.integers(0, 64, (4, 16)).astype(np.int32))

    @paddle.jit.to_static
    def step(i, l):
        loss = pipe.train_batch(i, l)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    losses = [float(step(ids, labels)) for _ in range(6)]
    assert losses[-1] < losses[0], losses

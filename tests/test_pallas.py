"""Pallas fused-kernel correctness tests (interpreter mode on CPU).

The reference validates its fused CUDA kernels against unfused compositions
(e.g. ``test/legacy_test/test_flash_attention.py`` checks flash_attn vs a
naive softmax attention); we do the same: each Pallas kernel is compared —
forward and gradients — against the plain-XLA composition it replaces.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import flash_attention as fa
from paddle_tpu.ops.pallas import norms, rope


def _ref_sdpa(q, k, v, causal):
    from paddle_tpu.nn.functional.attention import _sdpa_xla
    return _sdpa_xla(q, k, v, causal=causal)


def _rand(shape, dtype=jnp.float32, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape), dtype=dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_forward(causal):
    q = _rand((2, 70, 4, 32), seed=1)
    k = _rand((2, 70, 4, 32), seed=2)
    v = _rand((2, 70, 4, 32), seed=3)
    out = fa.flash_attention(q, k, v, causal=causal, interpret=True)
    ref = _ref_sdpa(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_attention_cross_lengths():
    # kv longer than q (decode-with-prefix shape): causal offset path
    q = _rand((1, 17, 2, 32), seed=1)
    k = _rand((1, 40, 2, 32), seed=2)
    v = _rand((1, 40, 2, 32), seed=3)
    out = fa.flash_attention(q, k, v, causal=True, interpret=True)
    ref = _ref_sdpa(q, k, v, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_attention_gqa():
    q = _rand((2, 33, 8, 32), seed=1)
    k = _rand((2, 33, 2, 32), seed=2)
    v = _rand((2, 33, 2, 32), seed=3)
    out = fa.flash_attention(q, k, v, causal=True, interpret=True)
    ref = _ref_sdpa(q, k, v, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads(causal):
    q = _rand((1, 37, 2, 32), seed=4)
    k = _rand((1, 37, 2, 32), seed=5)
    v = _rand((1, 37, 2, 32), seed=6)

    def loss_pl(q, k, v):
        o = fa.flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_ref_sdpa(q, k, v, causal)))

    gp = jax.grad(loss_pl, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


def test_flash_attention_gqa_grads():
    q = _rand((1, 21, 4, 32), seed=7)
    k = _rand((1, 21, 2, 32), seed=8)
    v = _rand((1, 21, 2, 32), seed=9)

    def loss_pl(q, k, v):
        o = fa.flash_attention(q, k, v, causal=True, interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(_ref_sdpa(q, k, v, True)))

    gp = jax.grad(loss_pl, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


def test_flash_attention_multiblock_grads():
    # seq > 128: multiple q/k blocks + padding (the tiled code paths the
    # single-block shapes above never reach)
    q = _rand((1, 300, 2, 16), seed=10)
    k = _rand((1, 300, 2, 16), seed=11)
    v = _rand((1, 300, 2, 16), seed=12)
    out = fa.flash_attention(q, k, v, causal=True, interpret=True)
    ref = _ref_sdpa(q, k, v, True)
    np.testing.assert_allclose(out, ref, atol=3e-5, rtol=3e-5)

    def loss_pl(q, k, v):
        o = fa.flash_attention(q, k, v, causal=True, interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(_ref_sdpa(q, k, v, True)))

    gp = jax.grad(loss_pl, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def _ref_segmented(q, k, v, seg_q, seg_k, causal):
    from paddle_tpu.nn.functional.attention import _sdpa_xla
    mask = seg_q[:, None, :, None] == seg_k[:, None, None, :]
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        iq = jnp.arange(sq)[:, None] + (sk - sq)
        mask = mask & (iq >= jnp.arange(sk)[None, :])[None, None]
    return _sdpa_xla(q, k, v, mask=mask, causal=False)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_segment_ids(causal):
    """Varlen via segment ids (the reference flash_attn_varlen capability):
    attention confined to same-segment pairs, parity vs masked XLA."""
    B, S, H, D = 2, 96, 4, 32
    q = _rand((B, S, H, D), seed=1)
    k = _rand((B, S, H, D), seed=2)
    v = _rand((B, S, H, D), seed=3)
    # ragged packing: row 0 -> [40, 56], row 1 -> [10, 30, 56]
    seg = np.zeros((B, S), np.int32)
    seg[0, 40:] = 1
    seg[1, 10:40] = 1
    seg[1, 40:] = 2
    seg = jnp.asarray(seg)
    out = fa.flash_attention(q, k, v, causal=causal, interpret=True,
                             segment_ids=seg)
    ref = _ref_segmented(q, k, v, seg, seg, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_attention_segment_ids_grads():
    B, S, H, D = 1, 64, 2, 32
    q = _rand((B, S, H, D), seed=4)
    k = _rand((B, S, H, D), seed=5)
    v = _rand((B, S, H, D), seed=6)
    seg = jnp.asarray(np.repeat([[0, 1]], B, 0).repeat(S // 2, axis=1))

    def loss_pl(q, k, v):
        o = fa.flash_attention(q, k, v, causal=True, interpret=True,
                               segment_ids=seg)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_ref_segmented(q, k, v, seg, seg, True)))

    gp = jax.grad(loss_pl, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


def test_flash_attention_segment_ids_gqa_multiblock():
    # segments spanning block boundaries + GQA head mapping
    B, S, H, D = 1, 300, 4, 32
    q = _rand((B, S, H, D), seed=7)
    k = _rand((B, S, 2, D), seed=8)
    v = _rand((B, S, 2, D), seed=9)
    seg = np.zeros((B, S), np.int32)
    seg[0, 130:] = 1
    seg[0, 250:] = 2
    seg = jnp.asarray(seg)
    out = fa.flash_attention(q, k, v, causal=True, interpret=True,
                             segment_ids=seg, blocks=(128, 128))
    ref = _ref_segmented(q, jnp.repeat(k, 2, 2), jnp.repeat(v, 2, 2),
                         seg, seg, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_attn_unpadded_functional():
    """paddle.nn.functional.flash_attn_unpadded parity: packed rows with
    cu_seqlens match per-sequence dense attention."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(0)
    lens = [24, 40]
    total, H, D = sum(lens), 2, 16
    q = rng.normal(size=(total, H, D)).astype(np.float32)
    k = rng.normal(size=(total, H, D)).astype(np.float32)
    v = rng.normal(size=(total, H, D)).astype(np.float32)
    cu = np.cumsum([0] + lens).astype(np.int32)
    out, _ = F.flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu), paddle.to_tensor(cu),
        max(lens), max(lens), causal=True)
    out = out.numpy()
    # each packed sequence == standalone causal attention
    from paddle_tpu.nn.functional.attention import _sdpa_xla
    for i, ln in enumerate(lens):
        s, e = cu[i], cu[i + 1]
        ref = _sdpa_xla(jnp.asarray(q[None, s:e]), jnp.asarray(k[None, s:e]),
                        jnp.asarray(v[None, s:e]), causal=True)[0]
        np.testing.assert_allclose(out[s:e], ref, atol=2e-5, rtol=2e-5)


def test_flash_attention_bf16():
    q = _rand((1, 64, 2, 64), jnp.bfloat16, seed=1)
    k = _rand((1, 64, 2, 64), jnp.bfloat16, seed=2)
    v = _rand((1, 64, 2, 64), jnp.bfloat16, seed=3)
    out = fa.flash_attention(q, k, v, causal=True, interpret=True)
    ref = _ref_sdpa(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(jnp.float32),
                               ref.astype(jnp.float32), atol=3e-2, rtol=3e-2)


# --------------------------------------------------------------------------
def _ref_rms(x, w, eps=1e-6):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(ms + eps)).astype(x.dtype) * w


def _ref_ln(x, w, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return out.astype(x.dtype) * w + b


def test_rms_norm_fwd_bwd():
    x = _rand((6, 37, 128), seed=1)
    w = _rand((128,), seed=2) + 1.0

    out = norms.rms_norm(x, w, interpret=True)
    np.testing.assert_allclose(out, _ref_rms(x, w), atol=1e-5, rtol=1e-5)

    def lp(x, w):
        return jnp.sum(jnp.sin(norms.rms_norm(x, w, interpret=True)))

    def lr(x, w):
        return jnp.sum(jnp.sin(_ref_rms(x, w)))

    gp = jax.grad(lp, argnums=(0, 1))(x, w)
    gr = jax.grad(lr, argnums=(0, 1))(x, w)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)


def test_layer_norm_fwd_bwd():
    x = _rand((300, 64), seed=3)  # non-multiple of row block: padding path
    w = _rand((64,), seed=4) + 1.0
    b = _rand((64,), seed=5)

    out = norms.layer_norm(x, w, b, interpret=True)
    np.testing.assert_allclose(out, _ref_ln(x, w, b), atol=1e-5, rtol=1e-5)

    def lp(x, w, b):
        return jnp.sum(jnp.cos(norms.layer_norm(x, w, b, interpret=True)))

    def lr(x, w, b):
        return jnp.sum(jnp.cos(_ref_ln(x, w, b)))

    gp = jax.grad(lp, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(lr, argnums=(0, 1, 2))(x, w, b)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(a, b_, atol=2e-5, rtol=2e-5)


# --------------------------------------------------------------------------
def _rope_tables(s, d, base=10000.0):
    inv = 1.0 / base ** (np.arange(0, d // 2) * 2.0 / d)
    ang = np.arange(s)[:, None] * inv[None, :]
    ang = np.concatenate([ang, ang], axis=-1)  # neox tiling
    return jnp.asarray(np.cos(ang), jnp.float32), \
        jnp.asarray(np.sin(ang), jnp.float32)


def _ref_rope_neox(x, cos, sin):
    d = x.shape[-1]
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return x * c + rot * s


def test_rope_interleaved():
    # pair (2i, 2i+1): rot[2i] = -x[2i+1], rot[2i+1] = x[2i]
    x = _rand((1, 16, 2, 32), seed=8)
    d = 32
    inv = 1.0 / 10000.0 ** (np.arange(0, d // 2) * 2.0 / d)
    ang = np.repeat(np.arange(16)[:, None] * inv[None, :], 2, axis=-1)
    cos = jnp.asarray(np.cos(ang), jnp.float32)
    sin = jnp.asarray(np.sin(ang), jnp.float32)
    out = rope.apply_rope(x, cos, sin, use_neox=False, interpret=True)
    xe = np.asarray(x).reshape(1, 16, 2, d // 2, 2)
    rot = np.stack([-xe[..., 1], xe[..., 0]], -1).reshape(1, 16, 2, d)
    ref = np.asarray(x) * np.asarray(cos)[None, :, None, :] + \
        rot * np.asarray(sin)[None, :, None, :]
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_rope_batched_tables():
    # per-example tables [B, S, D] (the position_ids path)
    x = _rand((2, 8, 2, 16), seed=9)
    cos, sin = _rope_tables(32, 16)
    pid = np.stack([np.arange(8), np.arange(8) + 3])  # shifted positions
    cb = jnp.asarray(np.asarray(cos)[pid])
    sb = jnp.asarray(np.asarray(sin)[pid])
    out = rope.apply_rope(x, cb, sb, interpret=True)
    for b in range(2):
        ref = _ref_rope_neox(x[b:b + 1], cb[b], sb[b])
        np.testing.assert_allclose(out[b:b + 1], ref, atol=1e-5, rtol=1e-5)


def test_rope_fwd_bwd():
    x = _rand((2, 48, 4, 64), seed=6)
    cos, sin = _rope_tables(48, 64)

    out = rope.apply_rope(x, cos, sin, interpret=True)
    ref = _ref_rope_neox(x, cos, sin)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def lp(x):
        return jnp.sum(jnp.sin(rope.apply_rope(x, cos, sin, interpret=True)))

    def lr(x):
        return jnp.sum(jnp.sin(_ref_rope_neox(x, cos, sin)))

    np.testing.assert_allclose(jax.grad(lp)(x), jax.grad(lr)(x),
                               atol=2e-5, rtol=2e-5)


# --------------------------------------------------------------------------
# ragged paged attention (ISSUE 3: multi-page compacted-grid serving kernel)
# --------------------------------------------------------------------------
from paddle_tpu.ops.pallas import paged_attention as pga


def _paged_gather(pool, bt, b, length, ps):
    """[L, Hk, D] kv of sequence ``b`` out of the page pool."""
    return np.stack([np.asarray(pool)[:, bt[b, t // ps], t % ps]
                     for t in range(length)], 0)


def _ref_causal_offset(q, k, v, kv_len, q_len):
    """Dense reference with the ragged causal rule: q token i attends
    kv positions <= kv_len - q_len + i.  q [q_len, Hq, D]; k/v
    [kv_len, Hk, D]."""
    hq, hk = q.shape[1], k.shape[1]
    kt = np.repeat(k, hq // hk, axis=1)
    vt = np.repeat(v, hq // hk, axis=1)
    s = np.einsum("qhd,lhd->hql", q, kt) / np.sqrt(q.shape[-1])
    qpos = kv_len - q_len + np.arange(q_len)
    mask = np.arange(kv_len)[None, :] <= qpos[:, None]
    s = np.where(mask[None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("hql,lhd->qhd", p, vt)


def _paged_setup(rng, lens, hk, ps, d, extra_pages=3):
    """Page pools with SHUFFLED page assignment (block-table indirection
    must matter) + block tables; page 0 left unassigned (null page)."""
    B = len(lens)
    NP = -(-max(lens) // ps) + 1
    total = B * NP + extra_pages
    pk = rng.normal(size=(hk, total, ps, d)).astype(np.float32)
    pv = rng.normal(size=(hk, total, ps, d)).astype(np.float32)
    ids = np.arange(1, total)
    rng.shuffle(ids)
    bt = np.zeros((B, NP), np.int32)
    n = 0
    for b in range(B):
        need = -(-lens[b] // ps)
        bt[b, :need] = ids[n:n + need]
        n += need
    return pk, pv, bt


@pytest.mark.parametrize("hq,hk,ps,lens,ppb", [
    (4, 4, 8, [5, 16, 23], 1),     # rep 1, non-aligned lengths
    (8, 2, 16, [1, 30, 17], 2),    # GQA rep 4, len < page, multi-page
    (6, 3, 8, [9, 40], 4),         # GQA rep 2, ppb > pages of some seq
    (8, 8, 16, [33], 3),           # ppb not dividing the page count
])
def test_paged_decode_matches_dense(hq, hk, ps, lens, ppb):
    rng = np.random.default_rng(0)
    d = 32
    B = len(lens)
    pk, pv, bt = _paged_setup(rng, lens, hk, ps, d)
    q = rng.normal(size=(B, hq, d)).astype(np.float32)
    out = np.asarray(pga.paged_decode_attention(
        jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
        jnp.asarray(bt), jnp.asarray(lens, dtype=jnp.int32),
        interpret=True, pages_per_block=ppb))
    for b in range(B):
        ref = _ref_causal_offset(
            q[b][None], _paged_gather(pk, bt, b, lens[b], ps),
            _paged_gather(pv, bt, b, lens[b], ps), lens[b], 1)[0]
        np.testing.assert_allclose(out[b], ref, atol=2e-5, rtol=2e-5)


def test_paged_decode_traced_lengths_no_recompile():
    """seq_lens/block_tables ride the scalar-prefetch channel: one
    compiled program serves CHANGING lengths and re-pointed tables (the
    serving engine's admission/retirement contract)."""
    rng = np.random.default_rng(1)
    hq = hk = 2
    ps, d, B, NP = 8, 16, 2, 3
    pk, pv, bt = _paged_setup(rng, [20, 11], hk, ps, d)
    q = rng.normal(size=(B, hq, d)).astype(np.float32)

    traces = []

    @jax.jit
    def step(q, pk, pv, bt, lens):
        traces.append(1)
        return pga.paged_decode_attention(q, pk, pv, bt, lens,
                                          interpret=True,
                                          pages_per_block=2)

    for lens in ([20, 11], [7, 23], [1, 1]):
        out = np.asarray(step(jnp.asarray(q), jnp.asarray(pk),
                              jnp.asarray(pv), jnp.asarray(bt),
                              jnp.asarray(lens, dtype=jnp.int32)))
        for b in range(B):
            ref = _ref_causal_offset(
                q[b][None], _paged_gather(pk, bt, b, lens[b], ps),
                _paged_gather(pv, bt, b, lens[b], ps), lens[b], 1)[0]
            np.testing.assert_allclose(out[b], ref, atol=2e-5,
                                       rtol=2e-5)
    assert len(traces) == 1  # lengths are data, not shape


@pytest.mark.parametrize("qb", [2, 4])
def test_ragged_mixed_prefill_decode(qb):
    """One kernel call serving a continuously-batched step: prefill
    chunks (q_len > 1) and decodes (q_len 1) with non-page-aligned
    lengths, causal offsets per sequence."""
    rng = np.random.default_rng(2)
    hq, hk, ps, d, ppb = 4, 2, 8, 16, 2
    kv_lens = [13, 6, 21, 1]
    q_lens = [5, 1, 9, 1]          # mixed prefill + decode
    B = len(kv_lens)
    pk, pv, bt = _paged_setup(rng, kv_lens, hk, ps, d)
    segs = [-(-ql // qb) * qb for ql in q_lens]
    starts = np.cumsum([0] + segs[:-1])
    T = sum(segs)
    q = np.zeros((T, hq, d), np.float32)
    for b in range(B):
        q[starts[b]:starts[b] + q_lens[b]] = rng.normal(
            size=(q_lens[b], hq, d))
    out = np.asarray(pga.ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
        jnp.asarray(bt), jnp.asarray(kv_lens, dtype=jnp.int32),
        jnp.asarray(q_lens, dtype=jnp.int32), q_block=qb,
        pages_per_block=ppb, interpret=True))
    for b in range(B):
        ref = _ref_causal_offset(
            q[starts[b]:starts[b] + q_lens[b]],
            _paged_gather(pk, bt, b, kv_lens[b], ps),
            _paged_gather(pv, bt, b, kv_lens[b], ps),
            kv_lens[b], q_lens[b])
        np.testing.assert_allclose(out[starts[b]:starts[b] + q_lens[b]],
                                   ref, atol=2e-5, rtol=2e-5)


def test_ragged_zero_qlen_sits_out():
    """q_len 0 (a slot sitting a step out) contributes no work items and
    corrupts nothing."""
    rng = np.random.default_rng(3)
    hq = hk = 2
    ps, d, qb = 8, 16, 2
    kv_lens = [10, 9]
    q_lens = [2, 0]
    pk, pv, bt = _paged_setup(rng, kv_lens, hk, ps, d)
    q = np.zeros((2, hq, d), np.float32)
    q[:2] = rng.normal(size=(2, hq, d))
    out = np.asarray(pga.ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
        jnp.asarray(bt), jnp.asarray(kv_lens, dtype=jnp.int32),
        jnp.asarray(q_lens, dtype=jnp.int32), q_block=qb,
        pages_per_block=2, interpret=True))
    ref = _ref_causal_offset(q[:2], _paged_gather(pk, bt, 0, 10, ps),
                             _paged_gather(pv, bt, 0, 10, ps), 10, 2)
    np.testing.assert_allclose(out[:2], ref, atol=2e-5, rtol=2e-5)


def test_pages_per_block_heuristic_and_candidates():
    from paddle_tpu.ops.pallas.paged_attention import (
        _tune_candidates, default_pages_per_block)
    assert default_pages_per_block(16, 128, 64) == 32   # 512-token target
    assert default_pages_per_block(16, 2, 64) == 2      # capped by table
    cands = _tune_candidates(16, 128, 64)
    assert cands[0] == 1 and all(b == a * 2 for a, b in
                                 zip(cands, cands[1:]))


# --------------------------------------------------------------------------
# int8 KV pages with in-kernel dequant (ISSUE 7)
# --------------------------------------------------------------------------

def _quant_pools(rng, lens, hk, ps, d):
    """Shuffled-page pools like ``_paged_setup``, plus their int8
    quantization (``quantization.kv_quantize``)."""
    from paddle_tpu.quantization import kv_quantize

    pk, pv, bt = _paged_setup(rng, lens, hk, ps, d)
    qk, sk = kv_quantize(jnp.asarray(pk))
    qv, sv = kv_quantize(jnp.asarray(pv))
    return pk, pv, bt, qk, sk, qv, sv


@pytest.mark.parametrize("hq,hk,ps,lens,q_lens,ppb", [
    (4, 2, 8, [13, 6, 21, 1], [5, 1, 9, 1], 2),  # mixed prefill+decode
    (8, 2, 16, [1, 30, 17], [1, 1, 1], 2),       # GQA decode
])
def test_ragged_int8_kernel_bitwise_vs_dequant(hq, hk, ps, lens,
                                               q_lens, ppb):
    """The quant kernel's contract: int8 pages + per-slot scales through
    the in-DMA dequant must be BITWISE what the fp kernel computes on
    the dequantized pools (same f32 values entering the same flash
    recurrence), and within int8 error of the original fp pools."""
    from paddle_tpu.quantization import kv_dequantize

    rng = np.random.default_rng(5)
    d, qb = 16, 2
    B = len(lens)
    pk, pv, bt, qk, sk, qv, sv = _quant_pools(rng, lens, hk, ps, d)
    segs = [-(-ql // qb) * qb for ql in q_lens]
    starts = np.cumsum([0] + segs[:-1])
    q = np.zeros((sum(segs), hq, d), np.float32)
    for b in range(B):
        q[starts[b]:starts[b] + q_lens[b]] = rng.normal(
            size=(q_lens[b], hq, d))
    args = (jnp.asarray(bt), jnp.asarray(lens, dtype=jnp.int32),
            jnp.asarray(q_lens, dtype=jnp.int32))
    out_q = np.asarray(pga.ragged_paged_attention(
        jnp.asarray(q), qk, qv, *args, q_block=qb, pages_per_block=ppb,
        interpret=True, k_scales=sk, v_scales=sv))
    out_deq = np.asarray(pga.ragged_paged_attention(
        jnp.asarray(q), kv_dequantize(qk, sk), kv_dequantize(qv, sv),
        *args, q_block=qb, pages_per_block=ppb, interpret=True))
    out_fp = np.asarray(pga.ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv), *args,
        q_block=qb, pages_per_block=ppb, interpret=True))
    rows = np.concatenate([np.arange(starts[b], starts[b] + q_lens[b])
                           for b in range(B)])
    np.testing.assert_array_equal(out_q[rows], out_deq[rows])  # bitwise
    # int8 absmax per-vector: softmax-weighted values stay close
    np.testing.assert_allclose(out_q[rows], out_fp[rows], atol=0.05,
                               rtol=0.05)


def test_ragged_int8_exact_grid_is_bitwise_vs_fp():
    """KV values on the int8 grid (v = n * s with s an exact binary
    scale) quantize losslessly, so the QUANT kernel must reproduce the
    FP kernel's output bit for bit — pinning that the dequant multiply
    sits before the dots exactly where the fp path casts."""
    rng = np.random.default_rng(9)
    hq = hk = 2
    ps, d, qb, ppb = 8, 16, 2, 2
    lens, q_lens = [11, 7], [3, 1]
    s = 2.0 ** -5                       # exact in fp32
    B = len(lens)
    NP = -(-max(lens) // ps) + 1
    total = B * NP + 2
    ints = rng.integers(-127, 128, size=(hk, total, ps, d))
    ints2 = rng.integers(-127, 128, size=(hk, total, ps, d))
    # pin every vector's absmax at 127 so kv_quantize's scale is
    # EXACTLY s (127*s/127) and the int8 roundtrip is lossless
    ints[..., 0] = 127
    ints2[..., 0] = -127
    pk = (ints * s).astype(np.float32)
    pv = (ints2 * s).astype(np.float32)
    from paddle_tpu.quantization import kv_quantize
    qk, sk = kv_quantize(jnp.asarray(pk))
    qv, sv = kv_quantize(jnp.asarray(pv))
    np.testing.assert_array_equal(
        np.asarray(qk, np.float32) * np.asarray(sk)[..., None], pk)
    bt = np.zeros((B, NP), np.int32)
    ids = np.arange(1, total)
    rng.shuffle(ids)
    n = 0
    for b in range(B):
        need = -(-lens[b] // ps)
        bt[b, :need] = ids[n:n + need]
        n += need
    segs = [-(-ql // qb) * qb for ql in q_lens]
    starts = np.cumsum([0] + segs[:-1])
    q = rng.normal(size=(sum(segs), hq, d)).astype(np.float32)
    args = (jnp.asarray(bt), jnp.asarray(lens, dtype=jnp.int32),
            jnp.asarray(q_lens, dtype=jnp.int32))
    out_q = np.asarray(pga.ragged_paged_attention(
        jnp.asarray(q), qk, qv, *args, q_block=qb, pages_per_block=ppb,
        interpret=True, k_scales=sk, v_scales=sv))
    out_fp = np.asarray(pga.ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv), *args,
        q_block=qb, pages_per_block=ppb, interpret=True))
    rows = np.concatenate([np.arange(starts[b], starts[b] + q_lens[b])
                           for b in range(len(lens))])
    np.testing.assert_array_equal(out_q[rows], out_fp[rows])


def test_ragged_int8_requires_both_scales():
    rng = np.random.default_rng(1)
    pk, pv, bt, qk, sk, qv, sv = _quant_pools(rng, [9], 2, 8, 16)
    with pytest.raises(ValueError, match="both"):
        pga.ragged_paged_attention(
            jnp.asarray(rng.normal(size=(2, 2, 16)), jnp.float32),
            qk, qv, jnp.asarray(bt), jnp.asarray([9], dtype=jnp.int32),
            jnp.asarray([2], dtype=jnp.int32), q_block=2,
            pages_per_block=1, interpret=True, k_scales=sk)

"""Observability runtime (ISSUE 8): metrics registry semantics
(buckets, merge, Prometheus golden, flag-off no-op), the structured
event ring + flight recorder (wraparound, dump-on-drill, clean runs
dump nothing), engine ``stats`` backward compatibility over the
registry re-backing, timeline histograms, and training step telemetry.

Everything here is model-free and fast except the two engine drills,
which reuse the session tiny GPT (``tests/conftest.py serving_gpt``)
and the geometries the serving suite already compiled.
"""
import json
import math
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.observability.metrics import Registry


@pytest.fixture
def gpt(serving_gpt):
    return serving_gpt


@pytest.fixture
def metrics_on():
    """Force the metrics flag on for one test, restoring after."""
    old = paddle.get_flags("metrics")["metrics"]
    paddle.set_flags({"metrics": True})
    yield
    paddle.set_flags({"metrics": old})


# ==========================================================================
# metrics core
# ==========================================================================

def test_histogram_bucket_edges_and_observe(metrics_on):
    h = Registry().histogram("h", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 1.0, 5.0, 10.0, 50.0, 1000.0):
        h.observe(v)
    # le semantics: v <= edge lands in that bucket; overflow is last
    assert h.counts == [2, 2, 1, 1]
    assert h.count == 6 and h.sum == pytest.approx(1066.5)
    assert h.mean == pytest.approx(1066.5 / 6)
    # the default latency buckets are fixed, log-spaced, increasing
    edges = obs.LATENCY_BUCKETS_MS
    assert list(edges) == sorted(edges) and len(set(edges)) == len(edges)
    ratios = [edges[i + 1] / edges[i] for i in range(len(edges) - 1)]
    assert all(abs(r - ratios[0]) < 1e-3 for r in ratios)  # log-spaced
    with pytest.raises(ValueError, match="increasing"):
        Registry().histogram("bad", buckets=(10.0, 1.0))


def test_histogram_merge(metrics_on):
    r = Registry()
    a = r.histogram("a", buckets=(1.0, 10.0))
    b = r.histogram("b", buckets=(1.0, 10.0))
    for v in (0.5, 5.0):
        a.observe(v)
    for v in (5.0, 50.0):
        b.observe(v)
    a.merge(b)
    assert a.counts == [1, 2, 1] and a.count == 4
    assert a.sum == pytest.approx(60.5)
    c = r.histogram("c", buckets=(2.0, 20.0))
    with pytest.raises(ValueError, match="different buckets"):
        a.merge(c)


def test_registry_get_or_create_and_snapshot(metrics_on):
    r = Registry()
    assert r.counter("x.n") is r.counter("x.n")       # same identity
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("x.n")
    r.counter("x.n").inc(3)
    r.gauge("x.g").set(1.5)
    r.gauge("x.lazy").set_function(lambda: 7)          # read at snap
    r.counter("x.lab", labels={"reason": "stop"}).inc()
    snap = r.snapshot()
    assert snap["x"]["n"] == 3
    assert snap["x"]["g"] == 1.5
    assert snap["x"]["lazy"] == 7
    assert snap["x"]["lab"] == {"reason=stop": 1}


def test_prometheus_text_golden(metrics_on):
    """Exact text: stable ordering (sorted names, sorted label sets),
    cumulative histogram buckets with +Inf, HELP/label escaping."""
    r = Registry()
    r.counter("req.total", help='served "requests"\nall').inc(5)
    r.gauge("pool.free").set(3)
    h = r.histogram("lat.ms", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(2.0)
    h.observe(99.0)
    r.counter("req.by", labels={"reason": 'a"b\\c'}).inc(2)
    assert r.render_prometheus() == (
        '# TYPE lat_ms histogram\n'
        'lat_ms_bucket{le="1"} 1\n'
        'lat_ms_bucket{le="10"} 2\n'
        'lat_ms_bucket{le="+Inf"} 3\n'
        'lat_ms_sum 101.5\n'
        'lat_ms_count 3\n'
        '# TYPE pool_free gauge\n'
        'pool_free 3\n'
        '# TYPE req_by counter\n'
        'req_by{reason="a\\"b\\\\c"} 2\n'
        '# HELP req_total served "requests"\\nall\n'
        '# TYPE req_total counter\n'
        'req_total 5\n')


def test_flag_off_is_noop_and_always_records():
    old = paddle.get_flags("metrics")["metrics"]
    r = Registry()
    c = r.counter("c")
    a = r.counter("a", always=True)     # stats-contract counters
    h = r.histogram("h")
    g = r.gauge("g")
    try:
        paddle.set_flags({"metrics": False})
        c.inc(5)
        h.observe(1.0)
        g.set(2.0)
        a.inc(5)
        assert c.value == 0 and h.count == 0 and g.value == 0.0
        assert a.value == 5                     # always-on contract
        obs.events.clear()
        obs.emit("k", x=1)
        assert obs.tail() == []                 # ring is gated too
        assert obs.dump("nope") is None         # ...and so are dumps
        paddle.set_flags({"metrics": True})
        c.inc(5)
        h.observe(1.0)
        assert c.value == 5 and h.count == 1
    finally:
        paddle.set_flags({"metrics": old})


# ==========================================================================
# event ring + flight recorder
# ==========================================================================

def test_event_ring_wraparound(metrics_on):
    from paddle_tpu.observability.events import EventRing
    ring = EventRing(capacity=4)
    for i in range(10):
        ring.emit("k", i=i)
    got = ring.tail()
    assert len(got) == 4
    assert [e["i"] for e in got] == [6, 7, 8, 9]       # oldest dropped
    assert [e["seq"] for e in got] == [6, 7, 8, 9]     # seq monotone
    assert ring.tail(2) == got[-2:]
    ring.clear()
    assert ring.tail() == []


def test_flight_dump_roundtrip(tmp_path, metrics_on, monkeypatch):
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    obs.events.clear()
    obs.emit("serving.enqueued", rid=7)
    err = ValueError("boom")
    path = obs.dump("unit_test", error=err, extra={"rid": 7})
    assert path and os.path.dirname(path) == str(tmp_path)
    assert obs.last_dump() == path
    rec = json.load(open(path))
    assert rec["reason"] == "unit_test"
    assert "boom" in rec["error"]
    assert rec["extra"] == {"rid": 7}
    assert any(e["kind"] == "serving.enqueued" and e["rid"] == 7
               for e in rec["events"])


def test_ring_collects_retry_guard_and_fault_events(metrics_on):
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.guard import StepGuard
    from paddle_tpu.resilience.retry import retry_call

    obs.events.clear()
    faults.clear()
    try:
        # retry attempts
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise ConnectionError("transient")
            return "ok"

        assert retry_call(flaky, max_attempts=3,
                          sleep=lambda s: None) == "ok"
        # fault firings
        faults.inject("nan_step", match="1")
        assert faults.check("nan_step", "1")
        # StepGuard skip
        StepGuard(max_bad_steps=3).observe(float("nan"))
        kinds = [e["kind"] for e in obs.tail()]
        assert kinds.count("retry.attempt") == 2
        assert "fault.fired" in kinds
        assert "guard.step_skip" in kinds
    finally:
        faults.clear()


# ==========================================================================
# engine: stats parity, metrics(), flight recorder on the nan drill
# ==========================================================================

_STAT_KEYS = [
    # counter block (declaration order == the pre-observability dict)
    "admitted", "retired", "steps", "mixed_steps", "decode_dispatches",
    "tokens_generated", "pages_allocated", "peak_pages_in_use",
    "preemptions", "timeouts", "cancelled", "failed", "rejected",
    "retries", "cache_hits", "cache_hit_tokens",
    "prefill_tokens_requested", "prefill_tokens_computed",
    # live gauges appended by the stats property
    "cached_pages", "evictions", "pages_in_use", "pages_free",
    "queue_depth", "kv_quant", "kv_page_bytes", "kv_bytes_in_use",
    # speculative decoding (ISSUE 9) — strictly APPENDED so every
    # pre-existing key keeps its position
    "spec_proposed", "spec_accepted", "spec_accept_rate",
    # live migration (ISSUE 20) — strictly APPENDED, same contract
    "migrated_in", "migrated_out",
]


def _drive(gpt, prompts, new):
    eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    return eng, rids, done


def test_engine_stats_backward_compat(gpt):
    """The registry re-backing is invisible through ``stats``: same
    keys, same order, same int values — and the numbers are identical
    with PDTPU_METRICS off (always=True counters keep the contract)."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (5, 9, 3, 12)]
    new = [6, 4, 7, 5]
    old = paddle.get_flags("metrics")["metrics"]
    try:
        paddle.set_flags({"metrics": True})
        _drive(gpt, prompts, new)   # warm the model's program cache:
        # the first engine on a cold model pays one extra scalar decode
        # dispatch to compile (steps/decode_dispatches +1) — that is
        # cache warmness, not flag behavior, so take it off the table
        eng_on, _, done_on = _drive(gpt, prompts, new)
        paddle.set_flags({"metrics": False})
        eng_off, _, done_off = _drive(gpt, prompts, new)
    finally:
        paddle.set_flags({"metrics": old})
    st_on, st_off = eng_on.stats, eng_off.stats
    assert list(st_on) == _STAT_KEYS == list(st_off)
    assert st_on == st_off                   # flag changes NOTHING here
    for k in _STAT_KEYS:
        if k not in ("kv_quant", "spec_accept_rate"):
            assert isinstance(st_on[k], int), k
    # ...and the off engine's outputs match the on engine's bitwise
    for rid in done_on:
        np.testing.assert_array_equal(done_on[rid].sequence,
                                      done_off[rid].sequence)
    assert st_on["admitted"] == 4 and st_on["retired"] == 4


def test_engine_metrics_timelines_populated(gpt, metrics_on):
    """The slot-contention workload (4 requests through 2 slots) fills
    the timeline histograms: one TTFT/queue observation per request,
    TPOT for every multi-token stream, finish-reason labeled counters,
    and per-dispatch latency."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (5, 9, 3, 12)]
    new = [6, 4, 7, 5]
    eng, rids, done = _drive(gpt, prompts, new)
    m = eng.metrics()["serving"]
    assert m["ttft_ms"]["count"] == 4
    assert m["queue_ms"]["count"] == 4
    assert m["tpot_ms"]["count"] == 4        # every stream has >= 2 toks
    assert m["ttft_ms"]["sum"] > 0 and m["tpot_ms"]["sum"] >= 0
    assert m["finished"] == {"reason=length": 4}
    assert m["decode_tokens_per_window"]["count"] >= 1
    # one dispatch_ms observation per engine dispatch (mixed steps are
    # counted inside decode_dispatches)
    assert m["dispatch_ms"]["count"] == eng.stats["decode_dispatches"]
    # stats counters surface in the same snapshot (registry-backed)
    assert m["tokens_generated"] == eng.stats["tokens_generated"]
    # prometheus rendering of the same registry is non-empty and stable
    text = eng.render_prometheus()
    assert "serving_ttft_ms_bucket" in text
    assert text == eng.render_prometheus()
    # queue time is sane: later requests waited for a slot
    assert m["queue_ms"]["sum"] >= 0
    # all timelines closed: no open-request leak
    assert eng._tl._open == {}


def test_flight_recorder_on_nan_drill(gpt, tmp_path, monkeypatch,
                                      metrics_on):
    """Acceptance drill: ``engine_nan_decode`` produces a flight dump
    containing the victim's admission and decode timeline; an identical
    clean run dumps nothing."""
    from paddle_tpu.core import errors
    from paddle_tpu.resilience import faults

    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    rng = np.random.default_rng(13)
    p1 = rng.integers(0, 96, (6,)).astype(np.int32)
    p2 = rng.integers(0, 96, (7,)).astype(np.int32)

    # clean run first: zero dumps
    obs.events.clear()
    eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    eng.add_request(p1, 8)
    eng.run()
    assert os.listdir(tmp_path) == []

    faults.clear()
    obs.events.clear()
    try:
        eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                       max_seq_len=32, decode_window=4,
                                       prefill_chunk=8, q_block=2)
        r1 = eng.add_request(p1, 8)
        r2 = eng.add_request(p2, 8)
        # at=3: dispatches 1-2 are the mixed prefill steps, so the
        # poison lands in a DECODE WINDOW — the dump must show the
        # victim's decode phase, not just its prefill
        faults.inject("engine_nan_decode", match=str(r1), at=3)
        done = eng.run()
        assert done[r1].finish_reason == "failed"
        assert isinstance(done[r1].error, errors.NonFiniteLogitsError)
        assert done[r2].finish_reason == "length"
    finally:
        faults.clear()
    dumps = sorted(os.listdir(tmp_path))
    assert len(dumps) == 1                       # one failure, one dump
    rec = json.load(open(os.path.join(tmp_path, dumps[0])))
    assert rec["reason"] == "nan_decode"
    assert rec["error_code"] == "PDT-E018"
    assert rec["extra"]["rid"] == r1
    evs = rec["events"]
    by_kind = {}
    for e in evs:
        by_kind.setdefault(e["kind"], []).append(e)
    # the victim's full story is in the ring: enqueue, admission,
    # prefill, first token, the injected poison, and the retirement
    assert any(e["rid"] == r1 for e in by_kind["serving.enqueued"])
    assert any(e["rid"] == r1 for e in by_kind["serving.admitted"])
    assert any(e["rid"] == r1 for e in by_kind["serving.prefill_chunk"])
    assert any(e["rid"] == r1 for e in by_kind["serving.first_token"])
    assert any(e["rid"] == r1 for e in by_kind["serving.nan_poison"])
    assert any(e["rid"] == r1 and e["finish_reason"] == "failed"
               for e in by_kind["serving.retired"])
    # decode-phase evidence: the dump is written mid-window (at the
    # guard failure), so the decode DISPATCH events are what it holds
    assert any(e["name"] in ("window", "decode")
               for e in by_kind["serving.dispatch"])
    assert any(e["site"] == "engine_nan_decode"
               for e in by_kind["fault.fired"])


# ==========================================================================
# training telemetry
# ==========================================================================

def test_steptimer_records_and_counts_retraces(metrics_on):
    r = Registry()
    st = obs.StepTimer(registry=r, n_params=1000, peak_flops=1e12,
                       log_every=0)
    st.mark()
    st.step(tokens=512, trace_count=1)      # first: compile baseline
    st.step(tokens=512, trace_count=1)
    st.step(tokens=512, trace_count=3)      # 2 retraces past baseline
    snap = r.snapshot()["train"]
    assert snap["steps"] == 3
    assert snap["step_ms"]["count"] == 3
    assert snap["retraces"] == 2
    assert snap["tokens_per_sec"] > 0
    assert snap["mfu"] == pytest.approx(
        6.0 * 1000 * snap["tokens_per_sec"] / 1e12, rel=1e-3)


def test_fit_populates_global_registry(metrics_on):
    import paddle_tpu.nn as nn

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 4)

        def forward(self, x):
            return self.fc(x)

    net = Net()
    m = paddle.hapi.Model(net)
    m.prepare(paddle.optimizer.Adam(parameters=net.parameters()),
              loss=nn.loss.CrossEntropyLoss())
    xs = np.random.default_rng(0).random((16, 8)).astype("float32")
    ys = np.zeros((16, 1), "int64")
    ds = paddle.io.TensorDataset([paddle.to_tensor(xs),
                                  paddle.to_tensor(ys)])
    reg = obs.registry()
    steps0 = reg.counter("train.steps").value
    hist0 = reg.histogram("train.step_ms").count
    m.fit(ds, batch_size=8, epochs=1, verbose=0)
    assert reg.counter("train.steps").value == steps0 + 2
    assert reg.histogram("train.step_ms").count == hist0 + 2
    assert reg.gauge("train.tokens_per_sec").value > 0


def test_eager_optimizer_step_telemetry(metrics_on):
    import paddle_tpu.nn as nn
    reg = obs.registry()
    h0 = reg.histogram("train.opt_step_ms").count
    net = nn.Linear(8, 8)
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    x = paddle.to_tensor(np.ones((4, 8), "float32"))
    for _ in range(2):
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert reg.histogram("train.opt_step_ms").count == h0 + 2
    # the fused path dispatched one kernel per dtype bucket per step
    assert reg.counter("train.fused_bucket_dispatches").value >= 2


# ==========================================================================
# bench smoke: the metrics_overhead row computes and stays in budget
# ==========================================================================

def test_metrics_overhead_row_smoke(gpt):
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "benchmarks", "serving_bench.py")
    spec = importlib.util.spec_from_file_location(
        "serving_bench_obs_smoke", path)
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    # De-flaked gate (ISSUE 12 satellite): the <= 3% claim belongs to
    # the BENCH ROW (TPU, real model, ~us metric cost amortized over
    # ~ms dispatches); this smoke drives a TINY CPU model whose
    # dispatches are so short that scheduler noise alone swings the
    # ratio by several percent — the old hard 3% gate passed isolated
    # but flaked under tier-1 load (known since PR 11).  The
    # MEASUREMENT still interleaves off/on reps and takes best-of
    # walls each way (drift charges both states equally); the TEST
    # gates on the BEST overhead fraction across attempts at a
    # CPU-appropriate 10% threshold.  A real always-on regression
    # (2x metric cost) fails every attempt by a wide margin; load
    # noise clears one attempt.  12 requests x 16 tokens through the
    # 2-slot geometry the serving suite already compiled keeps walls
    # ~100ms so the gate measures metric cost, not timer resolution.
    row = None
    fracs = []
    for _attempt in range(4):
        row = sb._measure_metrics_overhead(
            gpt.cfg, gpt, slots=2, prompt_len=8, new_tokens=16,
            page_size=8, max_seq_len=32, decode_window=4,
            prefill_chunk=8, q_block=2, reps=10, n_requests=12,
            warm=_attempt == 0)
        fracs.append(row["overhead_frac"])
        if row["overhead_frac"] <= 0.10:   # break at the GATE, not
            break                          # the bench row's 3% claim —
        # or a steady ~5% CPU overhead would run all 4 measurements
        # on every tier-1 pass
    assert row["requests"] == 12
    assert row["tokens_per_sec"] > 0 and row["tokens_per_sec_off"] > 0
    assert math.isfinite(row["overhead_frac"])
    assert min(fracs) <= 0.10, fracs

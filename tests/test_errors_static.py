"""Error/enforce system + static API tests (reference patterns:
``test/cpp/phi/core/test_enforce.cc``, ``test_inference_model_io.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import errors


def test_enforce_helpers():
    errors.enforce(True, "fine")
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce(False, "nope")
    with pytest.raises(ValueError):  # typed errors subclass builtins
        errors.enforce_eq(3, 4, "rank")
    errors.enforce_shape(np.zeros((2, 3)), [None, 3])
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce_shape(np.zeros((2, 3)), [2, 4])
    errors.enforce_dtype(np.zeros((1,), "float32"), ["float32", "bfloat16"])
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce_dtype(np.zeros((1,), "int32"), "float32")


def test_op_errors_carry_context():
    a = paddle.to_tensor(np.zeros((2, 3), "float32"))
    b = paddle.to_tensor(np.zeros((4, 5), "float32"))
    with pytest.raises(errors.EnforceNotMet) as ei:
        paddle.matmul(a, b)
    msg = str(ei.value)
    assert "matmul" in msg and "2,3" in msg and "4,5" in msg
    # still catchable as the builtin class
    with pytest.raises(ValueError):
        paddle.matmul(a, b)


def test_static_data_and_executor_guidance():
    spec = paddle.static.data("x", [None, 8], "float32")
    assert spec.shape == (None, 8)
    exe = paddle.static.Executor()
    with pytest.raises(NotImplementedError):
        exe.run(feed={}, fetch_list=[])
    prog = paddle.static.default_main_program()
    assert prog.clone() is not prog


def test_save_load_inference_model_roundtrip(tmp_path):
    import paddle_tpu.nn as nn
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    prefix = str(tmp_path / "infer")
    spec = [paddle.static.InputSpec([None, 4], "float32", "x")]
    paddle.static.save_inference_model(prefix, spec, net)

    exe = paddle.static.Executor()
    prog, feed_names, fetch_names = paddle.static.load_inference_model(
        prefix, exe)
    x = np.random.default_rng(0).normal(size=(3, 4)).astype("float32")
    out = exe.run(prog, feed={feed_names[0]: x}, fetch_list=fetch_names)
    ref = np.asarray(net(paddle.to_tensor(x))._read())
    np.testing.assert_allclose(out[0], ref, atol=1e-5)

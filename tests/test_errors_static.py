"""Error/enforce system + static API tests (reference patterns:
``test/cpp/phi/core/test_enforce.cc``, ``test_inference_model_io.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import errors


def test_enforce_helpers():
    errors.enforce(True, "fine")
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce(False, "nope")
    with pytest.raises(ValueError):  # typed errors subclass builtins
        errors.enforce_eq(3, 4, "rank")
    errors.enforce_shape(np.zeros((2, 3)), [None, 3])
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce_shape(np.zeros((2, 3)), [2, 4])
    errors.enforce_dtype(np.zeros((1,), "float32"), ["float32", "bfloat16"])
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce_dtype(np.zeros((1,), "int32"), "float32")


def test_op_errors_carry_context():
    a = paddle.to_tensor(np.zeros((2, 3), "float32"))
    b = paddle.to_tensor(np.zeros((4, 5), "float32"))
    with pytest.raises(errors.EnforceNotMet) as ei:
        paddle.matmul(a, b)
    msg = str(ei.value)
    assert "matmul" in msg and "2,3" in msg and "4,5" in msg
    # still catchable as the builtin class
    with pytest.raises(ValueError):
        paddle.matmul(a, b)


def test_static_data_and_executor_guidance():
    spec = paddle.static.data("x", [None, 8], "float32")
    assert spec.shape == (None, 8)
    exe = paddle.static.Executor()
    with pytest.raises(NotImplementedError):
        exe.run(feed={}, fetch_list=[])
    prog = paddle.static.default_main_program()
    assert prog.clone() is not prog


def test_save_load_inference_model_roundtrip(tmp_path):
    import paddle_tpu.nn as nn
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    prefix = str(tmp_path / "infer")
    spec = [paddle.static.InputSpec([None, 4], "float32", "x")]
    paddle.static.save_inference_model(prefix, spec, net)

    exe = paddle.static.Executor()
    prog, feed_names, fetch_names = paddle.static.load_inference_model(
        prefix, exe)
    x = np.random.default_rng(0).normal(size=(3, 4)).astype("float32")
    out = exe.run(prog, feed={feed_names[0]: x}, fetch_list=fetch_names)
    ref = np.asarray(net(paddle.to_tensor(x))._read())
    np.testing.assert_allclose(out[0], ref, atol=1e-5)


def test_error_codes_stable_and_unique():
    """Every EnforceNotMet subclass carries a stable, unique error_code
    (the phi::ErrorCode analog)."""
    import re

    def subclasses(cls):
        out = set()
        for c in cls.__subclasses__():
            out.add(c)
            out |= subclasses(c)
        return out

    classes = {errors.EnforceNotMet} | subclasses(errors.EnforceNotMet)
    codes = {}
    for c in classes:
        code = c.__dict__.get("error_code")
        assert code, f"{c.__name__} has no own error_code"
        assert re.match(r"^PDT-E\d{3}$", code), (c.__name__, code)
        assert code not in codes, \
            f"{c.__name__} shares {code} with {codes[code]}"
        codes[code] = c.__name__
    # the documented anchors stay put (stability contract)
    assert errors.EnforceNotMet.error_code == "PDT-E000"
    assert errors.InvalidArgumentError.error_code == "PDT-E001"
    assert errors.StaticAnalysisError.error_code == "PDT-E012"


def test_reraise_preserves_cause_and_traceback():
    """_reraise_with_op_context must chain the original exception as
    __cause__ with its traceback intact (the frames that actually
    raised), and tag the wrapper with the op name + error code."""
    import traceback

    from paddle_tpu.core import dispatch

    def kernel(x):
        raise ZeroDivisionError("boom in kernel")

    with pytest.raises(errors.InvalidArgumentError) as ei:
        dispatch.apply("my_op", kernel, paddle.to_tensor(np.zeros(2)))
    e = ei.value
    assert isinstance(e.__cause__, ZeroDivisionError)
    assert str(e.__cause__) == "boom in kernel"
    tb = e.__cause__.__traceback__
    assert tb is not None
    frames = [f.name for f in traceback.extract_tb(tb)]
    assert "kernel" in frames, frames  # the raising frame survived
    assert e.op_name == "my_op"
    assert "my_op" in str(e) and "[PDT-E001]" in str(e)


def test_op_context_keeps_framework_error_codes():
    """A framework-typed kernel error passes through unwrapped, code and
    all (EnforceNotMet never gets double-wrapped)."""
    from paddle_tpu.core import dispatch

    def kernel(x):
        raise errors.OutOfRangeError("index 9 out of range")

    with pytest.raises(errors.OutOfRangeError) as ei:
        dispatch.apply("gather", kernel, paddle.to_tensor(np.zeros(2)))
    assert ei.value.error_code == "PDT-E003"
    assert ei.value.__cause__ is None  # passed through, not wrapped

"""SLO guardrails, stall watchdog and the regression sentinel
(ISSUE 14): shared percentile math, burn-rate window math on a fake
clock, SLO pass/breach on slot-contention traffic through the session
tiny GPT, the ``engine_stall`` drill (coded ``EngineStallError`` within
the deadline, exactly one flight dump holding thread stacks and the
victim's timeline, zero dumps + nothing armed on clean runs,
co-residents bitwise), flight-dump keep-last-K retention, metrics-off
no-op parity, and the regress CLI (golden report, nonzero exit on an
injected 20% regression, tolerant loading of the real r01-r05 files).

Engine tests reuse the session ``serving_gpt`` and the exact geometry
the serving suite already compiled (max_slots=2/page_size=8/...), so
they ride cached programs — tier-1 budget, not semantics.
"""
import json
import os
import shutil
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.core import errors
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.observability import watchdog as wdog
from paddle_tpu.observability.metrics import (LATENCY_BUCKETS_MS,
                                              Registry,
                                              percentile_from_counts)
from paddle_tpu.observability.slo import SLOEngine, SLOSpec, parse_slo
from paddle_tpu.resilience import faults

_REPO = os.path.join(os.path.dirname(__file__), os.pardir)

# the geometry every serving suite compiles against (conftest comment)
_KW = dict(max_slots=2, page_size=8, max_seq_len=32, decode_window=4,
           prefill_chunk=8, q_block=2)


@pytest.fixture
def gpt(serving_gpt):
    return serving_gpt


@pytest.fixture
def metrics_on():
    old = paddle.get_flags("metrics")["metrics"]
    paddle.set_flags({"metrics": True})
    yield
    paddle.set_flags({"metrics": old})


def _prompts(seed=0, sizes=(5, 9)):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 96, (n,)).astype(np.int32) for n in sizes]


# ==========================================================================
# shared percentile math (satellite: _tl_pct dedupe)
# ==========================================================================

def test_histogram_percentile(metrics_on):
    h = Registry().histogram("h", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 2.0, 3.0, 20.0):
        h.observe(v)
    # q=0.5 -> 2nd of 4 observations -> the (1,10] bucket's upper edge
    assert h.percentile(0.5) == 10.0
    assert h.percentile(0.25) == 1.0
    assert h.percentile(1.0) == 100.0
    h.observe(1000.0)             # overflow bucket: no finite edge
    assert h.percentile(1.0) == float("inf")
    assert Registry().histogram("e").percentile(0.99) == 0.0
    # the module function is the same math over raw state
    assert percentile_from_counts(h.buckets, h.counts, h.count,
                                  0.5) == h.percentile(0.5)


def test_bench_tl_pct_uses_shared_percentile(gpt, metrics_on):
    """serving_bench's ``_tl_pct``/``_tl_mean`` must agree with the
    live histogram's own ``percentile()``/``mean`` — one home for the
    math (byte-identical bench columns are the satellite's claim)."""
    import importlib.util
    path = os.path.join(_REPO, "benchmarks", "serving_bench.py")
    spec = importlib.util.spec_from_file_location(
        "serving_bench_slo_smoke", path)
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    eng = ContinuousBatchingEngine(gpt, **_KW)
    for p in _prompts():
        eng.add_request(p, 6)
    eng.run()
    h_ttft = eng._registry.histogram("serving.ttft_ms")
    assert h_ttft.count > 0
    for q in (0.5, 0.95, 0.99):
        assert sb._tl_pct(eng, "ttft_ms", q) == h_ttft.percentile(q)
    assert sb._tl_mean(eng, "ttft_ms") == pytest.approx(h_ttft.mean)


# ==========================================================================
# SLO engine: spec parse + burn-rate window math (fake clock)
# ==========================================================================

def test_parse_slo():
    specs = parse_slo("ttft_p95_ms=500, tpot_p99_ms=100; goodput=0.99")
    by = {s.name: s for s in specs}
    assert by["ttft_p95_ms"].metric == "serving.ttft_ms"
    assert by["ttft_p95_ms"].threshold == 500.0
    assert by["ttft_p95_ms"].budget == pytest.approx(0.05)
    assert by["tpot_p99_ms"].budget == pytest.approx(0.01)
    assert by["goodput"].kind == "ratio"
    assert by["goodput"].objective == 0.99
    assert by["goodput"].budget == pytest.approx(0.01)
    assert parse_slo("") == [] and parse_slo(None) == []
    assert len(parse_slo(specs)) == 3          # list passthrough
    with pytest.raises(ValueError, match="unknown SLO spec"):
        parse_slo("ttft_p95=500")
    with pytest.raises(ValueError, match="objective"):
        SLOSpec("g", "serving.finished", kind="ratio", objective=1.5)


def test_slo_burn_rate_window_math(metrics_on):
    """Exact multi-window burn-rate accounting on a fake clock: fast
    window reacts, slow window confirms, breach fires once on the
    transition, recovery clears it, budget_remaining tracks the slow
    window's bad fraction against the budget."""
    t = [0.0]
    reg = Registry()
    h = reg.histogram("serving.ttft_ms", buckets=LATENCY_BUCKETS_MS)
    spec = SLOSpec("ttft_p95_ms", "serving.ttft_ms", threshold=10.0,
                   percentile=0.95, fast_window_s=10.0,
                   slow_window_s=60.0)
    breaches = []
    slo = SLOEngine(reg, [spec], clock=lambda: t[0],
                    on_breach=breaches.append)
    # 100 good observations at t=1
    t[0] = 1.0
    for _ in range(100):
        h.observe(1.0)
    (st,) = slo.evaluate()
    assert st["ok"] and not st["breached"]
    assert st["burn_fast"] == 0.0 and st["budget_remaining"] == 1.0
    # t=5: 50 bad observations -> fast window sees 50/150 bad
    t[0] = 5.0
    for _ in range(50):
        h.observe(1000.0)
    (st,) = slo.evaluate()
    assert st["burn_fast"] == pytest.approx((50 / 150) / 0.05)
    assert st["burn_slow"] == pytest.approx((50 / 150) / 0.05)
    assert st["breached"] and not st["ok"]
    assert st["value"] > 10.0                 # windowed p95 is bad
    assert st["budget_remaining"] == 0.0
    assert len(breaches) == 1                 # transition, not per-eval
    (st,) = slo.evaluate()
    assert st["breached"] and len(breaches) == 1
    # t=120: both windows have rolled past the bad burst; fresh good
    # traffic -> burn 0, recovered
    t[0] = 120.0
    for _ in range(20):
        h.observe(1.0)
    (st,) = slo.evaluate()
    assert st["burn_fast"] == 0.0 and st["burn_slow"] == 0.0
    assert st["ok"] and not st["breached"]
    assert st["budget_remaining"] == 1.0
    assert len(breaches) == 1
    kinds = [e["kind"] for e in obs.tail()]
    assert "slo.breach" in kinds and "slo.recovered" in kinds
    # budget gauges render through the registry
    assert "slo_budget_remaining" in reg.render_prometheus()


def test_slo_ratio_goodput(metrics_on):
    t = [0.0]
    reg = Registry()
    spec = SLOSpec("goodput", "serving.finished", kind="ratio",
                   objective=0.9, fast_window_s=10.0,
                   slow_window_s=60.0)
    slo = SLOEngine(reg, [spec], clock=lambda: t[0])
    good = reg.counter("serving.finished", labels={"reason": "length"})
    bad = reg.counter("serving.finished", labels={"reason": "timeout"})
    t[0] = 1.0
    good.inc(98)
    bad.inc(2)
    (st,) = slo.evaluate()
    assert st["ok"] and st["value"] == pytest.approx(0.98)
    assert st["burn_slow"] == pytest.approx(0.02 / 0.1)
    t[0] = 2.0
    bad.inc(50)                    # timeouts burn the goodput budget
    (st,) = slo.evaluate()
    assert not st["ok"] and st["breached"]
    assert st["value"] == pytest.approx(98 / 150)


# ==========================================================================
# engine integration: pass / breach / flight dump / prometheus
# ==========================================================================

def test_engine_slo_pass_and_breach(gpt, tmp_path, monkeypatch,
                                    metrics_on):
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    obs.events.clear()
    # generous objectives: slot-contention traffic passes them
    eng = ContinuousBatchingEngine(
        gpt, **_KW, slo="ttft_p95_ms=100000,goodput=0.5")
    for p in _prompts():
        eng.add_request(p, 6)
    eng.run()
    sts = eng.slo_status()
    assert [s["name"] for s in sts] == ["ttft_p95_ms", "goodput"]
    assert all(s["ok"] and not s["breached"] for s in sts)
    assert all(s["budget_remaining"] == 1.0 for s in sts)
    assert sts[1]["window_total"] == 2        # both requests retired ok
    assert "slo_budget_remaining" in eng.render_prometheus()
    assert os.listdir(tmp_path) == []         # no dump on a clean pass

    # impossible objective: every TTFT observation violates it ->
    # burn-rate breach on both windows -> slo.breach + ONE flight dump
    eng2 = ContinuousBatchingEngine(gpt, **_KW,
                                    slo="ttft_p95_ms=0.000001")
    for p in _prompts(seed=1):
        eng2.add_request(p, 6)
    eng2.run()
    (st,) = eng2.slo_status()
    assert st["breached"] and not st["ok"]
    assert st["burn_slow"] > 1.0 and st["budget_remaining"] == 0.0
    dumps = [f for f in sorted(os.listdir(tmp_path))
             if f.endswith(".json") and not f.endswith(".trace.json")]
    assert len(dumps) == 1                    # one transition, one dump
    rec = json.load(open(os.path.join(tmp_path, dumps[0])))
    assert rec["reason"] == "slo_breach"
    assert rec["extra"]["name"] == "ttft_p95_ms"
    assert any(e["kind"] == "slo.breach" for e in rec["events"])


# ==========================================================================
# stall watchdog: the engine_stall drill + clean-run disarm
# ==========================================================================

def test_engine_stall_drill(gpt, tmp_path, monkeypatch, metrics_on):
    """Acceptance drill: a deliberately-hung dispatch produces a coded
    EngineStallError within the deadline, exactly one flight dump
    containing thread stacks and the victim's lifecycle events, zero
    dumps on the clean run, and co-resident requests complete bitwise
    against the clean run."""
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    faults.clear()
    obs.events.clear()
    prompts = _prompts(seed=3)

    # clean run, watchdog armed: nothing fires, nothing stays armed
    eng = ContinuousBatchingEngine(gpt, **_KW, watchdog_ms=10000)
    rids = [eng.add_request(p, 6) for p in prompts]
    done_clean = eng.run()
    assert os.listdir(tmp_path) == []
    assert wdog.armed() == []

    deadline_ms = 300.0
    faults.inject("engine_stall", match="mixed", at=2)
    try:
        eng2 = ContinuousBatchingEngine(gpt, **_KW,
                                        watchdog_ms=deadline_ms)
        rids2 = [eng2.add_request(p, 6) for p in prompts]
        done, n_raised = {}, 0
        t0 = time.monotonic()
        while eng2.has_work:
            try:
                cs = eng2.step()
            except errors.EngineStallError as e:
                n_raised += 1
                # coded, and within the deadline (+ poll + slack)
                assert e.error_code == "PDT-E020"
                assert "mixed" in str(e)
                assert time.monotonic() - t0 < 10.0
                continue
            for c in cs:
                done[c.request_id] = c
    finally:
        faults.clear()
    assert n_raised == 1
    assert wdog.armed() == []
    # co-residents complete bitwise: the stalled dispatch never ran,
    # so the re-planned dispatch reproduces the clean stream exactly
    for r1, r2 in zip(rids, rids2):
        np.testing.assert_array_equal(done_clean[r1].sequence,
                                      done[r2].sequence)
    recs = [f for f in sorted(os.listdir(tmp_path))
            if f.endswith(".json") and not f.endswith(".trace.json")]
    assert len(recs) == 1                     # exactly one flight dump
    rec = json.load(open(os.path.join(tmp_path, recs[0])))
    assert rec["reason"] == "watchdog_stall"
    assert rec["extra"]["site"] == "serving.dispatch"
    assert rec["extra"]["key"] == "mixed"
    # thread stacks captured, including the stalled dispatch frame
    stacks = rec["extra"]["stacks"]
    assert stacks and any("simulated_stall" in s
                          for s in stacks.values())
    kinds = [e["kind"] for e in rec["events"]]
    assert "watchdog.stall" in kinds
    # the victims' lifecycle is in the dump: enqueue + admission of
    # both co-resident requests, and the drill's fault firing
    for want in ("serving.enqueued", "serving.admitted", "fault.fired"):
        assert want in kinds, want
    enq = [e["rid"] for e in rec["events"]
           if e["kind"] == "serving.enqueued"]
    assert set(rids2) <= set(enq)


def test_watchdog_heartbeat_and_fit_disarm(tmp_path, monkeypatch,
                                           metrics_on):
    """Heartbeats hold a slow-but-alive operation past its deadline
    without firing; a fit armed via the watchdog_stall_ms flag
    disarms cleanly (zero dumps, nothing armed)."""
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    token = wdog.arm("unit.op", 120.0, key="hb")
    for _ in range(4):
        time.sleep(0.06)
        token.heartbeat()
    assert not token.fired
    token.disarm()
    assert wdog.armed() == []

    import paddle_tpu.nn as nn
    old = paddle.get_flags("watchdog_stall_ms")["watchdog_stall_ms"]
    paddle.set_flags({"watchdog_stall_ms": 60000.0})
    try:
        net = nn.Linear(8, 4)
        m = paddle.hapi.Model(net)
        m.prepare(paddle.optimizer.Adam(parameters=net.parameters()),
                  loss=nn.loss.CrossEntropyLoss())
        xs = np.random.default_rng(0).random((8, 8)).astype("float32")
        ys = np.zeros((8, 1), "int64")
        ds = paddle.io.TensorDataset([paddle.to_tensor(xs),
                                      paddle.to_tensor(ys)])
        m.fit(ds, batch_size=4, epochs=1, verbose=0)
    finally:
        paddle.set_flags({"watchdog_stall_ms": old})
    assert wdog.armed() == []                 # disarm on clean runs
    assert os.listdir(tmp_path) == []


def test_watchdog_fires_and_rearms_on_heartbeat(tmp_path, monkeypatch,
                                                metrics_on):
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    obs.events.clear()
    token = wdog.arm("unit.op", 80.0, key="stall")
    deadline = time.monotonic() + 5.0
    # dump_path is set at the END of the fire sequence (the interrupt
    # goes out before the dump's file IO), so wait on it, not on fired
    while token.dump_path is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert token.fired
    assert token.dump_path and os.path.exists(token.dump_path)
    assert any(e["kind"] == "watchdog.stall" and e["key"] == "stall"
               for e in obs.tail())
    token.heartbeat()                         # re-arm clears the latch
    assert not token.fired
    token.disarm()


# ==========================================================================
# flight-dump retention (satellite: keep-last-K GC)
# ==========================================================================

def test_flight_dump_retention(tmp_path, monkeypatch, metrics_on):
    """Watchdog/SLO/NaN dumps all funnel through events.dump, so the
    keep-last-K cap (flight_keep flag / PDTPU_FLIGHT_KEEP) bounds the
    dir no matter who dumps; companion files die with their record."""
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    old = paddle.get_flags("flight_keep")["flight_keep"]
    paddle.set_flags({"flight_keep": 3})
    try:
        paths = []
        for i in range(6):
            p = obs.dump(f"retention_{i}")
            assert p is not None
            paths.append(p)
            # companion like the watchdog writes next to its record
            open(p[:-len(".json")] + ".trace.json", "w").write("{}")
            # distinct mtimes (same-second dumps tie-break by name,
            # which is already seq order; make age explicit anyway)
            os.utime(p, (1_000_000 + i, 1_000_000 + i))
        recs = [f for f in sorted(os.listdir(tmp_path))
                if f.endswith(".json")
                and not f.endswith(".trace.json")]
        assert len(recs) == 3
        # the newest three survived, companions of the dead are gone
        assert os.path.basename(paths[-1]) in recs
        assert os.path.basename(paths[0]) not in recs
        assert not os.path.exists(paths[0][:-len(".json")]
                                  + ".trace.json")
        assert os.path.exists(paths[-2][:-len(".json")]
                              + ".trace.json")
    finally:
        paddle.set_flags({"flight_keep": old})


# ==========================================================================
# metrics-off no-op parity
# ==========================================================================

def test_metrics_off_guardrails_noop(gpt, tmp_path, monkeypatch):
    """With PDTPU_METRICS off, slo=/watchdog_ms= arm NOTHING: outputs
    match the guardrail-free engine bitwise, slo_status is empty, no
    dumps are written, and watchdog.arm returns the null token."""
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    prompts = _prompts(seed=5)
    old = paddle.get_flags("metrics")["metrics"]
    try:
        paddle.set_flags({"metrics": True})
        eng_ref = ContinuousBatchingEngine(gpt, **_KW)
        r_ref = [eng_ref.add_request(p, 6) for p in prompts]
        done_ref = eng_ref.run()
        paddle.set_flags({"metrics": False})
        assert wdog.arm("x", 100.0) is wdog.NULL_TOKEN
        eng = ContinuousBatchingEngine(
            gpt, **_KW, slo="ttft_p95_ms=0.000001", watchdog_ms=50.0)
        rids = [eng.add_request(p, 6) for p in prompts]
        done = eng.run()
        assert eng.slo_status() == []
        assert wdog.armed() == []
    finally:
        paddle.set_flags({"metrics": old})
    for a, b in zip(r_ref, rids):
        np.testing.assert_array_equal(done_ref[a].sequence,
                                      done[b].sequence)
    assert os.listdir(tmp_path) == []


# ==========================================================================
# regression sentinel
# ==========================================================================

def test_regress_real_history_loads_and_passes(capsys):
    """The checked-in BENCH_r01-r05 files: r01/r04 are truncated and
    must be tolerated (skipped, not fatal); the judged r05 round is
    an improvement, so the CLI exits 0."""
    from paddle_tpu.observability import regress
    rc = regress.main([_REPO])
    out = capsys.readouterr().out
    assert rc == 0
    assert "# BENCH r01 skipped" in out
    assert "# BENCH r04 skipped" in out
    assert "OK         BENCH.value" in out
    assert "REGRESSION" not in out
    assert out.strip().endswith("regressions: none")


def test_regress_flags_injected_regression(tmp_path, capsys):
    """A synthetic 20% tok/s regression appended as r06 is flagged
    (nonzero exit) while every other metric stays clean."""
    from paddle_tpu.observability import regress
    for r in range(1, 6):
        shutil.copy(os.path.join(_REPO, f"BENCH_r{r:02d}.json"),
                    tmp_path)
    r05 = json.load(open(os.path.join(_REPO, "BENCH_r05.json")))
    bad = dict(r05["parsed"])
    bad["value"] = round(bad["value"] * 0.8, 1)
    json.dump({"n": 6, "parsed": bad, "tail": "", "rc": 0},
              open(os.path.join(tmp_path, "BENCH_r06.json"), "w"))
    rc = regress.main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION BENCH.value" in out
    assert out.strip().endswith("regressions: BENCH.value")
    # vs_baseline/step_time/mfu were not scaled: they stay OK
    assert "REGRESSION BENCH.vs_baseline" not in out
    assert "REGRESSION BENCH.extra.step_time_ms" not in out


def test_regress_golden_report(tmp_path, capsys):
    """Stable sorted text over a synthetic history — the golden the
    CLI contract is pinned to (like render_prometheus)."""
    from paddle_tpu.observability import regress
    vals = [100.0, 102.0, 98.0, 101.0]
    for i, v in enumerate(vals, start=1):
        json.dump({"n": i, "rc": 0, "tail": "", "parsed": {
            "metric": "m", "value": v, "unit": "tokens/sec",
            "extra": {"step_time_ms": 1000.0 / v, "mfu": v / 400.0}}},
            open(os.path.join(tmp_path, f"BENCH_r{i:02d}.json"), "w"))
    json.dump({"n": 5, "rc": 0, "tail": "", "parsed": {
        "metric": "m", "value": 80.0, "unit": "tokens/sec",
        "extra": {"step_time_ms": 12.5, "mfu": 0.2}}},
        open(os.path.join(tmp_path, "BENCH_r05.json"), "w"))
    rc = regress.main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert out == (
        "# BENCH: judging r05 against 4 prior round(s)\n"
        "REGRESSION BENCH.extra.mfu latest=0.2 baseline=0.25125 "
        "mad=0.0025 z=+13.83\n"
        "REGRESSION BENCH.extra.step_time_ms latest=12.5 "
        "baseline=9.9505 mad=0.0980392 z=+17.54\n"
        "REGRESSION BENCH.value latest=80 baseline=100.5 mad=1 "
        "z=+13.83\n"
        "regressions: BENCH.extra.mfu, BENCH.extra.step_time_ms, "
        "BENCH.value\n")


def test_regress_check_record_and_stale_subtrees(tmp_path):
    """bench.py's hook: the in-flight record is judged against the
    on-disk history; ``cached`` subtrees are stale re-reports and
    never feed baselines or judgment."""
    from paddle_tpu.observability import regress
    for i, v in enumerate((100.0, 101.0, 99.0), start=1):
        json.dump({"n": i, "rc": 0, "tail": "", "parsed": {
            "metric": "m", "value": v,
            "extra": {"sub": {"cached": True, "value": 5.0}}}},
            open(os.path.join(tmp_path, f"BENCH_r{i:02d}.json"), "w"))
    clean = {"metric": "m", "value": 100.5,
             "extra": {"sub": {"cached": True, "value": 1.0}}}
    assert regress.check_record(clean, str(tmp_path)) == []
    bad = dict(clean, value=60.0)
    assert regress.check_record(bad, str(tmp_path)) == ["BENCH.value"]
    # the cached subtree's 5.0 -> 1.0 "drop" was never judged
    report, _ = regress.analyze(str(tmp_path), extra_latest=bad)
    assert "extra.sub" not in report

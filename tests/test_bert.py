"""BERT model family (BASELINE config 3 class): forward shapes, MLM+NSP
pretraining convergence under jit, TP sharding parity, and sharding-2
(ZeRO) training on the virtual mesh."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                    BertForSequenceClassification,
                                    shard_bert)

CFG = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
           max_seq_len=32, dropout=0.0)


def _data(rng, b=4, s=16, vocab=128):
    ids = rng.integers(0, vocab, (b, s)).astype(np.int32)
    tt = (np.arange(s)[None, :] >= s // 2).astype(np.int32) * np.ones(
        (b, 1), np.int32)
    mlm = np.where(rng.random((b, s)) < 0.3, ids, -100).astype(np.int32)
    nsp = rng.integers(0, 2, (b,)).astype(np.int32)
    return ids, tt, mlm, nsp


def test_forward_shapes():
    paddle.seed(0)
    model = BertForPretraining(BertConfig(**CFG))
    rng = np.random.default_rng(0)
    ids, tt, mlm, nsp = _data(rng)
    logits = model(paddle.to_tensor(ids), paddle.to_tensor(tt))
    assert tuple(logits.shape) == (4, 16, 128)
    h, pooled = model.bert(paddle.to_tensor(ids))
    assert tuple(pooled.shape) == (4, 32)


def test_pretraining_loss_converges_under_jit():
    paddle.seed(0)
    model = BertForPretraining(BertConfig(**CFG))
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    rng = np.random.default_rng(1)
    ids, tt, mlm, nsp = _data(rng)

    @paddle.jit.to_static
    def step(i, t, m, n):
        loss = model(i, t, mlm_labels=m, nsp_labels=n)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    args = tuple(paddle.to_tensor(v) for v in (ids, tt, mlm, nsp))
    losses = [float(step(*args)) for _ in range(10)]
    assert losses[-1] < losses[0] * 0.8, losses


def test_mlm_ignore_index():
    """Positions labelled -100 must not contribute to the loss."""
    paddle.seed(0)
    model = BertForPretraining(BertConfig(**CFG))
    rng = np.random.default_rng(2)
    ids, tt, mlm, _ = _data(rng)
    all_ignored = np.full_like(mlm, -100)
    l1 = model(paddle.to_tensor(ids), paddle.to_tensor(tt),
               mlm_labels=paddle.to_tensor(mlm))
    l2 = model(paddle.to_tensor(ids), paddle.to_tensor(tt),
               mlm_labels=paddle.to_tensor(all_ignored))
    assert float(l1) > 0 and abs(float(l2)) < 1e-5


def test_tp_sharding_parity():
    """shard_bert over mp=2 computes the same loss as unsharded."""
    mesh = dist.ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])
    paddle.seed(0)
    ref = BertForPretraining(BertConfig(**CFG))
    paddle.seed(0)
    tp = BertForPretraining(BertConfig(**CFG))
    shard_bert(tp, mesh, dp_axis="dp", mp_axis="mp")
    rng = np.random.default_rng(3)
    ids, tt, mlm, nsp = _data(rng)
    args = tuple(paddle.to_tensor(v) for v in (ids, tt, mlm, nsp))
    l_ref = ref(args[0], args[1], mlm_labels=args[2], nsp_labels=args[3])
    l_tp = tp(args[0], args[1], mlm_labels=args[2], nsp_labels=args[3])
    np.testing.assert_allclose(float(l_ref), float(l_tp), rtol=1e-4)


def test_sharding2_training():
    """BASELINE config 3 shape: BERT + ZeRO sharding-2 — optimizer
    moments shard over the sharding axis and the loss still converges."""
    import paddle_tpu.distributed.fleet as fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 8,
                               "sep_degree": 1}
    hcg_prev = fleet.get_hybrid_communicate_group()
    fleet.init(is_collective=True, strategy=strategy)
    try:
        paddle.seed(0)
        model = BertForPretraining(BertConfig(**CFG))
        model.train()
        inner = paddle.optimizer.AdamW(learning_rate=3e-3,
                                       parameters=model.parameters())
        opt = fleet.DygraphShardingOptimizer(
            inner, fleet.get_hybrid_communicate_group(), stage=2)
        rng = np.random.default_rng(4)
        ids, tt, mlm, nsp = _data(rng, b=8)

        @paddle.jit.to_static
        def step(i, t, m, n):
            loss = model(i, t, mlm_labels=m, nsp_labels=n)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        args = tuple(paddle.to_tensor(v) for v in (ids, tt, mlm, nsp))
        losses = [float(step(*args)) for _ in range(8)]
        assert losses[-1] < losses[0], losses
        # adam moments really are sharded over the 8-way sharding axis
        w = model.bert.layers[0].fc1.weight
        m = inner._accumulators["moment1"][id(w)]
        shapes = {s.data.shape for s in m._read().addressable_shards}
        assert shapes == {(32 // 8, 128)}, shapes
    finally:
        fleet.set_hybrid_communicate_group(hcg_prev)


def test_sequence_classification():
    paddle.seed(0)
    model = BertForSequenceClassification(BertConfig(**CFG), num_classes=3)
    rng = np.random.default_rng(5)
    ids, tt, _, _ = _data(rng)
    logits = model(paddle.to_tensor(ids), paddle.to_tensor(tt))
    assert tuple(logits.shape) == (4, 3)
    loss = model(paddle.to_tensor(ids), paddle.to_tensor(tt),
                 labels=paddle.to_tensor(rng.integers(0, 3, (4,))
                                         .astype(np.int32)))
    assert float(loss) > 0


def test_masked_gather_mlm_head_parity():
    """cfg.max_predictions gathers the masked positions before the vocab
    projection (the reference's max_predictions_per_seq contract); with
    <= K masked per row the loss is identical to the dense head."""
    rng = np.random.default_rng(3)
    b, s, k = 3, 32, 8
    paddle.seed(0)
    dense = BertForPretraining(BertConfig(**CFG))
    paddle.seed(0)
    gathered = BertForPretraining(BertConfig(**CFG, max_predictions=k))

    ids = rng.integers(0, 128, (b, s)).astype(np.int32)
    tt = np.zeros((b, s), np.int32)
    mlm = np.full((b, s), -100, np.int32)
    for i in range(b):
        pos = rng.choice(s, size=k - 2, replace=False)
        mlm[i, pos] = rng.integers(0, 128, k - 2)
    nsp = rng.integers(0, 2, (b,)).astype(np.int32)
    args = [paddle.to_tensor(v) for v in (ids, tt, mlm, nsp)]
    np.testing.assert_allclose(float(dense(*args)), float(gathered(*args)),
                               rtol=1e-5)
    # more masked than K: extras drop, loss stays finite (the reference
    # data pipeline guarantees <= K; this is the out-of-contract guard)
    over = np.where(rng.random((b, s)) < 0.9, ids, -100).astype(np.int32)
    lv = float(gathered(args[0], args[1], paddle.to_tensor(over), args[3]))
    assert np.isfinite(lv)

"""Native C++ IO runtime tests (analog of the reference's
buffered_reader / blocking_queue C++ unit tests, SURVEY §4)."""
import threading

import numpy as np
import pytest

from paddle_tpu.io import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


def test_normalize_batch_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.integers(0, 256, (8, 16, 12, 3), dtype=np.uint8)
    mean = [10.0, 20.0, 30.0]
    std = [2.0, 3.0, 4.0]
    out = native.normalize_batch(src, mean, std, to_chw=True)
    ref = ((src.astype(np.float32) - np.float32(mean)) /
           np.float32(std)).transpose(0, 3, 1, 2)
    np.testing.assert_allclose(out, ref, atol=1e-5)
    out2 = native.normalize_batch(src, mean, std, to_chw=False)
    np.testing.assert_allclose(
        out2, (src.astype(np.float32) - np.float32(mean)) /
        np.float32(std), atol=1e-5)


def test_nhwc_to_nchw_and_gather():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 5, 6, 3)).astype("float32")
    np.testing.assert_array_equal(native.nhwc_to_nchw(x),
                                  x.transpose(0, 3, 1, 2))
    base = rng.integers(0, 255, (10, 33), dtype=np.uint8)
    idx = np.array([9, 0, 3, 3], np.int64)
    np.testing.assert_array_equal(native.gather_rows(base, idx), base[idx])


def test_native_queue_producer_consumer():
    q = native.NativeQueue(capacity=2)
    payloads = [np.full((5,), i, np.int32) for i in range(6)]
    got = []

    def producer():
        for p in payloads:
            assert q.push(p)
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    while True:
        item = q.pop(20, np.int32, (5,))
        if item is None:
            break
        got.append(item.copy())
    t.join()
    assert len(got) == 6
    for i, g in enumerate(got):
        np.testing.assert_array_equal(g, payloads[i])


def test_queue_capacity_blocks():
    q = native.NativeQueue(capacity=1)
    assert q.push(np.zeros(3, np.uint8))
    assert q.size() == 1
    state = {}

    def push_second():
        state["r"] = q.push(np.ones(3, np.uint8))

    t = threading.Thread(target=push_second)
    t.start()
    t.join(timeout=0.2)
    assert t.is_alive()  # blocked on capacity
    q.pop(3)
    t.join(timeout=2)
    assert not t.is_alive() and state["r"]
    q.close()


def test_batch_normalize_transform():
    from paddle_tpu.vision.transforms import BatchNormalize
    rng = np.random.default_rng(2)
    src = rng.integers(0, 256, (4, 8, 8, 1), dtype=np.uint8)
    out = BatchNormalize([127.5], [127.5])(src)
    assert out.shape == (4, 1, 8, 8) and out.dtype == np.float32
    with pytest.raises(ValueError):
        BatchNormalize([0.0], [1.0])(src.astype("float32"))


"""Speculative decoding subsystem (ISSUE 9).

Correctness model: greedy engine outputs with ``spec_decode`` on —
either proposer, any drill — must be BITWISE-identical to
``spec_decode`` off and to ``generate(kv_cache='paged')``.  Drafts may
only change how many tokens a dispatch emits, never which; the
acceptance rule guarantees that for ANY proposal, so every test here
pins outputs first and throughput accounting second.

Budget note: the suite reuses the session-scoped ``serving_gpt`` tiny
model and the SAME engine geometry as tests/test_serving_engine.py
(max_slots=2, page_size=4, max_seq_len=32, q_block=2), so the fp
reference programs are already compiled; the speculative tests share
ONE spec program among themselves (spec_k=3 keeps one token budget).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (ContinuousBatchingEngine,
                                  DraftModelProposer, NGramProposer)
from paddle_tpu.inference.speculative import (accept_greedy,
                                              accept_sampled)
from paddle_tpu.models import generate


@pytest.fixture(scope="module")
def gpt(serving_gpt):
    # session tiny model (tests/conftest.py): compiled programs are
    # shared with test_serving_engine / test_quant_serving
    return serving_gpt


@pytest.fixture(scope="module")
def draft_gpt():
    """A smaller, differently-seeded GPT: a REAL draft model (its
    greedy picks genuinely differ from the target's)."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(1)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=96, hidden_size=16, num_layers=1, num_heads=2,
        max_seq_len=64, dropout=0.0))
    m.eval()
    return m


def _paged_refs(model, prompts, new):
    return [generate(model, p[None, :], max_new_tokens=n,
                     kv_cache="paged").numpy()[0]
            for p, n in zip(prompts, new)]


def _engine(gpt, **kw):
    args = dict(max_slots=2, page_size=4, max_seq_len=32,
                decode_window=4, prefill_chunk=8, q_block=2)
    args.update(kw)
    return ContinuousBatchingEngine(gpt, **args)


def _spec_engine(gpt, **kw):
    args = dict(spec_decode=True, spec_k=3)
    args.update(kw)
    return _engine(gpt, **args)


def _workload(seed=0, lens=(5, 9, 3, 12), new=(6, 4, 7, 5)):
    rng = np.random.default_rng(seed)
    return ([rng.integers(0, 96, (n,)).astype(np.int32)
             for n in lens], list(new))


# ----------------------------------------------------------------------
# proposers + acceptance rule, model-free (pure python)
# ----------------------------------------------------------------------

def test_ngram_proposer_prompt_lookup():
    p = NGramProposer(max_ngram=3, min_ngram=1)
    ids = np.array([7, 1, 2, 3, 9, 1, 2, 3], np.int32)
    # tail [1,2,3] occurred earlier at index 1 -> continuation was 9
    np.testing.assert_array_equal(p.propose(0, ids, 2), [9, 1])
    # most RECENT occurrence wins: the tail [5] after two earlier 5s
    ids = np.array([5, 1, 5, 2, 5], np.int32)
    np.testing.assert_array_equal(p.propose(0, ids, 1), [2])
    # no earlier occurrence of any suffix: no drafts
    assert p.propose(0, np.array([1, 2, 3], np.int32), 4).size == 0
    # k caps the continuation
    ids = np.array([4, 8, 8, 8, 4, 8, 8, 8, 4], np.int32)
    assert p.propose(0, ids, 3).size == 3
    assert p.propose(0, ids, 0).size == 0


def test_accept_greedy_rule():
    # m leading matches emit m drafts + the free target token
    emitted, m = accept_greedy([3, 5, 7], [3, 5, 9, 11])
    np.testing.assert_array_equal(emitted, [3, 5, 9])
    assert m == 2
    # full agreement: all K drafts + the bonus token
    emitted, m = accept_greedy([3, 5], [3, 5, 8])
    np.testing.assert_array_equal(emitted, [3, 5, 8])
    assert m == 2
    # first draft wrong: exactly the plain-decode token
    emitted, m = accept_greedy([4], [6, 2])
    np.testing.assert_array_equal(emitted, [6])
    assert m == 0
    # no drafts: a plain 1-token step
    emitted, m = accept_greedy([], [9])
    np.testing.assert_array_equal(emitted, [9])
    assert m == 0


def test_accept_sampled_rejection_rule():
    rng = np.random.default_rng(0)
    v = 8
    lg = np.zeros((3, v), np.float32)
    lg[:, 2] = 50.0          # temperature-scaled target ~ delta at 2
    emitted, m = accept_sampled([2, 2], lg, 1.0, rng)
    np.testing.assert_array_equal(emitted, [2, 2, 2])
    assert m == 2
    # a draft the target gives ~zero mass is rejected and resampled
    # from the residual (never the draft itself)
    emitted, m = accept_sampled([5], lg[:2], 1.0, rng)
    assert m == 0 and emitted.size == 1 and emitted[0] != 5


# ----------------------------------------------------------------------
# engine parity: both proposers, eos, contention
# ----------------------------------------------------------------------

def test_spec_engine_matches_generate_ngram(gpt):
    """Slot contention + mid-stream admission with the n-gram proposer:
    every output equals the sequential generate() row AND the spec-off
    engine; drafts were actually proposed and some accepted."""
    prompts, new = _workload(0)
    refs = _paged_refs(gpt, prompts, new)
    outs = {}
    for spec in (False, True):
        eng = (_spec_engine(gpt) if spec else _engine(gpt))
        rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
        done = eng.run()
        outs[spec] = [done[r].sequence for r in rids]
        if spec:
            st = eng.stats
            assert st["spec_proposed"] > 0
            assert st["spec_accepted"] > 0
            assert 0.0 < st["spec_accept_rate"] <= 1.0
            assert st["pages_in_use"] == 0
    for got_on, got_off, ref in zip(outs[True], outs[False], refs):
        np.testing.assert_array_equal(got_on, ref)
        np.testing.assert_array_equal(got_off, ref)


def test_spec_engine_matches_generate_draft_model(gpt, draft_gpt):
    """The draft-model proposer: a real small LM drafting against its
    own paged pool — outputs bitwise, and the draft pool's free list
    is whole after the drain (page discipline shared with the
    engine)."""
    prompts, new = _workload(3, lens=(5, 9, 3), new=(6, 4, 7))
    refs = _paged_refs(gpt, prompts, new)
    prop = DraftModelProposer(draft_gpt)
    eng = _spec_engine(gpt, spec_proposer=prop)
    assert prop.total_pages == 1 + eng.max_slots * eng.np_per_seq
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    assert eng.stats["spec_proposed"] > 0
    # every request released its draft pages through _release_slot
    assert prop.pages_free == prop.total_pages - 1
    assert not prop._seqs


def test_spec_engine_eos_early_retire(gpt):
    """eos inside an ACCEPTED draft run stops the stream exactly where
    plain decode stops it (host replay of the stop rule mid-accept)."""
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 96, (5,)).astype(np.int32)
    full = generate(gpt, prompt[None, :], max_new_tokens=8).numpy()[0]
    eos = int(full[prompt.size + 1])
    ref = generate(gpt, prompt[None, :], max_new_tokens=8,
                   eos_token_id=eos).numpy()[0]
    eng = _spec_engine(gpt)
    rid = eng.add_request(prompt, 8, eos_token_id=eos)
    done = eng.run()
    got = done[rid].sequence
    assert done[rid].finish_reason == "stop"
    assert got[-1] == eos and got.size < prompt.size + 8
    np.testing.assert_array_equal(got, ref[:got.size])
    assert eng.stats["pages_in_use"] == 0


# ----------------------------------------------------------------------
# composition: prefix cache, kv_quant, preemption
# ----------------------------------------------------------------------

def test_spec_engine_prefix_cache_compose(gpt):
    """Shared-prefix traffic with spec on: published pages hold only
    ACCEPTED tokens (rejected drafts are rolled back positionally), so
    later admissions hit the cache and stay bitwise; pool conservation
    holds throughout."""
    rng = np.random.default_rng(29)
    shared = rng.integers(0, 96, (12,)).astype(np.int32)
    tails = [rng.integers(0, 96, (n,)).astype(np.int32)
             for n in (3, 2, 5, 1)]
    prompts = [np.concatenate([shared, t]) for t in tails]
    new = [6, 5, 4, 6]
    refs = _paged_refs(gpt, prompts, new)
    eng = _spec_engine(gpt)           # prefix cache defaults ON
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    st = eng.stats
    assert st["cache_hits"] >= 2
    assert st["prefill_tokens_computed"] < st["prefill_tokens_requested"]
    assert st["spec_accepted"] > 0    # speculation ran alongside
    eng._cache.check()                # PDT-E019 conservation audit
    assert (st["pages_in_use"] + st["pages_free"]
            + st["cached_pages"]) == eng.total_pages - 1
    assert st["pages_in_use"] == 0


def test_spec_engine_kv_quant_token_identical(gpt):
    """int8 KV + speculation: quantized writes for accepted positions
    are byte-identical to the non-speculative quant path, so the spec
    quant engine's streams equal the plain quant engine's exactly."""
    prompts, new = _workload(3, lens=(5, 9, 3), new=(6, 4, 7))
    outs = {}
    for spec in (False, True):
        eng = (_spec_engine(gpt, kv_quant=True) if spec
               else _engine(gpt, kv_quant=True))
        rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
        done = eng.run()
        outs[spec] = [done[r].sequence for r in rids]
        assert eng.stats["kv_quant"] is True
    for a, b in zip(outs[True], outs[False]):
        np.testing.assert_array_equal(a, b)


def test_spec_engine_forced_preemption_bitwise(gpt):
    """The engine_page_pressure drill under spec_decode: the victim
    requeues, re-prefills (proposer state dropped with its pages) and
    both outputs stay bitwise."""
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(5)
    p1 = rng.integers(0, 96, (6,)).astype(np.int32)
    p2 = rng.integers(0, 96, (7,)).astype(np.int32)
    ref1, ref2 = _paged_refs(gpt, [p1, p2], [8, 8])
    faults.clear()
    try:
        eng = _spec_engine(gpt)
        r1 = eng.add_request(p1, 8)
        r2 = eng.add_request(p2, 8)
        faults.inject("engine_page_pressure", match=str(r1))
        done = eng.run()
        np.testing.assert_array_equal(done[r1].sequence, ref1)
        np.testing.assert_array_equal(done[r2].sequence, ref2)
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["pages_in_use"] == 0
    finally:
        faults.clear()


# ----------------------------------------------------------------------
# fault drills: engine_draft_nan / engine_draft_mismatch (ISSUE 9
# satellite) — victim fails coded, survivors bitwise
# ----------------------------------------------------------------------

def test_spec_engine_draft_nan_drill(gpt):
    """A NaN'd draft (engine_draft_nan poisons the victim's verify
    rows) fails EXACTLY that request with PDT-E018 while the
    co-resident request's stream is bitwise-untouched."""
    from paddle_tpu.core import errors
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(13)
    p1 = rng.integers(0, 96, (6,)).astype(np.int32)
    p2 = rng.integers(0, 96, (7,)).astype(np.int32)
    (ref2,) = _paged_refs(gpt, [p2], [8])
    faults.clear()
    try:
        eng = _spec_engine(gpt)
        r1 = eng.add_request(p1, 8)
        r2 = eng.add_request(p2, 8)
        # the site arms ONLY on verify dispatches (never r1's prefill
        # chunks); at=2 poisons the SECOND verify, so the prefill
        # token and the first verify's tokens survive the failure
        faults.inject("engine_draft_nan", match=str(r1), at=2)
        done = eng.run()
        assert done[r1].finish_reason == "failed"
        assert isinstance(done[r1].error, errors.NonFiniteLogitsError)
        assert done[r1].error.error_code == "PDT-E018"
        assert 0 < done[r1].tokens.size < 8
        assert done[r2].finish_reason == "length"
        np.testing.assert_array_equal(done[r2].sequence, ref2)
        assert eng.stats["failed"] == 1
        assert eng.stats["pages_in_use"] == 0
        # at=1 fires on the FIRST verify — the site never arms on
        # prefill chunks, so the prefill-completion token always
        # survives and the failed verify's tokens are all discarded
        faults.clear()
        eng = _spec_engine(gpt)
        r1 = eng.add_request(p1, 8)
        faults.inject("engine_draft_nan", match=str(r1), at=1)
        done = eng.run()
        assert done[r1].finish_reason == "failed"
        assert done[r1].tokens.size == 1
    finally:
        faults.clear()


def test_spec_engine_draft_mismatch_drill(gpt):
    """engine_draft_mismatch corrupts every proposal: verify rejects
    all drafts (0-accept steps), outputs stay BITWISE — the acceptance
    rule is correct for arbitrary garbage drafts."""
    from paddle_tpu.resilience import faults

    prompts, new = _workload(0)
    refs = _paged_refs(gpt, prompts, new)
    faults.clear()
    try:
        eng = _spec_engine(gpt)
        rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
        faults.inject("engine_draft_mismatch", times=0)  # every step
        done = eng.run()
        for rid, ref in zip(rids, refs):
            np.testing.assert_array_equal(done[rid].sequence, ref)
        st = eng.stats
        assert st["spec_proposed"] > 0
        assert st["spec_accepted"] == 0       # forced 0-accept steps
        assert st["spec_accept_rate"] == 0.0
    finally:
        faults.clear()


# ----------------------------------------------------------------------
# sampling mode, stats contract, observability, bench smoke
# ----------------------------------------------------------------------

def test_spec_rejection_sampling_deterministic(gpt):
    """spec_temperature > 0 with rejection sampling: runs clean,
    respects stop lengths, and is deterministic under spec_seed (the
    host RNG is the only entropy source)."""
    prompts, new = _workload(3, lens=(5, 9, 3), new=(6, 4, 7))
    outs = []
    for _ in range(2):
        eng = _spec_engine(gpt, spec_temperature=0.8,
                           spec_rejection_sampling=True, spec_seed=7)
        rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
        done = eng.run()
        for rid, p, n in zip(rids, prompts, new):
            assert done[rid].finish_reason == "length"
            assert done[rid].tokens.size == n
        outs.append([done[r].sequence for r in rids])
    for a, b in zip(*outs):
        np.testing.assert_array_equal(a, b)


def test_spec_stats_appended_backward_compat(gpt):
    """The spec counters APPEND to stats: every pre-existing key keeps
    its exact position (the PR5-PR8 contract), the three new keys come
    last, and spec_accept_rate is the only non-int besides kv_quant."""
    _OLD_KEYS = [
        "admitted", "retired", "steps", "mixed_steps",
        "decode_dispatches", "tokens_generated", "pages_allocated",
        "peak_pages_in_use", "preemptions", "timeouts", "cancelled",
        "failed", "rejected", "retries", "cache_hits",
        "cache_hit_tokens", "prefill_tokens_requested",
        "prefill_tokens_computed", "cached_pages", "evictions",
        "pages_in_use", "pages_free", "queue_depth", "kv_quant",
        "kv_page_bytes", "kv_bytes_in_use",
    ]
    eng = _engine(gpt)
    st = eng.stats
    assert list(st) == _OLD_KEYS + ["spec_proposed", "spec_accepted",
                                    "spec_accept_rate"]
    assert st["spec_proposed"] == 0 and st["spec_accepted"] == 0
    assert st["spec_accept_rate"] == 0.0
    assert isinstance(st["spec_proposed"], int)
    assert isinstance(st["spec_accept_rate"], float)


def test_spec_timelines_and_metrics(gpt):
    """verify_window events feed the accepted-tokens-per-step
    histogram: count == verify slot-steps, mean >= 1 (every verify
    emits at least the free target token), and the registry carries
    the spec counters."""
    prompts, new = _workload(0)
    eng = _spec_engine(gpt)
    for p, n in zip(prompts, new):
        eng.add_request(p, n)
    eng.run()
    snap = eng.metrics()["serving"]
    h = snap["spec_accepted_per_step"]
    assert h["count"] > 0
    assert h["sum"] == eng.stats["tokens_generated"] - sum(
        1 for _ in prompts)     # prefill emits 1 token/request outside
    assert h["sum"] / h["count"] >= 1.0
    assert snap["spec_proposed"] == eng.stats["spec_proposed"]
    assert snap["spec_accepted"] == eng.stats["spec_accepted"]


def test_serving_bench_speculative_accounting(gpt):
    """CPU tiny-model smoke for the serving_bench ``speculative`` row:
    outputs_equal must hold, accepted tokens/step must clear 1.5 on
    the repetitive-text workload, zero pages leak."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "benchmarks", "serving_bench.py")
    spec = importlib.util.spec_from_file_location(
        "serving_bench_spec_smoke", path)
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    row = sb._measure_speculative(
        gpt.cfg, gpt, slots=2, max_seq_len=64, prompt_len=16,
        motif_len=4, new_tokens=24, n_requests=4, spec_k=4,
        page_size=4, decode_window=4, prefill_chunk=8, q_block=2,
        warm=False)
    assert row["outputs_equal"] is True
    assert row["accepted_tokens_per_step"] > 1.5
    assert row["spec_accept_rate"] > 0.5
    assert row["pages_leaked"] == 0
    assert row["spec_proposed"] >= row["spec_accepted"] > 0

"""fft / signal / distribution / vision-functional coverage (reference
test patterns: ``test/legacy_test/test_fft.py``, ``test_stft_op.py``,
``test/distribution/test_distribution_*.py``, ``test_grid_sampler_op.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

R = np.random.default_rng(11)


# --- fft -------------------------------------------------------------------

def test_fft_roundtrip_and_numpy_parity():
    x = R.normal(size=(4, 16)).astype("float32")
    X = paddle.fft.fft(paddle.to_tensor(x.astype("complex64")))
    np.testing.assert_allclose(np.asarray(X._read()), np.fft.fft(x),
                               atol=1e-4)
    back = paddle.fft.ifft(X)
    np.testing.assert_allclose(np.asarray(back._read()).real, x, atol=1e-5)

    for norm in ("backward", "ortho", "forward"):
        Xr = paddle.fft.rfft(paddle.to_tensor(x), norm=norm)
        np.testing.assert_allclose(np.asarray(Xr._read()),
                                   np.fft.rfft(x, norm=norm), atol=1e-4)
        rec = paddle.fft.irfft(Xr, n=16, norm=norm)
        np.testing.assert_allclose(np.asarray(rec._read()), x, atol=1e-5)


def test_fft2_fftn_shift():
    x = R.normal(size=(3, 8, 8)).astype("float32")
    np.testing.assert_allclose(
        np.asarray(paddle.fft.fft2(
            paddle.to_tensor(x.astype("complex64")))._read()),
        np.fft.fft2(x), atol=1e-3)
    np.testing.assert_allclose(
        np.asarray(paddle.fft.rfftn(paddle.to_tensor(x))._read()),
        np.fft.rfftn(x), atol=1e-3)
    s = paddle.fft.fftshift(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(s._read()), np.fft.fftshift(x),
                               atol=0)
    f = paddle.fft.fftfreq(8, d=0.5)
    np.testing.assert_allclose(np.asarray(f._read()),
                               np.fft.fftfreq(8, d=0.5), atol=1e-7)


def test_fft_grad_flows():
    x = paddle.to_tensor(R.normal(size=(8,)).astype("float32"))
    x.stop_gradient = False
    y = paddle.fft.rfft(x)
    from paddle_tpu import ops
    loss = ops.sum(ops.as_real(y) ** 2)
    loss.backward()
    assert x.grad is not None
    # Parseval: d/dx sum|X|^2 = 2*N*x for rfft needs care; just check finite
    assert np.isfinite(np.asarray(x.grad._read())).all()


def test_stft_istft_roundtrip():
    x = R.normal(size=(2, 256)).astype("float32")
    window = np.hanning(64).astype("float32")
    spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=64, hop_length=16,
                              window=paddle.to_tensor(window))
    assert tuple(spec.shape) == (2, 33, 256 // 16 + 1)
    rec = paddle.signal.istft(spec, n_fft=64, hop_length=16,
                              window=paddle.to_tensor(window), length=256)
    # COLA reconstruction: interior matches closely
    np.testing.assert_allclose(np.asarray(rec._read())[:, 32:-32],
                               x[:, 32:-32], atol=1e-4)


# --- distributions ---------------------------------------------------------

def test_normal_distribution():
    import scipy.stats as st
    d = paddle.distribution.Normal(1.0, 2.0)
    v = np.array([0.5, 1.0, 3.0], "float32")
    np.testing.assert_allclose(
        np.asarray(d.log_prob(paddle.to_tensor(v))._read()),
        st.norm.logpdf(v, 1.0, 2.0), atol=1e-5)
    np.testing.assert_allclose(float(d.entropy()._read()),
                               st.norm.entropy(1.0, 2.0), atol=1e-5)
    paddle.seed(0)
    s = d.sample([20000])
    assert abs(float(np.asarray(s._read()).mean()) - 1.0) < 0.06


def test_more_distribution_logprobs():
    import scipy.stats as st
    cases = [
        (paddle.distribution.Uniform(0.0, 2.0), np.array([0.5, 1.5], "f4"),
         st.uniform.logpdf([0.5, 1.5], 0, 2)),
        (paddle.distribution.Bernoulli(0.3), np.array([0.0, 1.0], "f4"),
         st.bernoulli.logpmf([0, 1], 0.3)),
        (paddle.distribution.Beta(2.0, 3.0), np.array([0.2, 0.7], "f4"),
         st.beta.logpdf([0.2, 0.7], 2, 3)),
        (paddle.distribution.Gamma(2.0, 3.0), np.array([0.5, 1.0], "f4"),
         st.gamma.logpdf([0.5, 1.0], 2, scale=1 / 3)),
        (paddle.distribution.Exponential(1.5), np.array([0.5, 2.0], "f4"),
         st.expon.logpdf([0.5, 2.0], scale=1 / 1.5)),
        (paddle.distribution.Laplace(0.0, 1.5), np.array([-1.0, 2.0], "f4"),
         st.laplace.logpdf([-1.0, 2.0], 0, 1.5)),
        (paddle.distribution.LogNormal(0.2, 0.8), np.array([0.5, 2.0], "f4"),
         st.lognorm.logpdf([0.5, 2.0], 0.8, scale=np.exp(0.2))),
        (paddle.distribution.Gumbel(0.5, 2.0), np.array([0.0, 3.0], "f4"),
         st.gumbel_r.logpdf([0.0, 3.0], 0.5, 2.0)),
        (paddle.distribution.Cauchy(0.0, 1.0), np.array([0.5, -2.0], "f4"),
         st.cauchy.logpdf([0.5, -2.0])),
        (paddle.distribution.Poisson(3.0), np.array([2.0, 5.0], "f4"),
         st.poisson.logpmf([2, 5], 3.0)),
        (paddle.distribution.Geometric(0.4), np.array([0.0, 3.0], "f4"),
         st.geom.logpmf([1, 4], 0.4)),  # scipy geom counts trials
    ]
    for d, v, want in cases:
        got = np.asarray(d.log_prob(paddle.to_tensor(v))._read())
        np.testing.assert_allclose(got, want, atol=1e-4,
                                   err_msg=type(d).__name__)


def test_categorical_and_multinomial():
    logits = np.log(np.array([0.2, 0.3, 0.5], "float32"))
    c = paddle.distribution.Categorical(logits)
    lp = np.asarray(c.log_prob(paddle.to_tensor(
        np.array([0, 2], "int64")))._read())
    np.testing.assert_allclose(lp, np.log([0.2, 0.5]), atol=1e-5)
    np.testing.assert_allclose(
        float(c.entropy()._read()),
        -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5)),
        atol=1e-5)
    m = paddle.distribution.Multinomial(10, np.array([0.2, 0.8], "f4"))
    paddle.seed(1)
    s = np.asarray(m.sample([500])._read())
    assert s.shape == (500, 2) and (s.sum(-1) == 10).all()
    assert abs(s[:, 1].mean() - 8.0) < 0.3


def test_kl_divergence():
    import scipy.stats as st
    p = paddle.distribution.Normal(0.0, 1.0)
    q = paddle.distribution.Normal(1.0, 2.0)
    got = float(paddle.distribution.kl_divergence(p, q)._read())
    # closed form
    want = np.log(2.0) + (1 + 1.0) / (2 * 4.0) - 0.5
    np.testing.assert_allclose(got, want, atol=1e-6)
    b1 = paddle.distribution.Beta(2.0, 3.0)
    b2 = paddle.distribution.Beta(4.0, 1.5)
    kl = float(paddle.distribution.kl_divergence(b1, b2)._read())
    # monte-carlo cross-check
    paddle.seed(0)
    xs = np.asarray(b1.sample([100000])._read()).clip(1e-5, 1 - 1e-5)
    mc = (st.beta.logpdf(xs, 2, 3) - st.beta.logpdf(xs, 4, 1.5)).mean()
    assert abs(kl - mc) < 0.02
    with pytest.raises(NotImplementedError):
        paddle.distribution.kl_divergence(p, b1)


# --- vision functionals ----------------------------------------------------

def test_grid_sample_identity_and_torch_parity():
    import torch
    x = R.normal(size=(2, 3, 5, 7)).astype("float32")
    # identity grid reproduces the input
    theta = np.tile(np.array([[1, 0, 0], [0, 1, 0]], "float32"), (2, 1, 1))
    grid = F.affine_grid(paddle.to_tensor(theta), [2, 3, 5, 7],
                         align_corners=True)
    out = F.grid_sample(paddle.to_tensor(x), grid, align_corners=True)
    np.testing.assert_allclose(np.asarray(out._read()), x, atol=1e-5)

    # random grid vs torch
    grid_np = R.uniform(-1.2, 1.2, (2, 4, 6, 2)).astype("float32")
    for mode in ("bilinear", "nearest"):
        for pad in ("zeros", "border", "reflection"):
            ours = F.grid_sample(paddle.to_tensor(x),
                                 paddle.to_tensor(grid_np), mode=mode,
                                 padding_mode=pad, align_corners=True)
            ref = torch.nn.functional.grid_sample(
                torch.tensor(x), torch.tensor(grid_np), mode=mode,
                padding_mode="reflection" if pad == "reflection" else pad,
                align_corners=True)
            np.testing.assert_allclose(np.asarray(ours._read()),
                                       ref.numpy(), atol=1e-4,
                                       err_msg=f"{mode}/{pad}")


def test_fold_inverts_unfold():
    x = R.normal(size=(2, 3, 8, 8)).astype("float32")
    cols = F.unfold(paddle.to_tensor(x), kernel_sizes=2, strides=2)
    back = F.fold(cols, output_sizes=8, kernel_sizes=2, strides=2)
    np.testing.assert_allclose(np.asarray(back._read()), x, atol=1e-5)


def test_channel_shuffle_and_sequence_mask():
    x = np.arange(2 * 4 * 2 * 2, dtype="float32").reshape(2, 4, 2, 2)
    out = F.channel_shuffle(paddle.to_tensor(x), groups=2)
    import torch
    ref = torch.nn.functional.channel_shuffle(torch.tensor(x), 2).numpy()
    np.testing.assert_allclose(np.asarray(out._read()), ref, atol=0)

    m = F.sequence_mask(paddle.to_tensor(np.array([1, 3], "int64")),
                        maxlen=4)
    np.testing.assert_allclose(np.asarray(m._read()),
                               [[1, 0, 0, 0], [1, 1, 1, 0]])


def test_misc_losses_and_logit():
    import torch
    import torch.nn.functional as TF
    x = R.normal(size=(4, 5)).astype("float32")
    y = np.sign(R.normal(size=(4, 5))).astype("float32")
    got = float(F.soft_margin_loss(paddle.to_tensor(x),
                                   paddle.to_tensor(y))._read())
    want = TF.soft_margin_loss(torch.tensor(x), torch.tensor(y)).item()
    np.testing.assert_allclose(got, want, rtol=1e-5)

    lbl = (R.uniform(size=(4, 5)) > 0.5).astype("float32")
    got = float(F.multi_label_soft_margin_loss(
        paddle.to_tensor(x), paddle.to_tensor(lbl))._read())
    want = TF.multilabel_soft_margin_loss(torch.tensor(x),
                                          torch.tensor(lbl)).item()
    np.testing.assert_allclose(got, want, rtol=1e-5)

    p = R.uniform(0.05, 0.95, (6,)).astype("float32")
    np.testing.assert_allclose(
        np.asarray(F.logit(paddle.to_tensor(p))._read()),
        np.log(p / (1 - p)), atol=1e-5)

    var = R.uniform(0.5, 2.0, (4, 5)).astype("float32")
    got = float(F.gaussian_nll_loss(paddle.to_tensor(x),
                                    paddle.to_tensor(lbl),
                                    paddle.to_tensor(var))._read())
    want = TF.gaussian_nll_loss(torch.tensor(x), torch.tensor(lbl),
                                torch.tensor(var)).item()
    np.testing.assert_allclose(got, want, rtol=1e-4)

    got = float(F.poisson_nll_loss(paddle.to_tensor(x),
                                   paddle.to_tensor(lbl))._read())
    want = TF.poisson_nll_loss(torch.tensor(x), torch.tensor(lbl)).item()
    np.testing.assert_allclose(got, want, rtol=1e-5)

    d = float(F.pairwise_distance(paddle.to_tensor(x),
                                  paddle.to_tensor(lbl))._read().sum())
    want = TF.pairwise_distance(torch.tensor(x),
                                torch.tensor(lbl)).sum().item()
    np.testing.assert_allclose(d, want, rtol=1e-4)

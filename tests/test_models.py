"""GPT flagship model + recompute + driver hooks.

Mirrors the reference test pattern of training-parity checks
(test/dygraph_to_static model tests; recompute tests in
test/collective/fleet/test_dygraph_recompute*.py — loss/grad parity with
and without recompute)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, shard_gpt


def _cfg(**kw):
    d = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
             max_seq_len=16, dropout=0.0)
    d.update(kw)
    return GPTConfig(**d)


def _batch(cfg, b=2, s=8, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32)
    lab = rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32)
    return pt.to_tensor(ids), pt.to_tensor(lab)


def test_gpt_forward_shapes():
    pt.seed(0)
    cfg = _cfg()
    m = GPTForCausalLM(cfg)
    m.eval()
    ids, _ = _batch(cfg)
    logits = m(ids)
    assert logits.shape == [2, 8, cfg.vocab_size]


def test_gpt_trains_jit():
    pt.seed(0)
    cfg = _cfg()
    m = GPTForCausalLM(cfg)
    m.train()
    opt = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())

    @pt.jit.to_static(full_graph=True)
    def step(ids, labels):
        loss = m(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    ids, lab = _batch(cfg)
    losses = [float(step(ids, lab)) for _ in range(12)]
    assert losses[-1] < losses[0] - 0.5


def test_recompute_grad_parity():
    """Same loss and same grads with recompute on/off (the reference's
    test_dygraph_recompute check)."""

    def run(recompute):
        pt.seed(7)
        cfg = _cfg(recompute=recompute)
        m = GPTForCausalLM(cfg)
        m.train()
        ids, lab = _batch(cfg, seed=3)
        loss = m(ids, lab)
        loss.backward()
        grads = {n: p.grad.numpy() for n, p in m.named_parameters()
                 if p.grad is not None}
        return float(loss), grads

    l0, g0 = run(False)
    l1, g1 = run(True)
    assert abs(l0 - l1) < 1e-5
    assert g0.keys() == g1.keys() and len(g0) > 0
    for k in g0:
        np.testing.assert_allclose(g0[k], g1[k], rtol=1e-4, atol=1e-5)


def test_recompute_policy_grad_parity():
    """Every remat policy (incl. dots_and_kernels_saveable, which keeps
    Pallas flash-attention outputs as residuals) produces the same loss
    and grads — policies trade memory for recompute work, never math."""

    def run(policy):
        pt.seed(7)
        cfg = _cfg(recompute=True, recompute_policy=policy)
        m = GPTForCausalLM(cfg)
        m.train()
        ids, lab = _batch(cfg, seed=3)
        loss = m(ids, lab)
        loss.backward()
        grads = {n: p.grad.numpy() for n, p in m.named_parameters()
                 if p.grad is not None}
        return float(loss), grads

    ref_l, ref_g = run("full")
    for policy in ("dots_saveable", "dots_and_kernels_saveable"):
        l, g = run(policy)
        assert abs(l - ref_l) < 1e-5, policy
        assert g.keys() == ref_g.keys()
        for k in g:
            np.testing.assert_allclose(g[k], ref_g[k], rtol=1e-4,
                                       atol=1e-5, err_msg=policy)


def test_recompute_under_jit():
    pt.seed(0)
    cfg = _cfg(recompute=True)
    m = GPTForCausalLM(cfg)
    m.train()
    opt = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())

    @pt.jit.to_static(full_graph=True)
    def step(ids, labels):
        loss = m(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    ids, lab = _batch(cfg)
    losses = [float(step(ids, lab)) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_recompute_sequential():
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet.recompute import recompute_sequential
    pt.seed(0)
    seq = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8))
    x = pt.to_tensor(np.random.default_rng(0).standard_normal(
        (4, 8)).astype(np.float32))
    x.stop_gradient = False
    y = recompute_sequential({"segments": 2}, list(seq), x)
    ref = seq(x)
    np.testing.assert_allclose(y.numpy(), ref.numpy(), rtol=1e-5, atol=1e-6)
    y.backward(pt.to_tensor(np.ones((4, 8), np.float32)))
    assert x.grad is not None


def test_shard_gpt_multichip_dryrun():
    """The driver's dryrun_multichip contract, exercised in CI."""
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_entry_compiles():
    import sys

    import jax
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (2, 32, 256)

"""Fleet hybrid-parallel tests: TP layers parity vs plain layers, sharding
(ZeRO) stages, fleet facade (reference pattern
test/collective/fleet/hybrid_parallel_mp_model.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


@pytest.fixture(scope="module", autouse=True)
def _env():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)


def test_topology():
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == 4
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.nranks == 8
    topo = hcg.topology()
    assert topo.world_size() == 8
    assert topo.get_dim("model") == 2
    comm_list = topo.get_comm_list("model")
    assert len(comm_list) == 4 and all(len(g) == 2 for g in comm_list)


def test_column_row_parallel_linear_parity():
    paddle.seed(21)
    col = fleet.ColumnParallelLinear(8, 16, gather_output=False)
    row = fleet.RowParallelLinear(16, 4, input_is_parallel=True)
    paddle.seed(21)
    fc1 = paddle.nn.Linear(8, 16)
    fc2 = paddle.nn.Linear(16, 4)

    np.testing.assert_allclose(col.weight.numpy(), fc1.weight.numpy(),
                               rtol=1e-6)

    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    y_tp = row(col(x))
    y_ref = fc2(fc1(x))
    np.testing.assert_allclose(y_tp.numpy(), y_ref.numpy(), rtol=1e-4,
                               atol=1e-5)

    # weights actually sharded over mp (2-way on the right dims)
    w = col.weight._read()
    assert {s.data.shape for s in w.addressable_shards} == {(8, 8)}
    w = row.weight._read()
    assert {s.data.shape for s in w.addressable_shards} == {(8, 4)}


def test_tp_backward_parity():
    paddle.seed(33)
    col = fleet.ColumnParallelLinear(8, 16, gather_output=False)
    row = fleet.RowParallelLinear(16, 4, input_is_parallel=True)
    paddle.seed(33)
    fc1 = paddle.nn.Linear(8, 16)
    fc2 = paddle.nn.Linear(16, 4)

    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    loss_tp = (row(col(x)) ** 2).mean()
    loss_tp.backward()
    loss_ref = (fc2(fc1(x)) ** 2).mean()
    loss_ref.backward()
    np.testing.assert_allclose(col.weight.grad.numpy(),
                               fc1.weight.grad.numpy(), rtol=1e-4,
                               atol=1e-6)
    np.testing.assert_allclose(row.weight.grad.numpy(),
                               fc2.weight.grad.numpy(), rtol=1e-4,
                               atol=1e-6)


def test_vocab_parallel_embedding_parity():
    paddle.seed(5)
    vp = fleet.VocabParallelEmbedding(16, 8)
    paddle.seed(5)
    emb = paddle.nn.Embedding(16, 8)
    np.testing.assert_allclose(vp.weight.numpy(), emb.weight.numpy(),
                               rtol=1e-6)
    ids = paddle.to_tensor(np.array([[0, 3, 15], [7, 8, 2]], dtype=np.int32))
    np.testing.assert_allclose(vp(ids).numpy(), emb(ids).numpy(), rtol=1e-6)
    w = vp.weight._read()
    assert {s.data.shape for s in w.addressable_shards} == {(8, 8)}


def test_parallel_cross_entropy():
    logits = paddle.to_tensor(
        np.random.randn(4, 16).astype(np.float32), stop_gradient=False)
    labels = paddle.to_tensor(np.array([1, 5, 10, 15], dtype=np.int64))
    pce = fleet.ParallelCrossEntropy()
    loss = pce(logits, labels)
    ref = paddle.nn.functional.cross_entropy(logits, labels,
                                             reduction="none")
    np.testing.assert_allclose(loss.numpy().ravel(), ref.numpy().ravel(),
                               rtol=1e-5)


class _TPMLP(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.embed = fleet.VocabParallelEmbedding(32, 16)
        self.fc1 = fleet.ColumnParallelLinear(16, 32, gather_output=False)
        self.fc2 = fleet.RowParallelLinear(32, 16, input_is_parallel=True)
        self.head = paddle.nn.Linear(16, 32)

    def forward(self, ids):
        h = self.embed(ids)
        h = paddle.nn.functional.relu(self.fc1(h))
        h = self.fc2(h)
        return self.head(h)


def test_fleet_distributed_model_trains():
    paddle.seed(9)
    model = fleet.distributed_model(_TPMLP())
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(
        learning_rate=0.01, parameters=model.parameters()))
    rng = np.random.RandomState(2)
    ids = paddle.to_tensor(rng.randint(0, 32, (8, 6)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, 32, (8, 6)).astype(np.int64))
    losses = []
    for _ in range(5):
        logits = model(ids)
        loss = paddle.nn.functional.cross_entropy(
            logits.reshape([-1, 32]), labels.reshape([-1]))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_sharding_stage2():
    """DygraphShardingOptimizer shards moments + grads over sharding axis."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 8,
                               "sep_degree": 1}
    hcg_prev = fleet.get_hybrid_communicate_group()
    fleet.init(is_collective=True, strategy=strategy)
    try:
        paddle.seed(3)
        net = paddle.nn.Linear(16, 16)
        inner = paddle.optimizer.Adam(learning_rate=0.01,
                                      parameters=net.parameters())
        opt = fleet.DygraphShardingOptimizer(
            inner, fleet.get_hybrid_communicate_group(), stage=2)
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        m = inner._accumulators["moment1"][id(net.weight)]
        assert {s.data.shape for s in m._read().addressable_shards} \
            == {(2, 16)}
    finally:
        fleet.set_hybrid_communicate_group(hcg_prev)


def test_group_sharded_parallel_stage3():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 8,
                               "sep_degree": 1}
    hcg_prev = fleet.get_hybrid_communicate_group()
    fleet.init(is_collective=True, strategy=strategy)
    try:
        from paddle_tpu.distributed.fleet.sharding_optimizer import \
            group_sharded_parallel
        paddle.seed(3)
        net = paddle.nn.Linear(16, 16)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        net, opt, _ = group_sharded_parallel(net, opt, level="p_g_os")
        # params now sharded (FSDP layout)
        w = net.weight._read()
        assert {s.data.shape for s in w.addressable_shards} == {(2, 16)}
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
    finally:
        fleet.set_hybrid_communicate_group(hcg_prev)

"""Decode megakernel acceptance suite (ISSUE 18).

Correctness model: the fused decode path (``ops/pallas/``
``fused_decode_qkv`` = pre-norm + QKV + RoPE + paged-KV append,
``fused_decode_mlp`` = out-proj + residual + MLP + residual, and the
``fused_decode_epilogue`` = final norm + LM head + guarded argmax) is
gated two ways:

* KERNEL level — every kernel runs under ``interpret=True`` and must be
  BITWISE-identical to its jnp twin across geometries: padded row
  tails, GQA, rotary embeddings, bf16 KV pages, int8-quantized pages.
* ENGINE level — ``megakernel=True`` must produce BITWISE-identical
  token streams to ``megakernel=False`` (and to
  ``generate(kv_cache='paged')``) over the serving workloads that
  stress the scheduler: slot contention, shared-prefix + copy-on-write
  admission, int8 KV quant, speculative decoding, and TP=2.  An
  off-spelling must restore today's compiled decode programs exactly
  (same ``_geometry()`` cache key), and a typo'd spelling raises
  instead of silently picking a path.

The engine tests reuse the session ``serving_gpt`` fixture and the
serving-suite geometry so they ride the already-compiled programs
(tier-1 budget, not semantics).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.core import state as _state
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models import generate
from paddle_tpu.ops.pallas import fused_decode_mlp as FM
from paddle_tpu.ops.pallas import fused_decode_qkv as FQ

# serving-suite geometry (test_serving_engine.py): same compiled
# programs as the rest of the pinned acceptance block
KW = dict(max_slots=2, page_size=8, max_seq_len=32, decode_window=4,
          prefill_chunk=8, q_block=2)


def _workload(seed=0, lens=(5, 9, 3, 12), new=(6, 4, 7, 5)):
    rng = np.random.default_rng(seed)
    return ([rng.integers(0, 96, (n,)).astype(np.int32)
             for n in lens], list(new))


def _run(model, prompts, new, mk, **kw):
    eng = ContinuousBatchingEngine(model, megakernel=mk, **{**KW, **kw})
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    return [done[r].sequence for r in rids], eng


def _paged_refs(model, prompts, new):
    return [generate(model, p[None, :], max_new_tokens=n,
                     kv_cache="paged").numpy()[0]
            for p, n in zip(prompts, new)]


# ----------------------------------------------------------------------
# kernel vs jnp twin, bitwise (model-free)
# ----------------------------------------------------------------------

def _t(rng, *s):
    return jnp.asarray(rng.normal(size=s), jnp.float32)


def _qkv_case():
    """B=5 deliberately leaves a padded row tail at rows=2 (ceil 5/2=3
    blocks, last half-empty); NP=3 pages x ps=4 slots spans page
    boundaries at every test position."""
    rng = np.random.default_rng(0)
    B, H, nh, hd, NP, ps, P = 5, 32, 4, 8, 3, 4, 12
    pos = jnp.asarray([0, 3, 7, 11, 2], jnp.int32)
    bt = jnp.asarray(rng.integers(0, P, size=(B, NP)), jnp.int32)
    return rng, B, H, nh, hd, NP, ps, P, pos, bt


@pytest.mark.parametrize("rows", [None, 2])
def test_fused_qkv_matches_twin_gpt(rows):
    """Fused QKV (layernorm + packed QKV + paged append) vs its jnp
    twin: bitwise, including the rows=2 padded-tail grid."""
    rng, B, H, nh, hd, NP, ps, P, pos, bt = _qkv_case()
    x, nw, nb = _t(rng, B, H), _t(rng, H), _t(rng, H)
    w = _t(rng, H, 3 * nh * hd) * 0.05
    b = _t(rng, 3 * nh * hd) * 0.1
    kp, vp = _t(rng, nh, P, ps, hd), _t(rng, nh, P, ps, hd)
    kw = dict(norm="layer", eps=1e-5, n_heads=nh, n_kv_heads=nh,
              head_dim=hd, rope_theta=None, rows=rows)
    got = FQ.fused_decode_qkv(x, nw, nb, [w], [b], pos, bt, kp, vp,
                              interpret=True, **kw)
    ref = FQ.fused_decode_qkv_twin(x, nw, nb, [w], [b], pos, bt,
                                   kp, vp, **kw)
    for a, b_ in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))


@pytest.mark.parametrize("pages", ["int8", "bf16"])
def test_fused_qkv_matches_twin_llama_gqa(pages):
    """LLaMA shape: rmsnorm, split Q/K/V, GQA (2 KV heads under 4 Q
    heads), rotary at theta=1e4 — against int8-quantized pages (scale
    pools round-trip) and bf16 pages (cast-on-append)."""
    rng, B, H, nh, hd, NP, ps, P, pos, bt = _qkv_case()
    hk = 2
    x, nw = _t(rng, B, H), _t(rng, H)
    wq = _t(rng, H, nh * hd) * 0.05
    wk = _t(rng, H, hk * hd) * 0.05
    wv = _t(rng, H, hk * hd) * 0.05
    kw = dict(norm="rms", eps=1e-6, n_heads=nh, n_kv_heads=hk,
              head_dim=hd, rope_theta=10000.0)
    if pages == "int8":
        kp = jnp.zeros((hk, P, ps, hd), jnp.int8)
        vp = jnp.zeros((hk, P, ps, hd), jnp.int8)
        scales = (jnp.ones((hk, P, ps), jnp.float32),
                  jnp.ones((hk, P, ps), jnp.float32))
    else:
        kp = jnp.zeros((hk, P, ps, hd), jnp.bfloat16)
        vp = jnp.zeros((hk, P, ps, hd), jnp.bfloat16)
        scales = (None, None)
    got = FQ.fused_decode_qkv(x, nw, None, [wq, wk, wv], [], pos, bt,
                              kp, vp, *scales, interpret=True, **kw)
    ref = FQ.fused_decode_qkv_twin(x, nw, None, [wq, wk, wv], [], pos,
                                   bt, kp, vp, *scales, **kw)
    for a, b_ in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))


def test_fused_mlp_matches_twin():
    """Fused out-proj+residual+MLP+residual vs twin: GPT (gelu,
    biases), LLaMA (swiglu, rows=2 padded tail), and the TP partial
    form (stops before the down-proj psum)."""
    rng = np.random.default_rng(1)
    B, H, nh, hd = 5, 32, 4, 8
    I = 4 * H
    x, nw, nb = _t(rng, B, H), _t(rng, H), _t(rng, H)
    att = _t(rng, B, nh * hd)
    wo, bo = _t(rng, nh * hd, H) * 0.05, _t(rng, H) * 0.1
    w1, b1 = _t(rng, H, I) * 0.05, _t(rng, I) * 0.1
    w2, b2 = _t(rng, I, H) * 0.05, _t(rng, H) * 0.1
    g = FM.fused_decode_mlp(x, att, wo, bo, nw, nb, w1, b1, w2, b2,
                            arch="gpt", norm="layer", eps=1e-5,
                            interpret=True)
    r = FM.fused_decode_mlp_twin(x, att, wo, bo, nw, nb, w1, b1, w2,
                                 b2, arch="gpt", norm="layer", eps=1e-5)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(r))
    wu = _t(rng, H, I) * 0.05
    g = FM.fused_decode_mlp(x, att, wo, None, nw, None, w1, None, w2,
                            None, w_up=wu, arch="llama", norm="rms",
                            eps=1e-6, rows=2, interpret=True)
    r = FM.fused_decode_mlp_twin(x, att, wo, None, nw, None, w1, None,
                                 w2, None, w_up=wu, arch="llama",
                                 norm="rms", eps=1e-6, rows=2)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(r))
    g = FM.fused_decode_mlp_partial(x, nw, nb, w1, b1, w2, arch="gpt",
                                    norm="layer", eps=1e-5,
                                    interpret=True)
    r = FM.fused_decode_mlp_partial_twin(x, nw, nb, w1, b1, w2,
                                         arch="gpt", norm="layer",
                                         eps=1e-5)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(r))


def test_fused_epilogue_matches_twin_and_poison_drill():
    """Sampling epilogue (final norm + LM head + guarded argmax) vs
    twin, bitwise — and the guard drill: a NaN-poisoned row must raise
    its ``bad`` flag and emit token 0 (the engine's quarantine
    sentinel), with clean rows untouched."""
    rng = np.random.default_rng(2)
    B, H, V = 5, 32, 17
    x, nw, nb = _t(rng, B, H), _t(rng, H), _t(rng, H)
    wlm = _t(rng, V, H) * 0.05
    poison = jnp.asarray([0.0, 0.0, float("nan"), 0.0, 0.0],
                         jnp.float32)
    got = FM.fused_decode_epilogue(x, nw, nb, wlm, None, poison,
                                   norm="layer", eps=1e-5,
                                   transpose_lm=True, interpret=True)
    ref = FM.fused_decode_epilogue_twin(x, nw, nb, wlm, None, poison,
                                        norm="layer", eps=1e-5,
                                        transpose_lm=True)
    for a, b_ in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    logits, nxt, bad = got
    assert bool(bad[2]) and int(nxt[2]) == 0          # poisoned row
    assert not bool(bad[0]) and not bool(bad[4])      # clean rows
    # logits are returned PRE-poison (observability keeps real values)
    assert np.isfinite(np.asarray(logits)).all()


# ----------------------------------------------------------------------
# engine: megakernel on/off bitwise over the serving workloads
# ----------------------------------------------------------------------

def test_engine_megakernel_slot_contention_bitwise(serving_gpt):
    """4 ragged requests through 2 slots with mid-stream admission:
    megakernel on == off == sequential generate(), bitwise, and the
    scheduler behaved identically both ways."""
    prompts, new = _workload()
    refs = _paged_refs(serving_gpt, prompts, new)
    off, e_off = _run(serving_gpt, prompts, new, False)
    on, e_on = _run(serving_gpt, prompts, new, True)
    for a, b, r in zip(off, on, refs):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, r)
    assert e_on.stats["mixed_steps"] >= 2             # contention happened
    assert e_on.stats["decode_dispatches"] == e_off.stats[
        "decode_dispatches"]                          # same window schedule


def test_engine_megakernel_shared_prefix_cow_bitwise(serving_gpt):
    """Shared-prefix admissions under prefix_cache: later requests map
    published pages (cache hits > 0) and the COW re-admission of an
    identical prompt recomputes one token — bitwise on/off throughout."""
    rng = np.random.default_rng(29)
    shared = rng.integers(0, 96, (16,)).astype(np.int32)  # 2 full pages
    tails = [rng.integers(0, 96, (n,)).astype(np.int32)
             for n in (3, 2, 5, 1)]
    prompts = [np.concatenate([shared, t]) for t in tails]
    new = [6, 5, 4, 6]
    outs = {}
    for mk in (False, True):
        eng = ContinuousBatchingEngine(serving_gpt, megakernel=mk,
                                       prefix_cache=True, **KW)
        rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
        done = eng.run()
        assert eng.stats["cache_hits"] >= 2           # prefix reuse ran
        # COW drill: the page-aligned shared prompt (2 full pages) is
        # fully cached by now, so its admission takes the copy-on-write
        # path — exactly ONE token recomputed for the last position
        base = eng.stats["prefill_tokens_computed"]
        r2 = eng.add_request(shared, 4)
        done2 = eng.run()
        assert eng.stats["prefill_tokens_computed"] - base == 1
        outs[mk] = ([done[r].sequence for r in rids]
                    + [done2[r2].sequence])
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b)


def test_engine_megakernel_kv_quant_bitwise(serving_gpt):
    """int8 KV quant: the fused QKV kernel quantizes-and-appends inside
    the megakernel; token streams stay bitwise vs the unfused quant
    path."""
    prompts, new = _workload(seed=3)
    off, _ = _run(serving_gpt, prompts, new, False, kv_quant=True)
    on, _ = _run(serving_gpt, prompts, new, True, kv_quant=True)
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)


def test_engine_megakernel_spec_decode_bitwise(serving_gpt):
    """Speculative decoding composes: verify segments run through the
    mixed program regardless of the flag, so megakernel on/off (and
    spec on/off) all agree bitwise."""
    prompts, new = _workload(seed=4)
    plain, _ = _run(serving_gpt, prompts, new, False)
    off, _ = _run(serving_gpt, prompts, new, False,
                  spec_decode=True, spec_k=3)
    on, _ = _run(serving_gpt, prompts, new, True,
                 spec_decode=True, spec_k=3)
    for a, b, c in zip(plain, off, on):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)


def test_engine_megakernel_tp2_bitwise(serving_gpt):
    """TP=2: the fused TP decode body keeps the unfused psum schedule
    (one per out-proj, one per MLP down), so megakernel on == off ==
    the single-device stream, bitwise — fp and kv_quant."""
    mesh2 = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
    prompts, new = _workload(seed=5)
    single, _ = _run(serving_gpt, prompts, new, False)
    tp_off, _ = _run(serving_gpt, prompts, new, False, mesh=mesh2)
    tp_on, _ = _run(serving_gpt, prompts, new, True, mesh=mesh2)
    for a, b, c in zip(single, tp_off, tp_on):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
    tq_off, _ = _run(serving_gpt, prompts, new, False, mesh=mesh2,
                     kv_quant=True)
    tq_on, _ = _run(serving_gpt, prompts, new, True, mesh=mesh2,
                    kv_quant=True)
    for a, b in zip(tq_off, tq_on):
        np.testing.assert_array_equal(a, b)


# ----------------------------------------------------------------------
# flag plumbing: spellings, restore, strictness
# ----------------------------------------------------------------------

def test_megakernel_off_spelling_restores_default_programs(serving_gpt):
    """An explicit off-spelling must be INDISTINGUISHABLE from the
    default: same parsed value and the same ``_geometry()`` program
    cache key, so no decode program recompiles when the flag is
    toggled back off."""
    base = ContinuousBatchingEngine(serving_gpt, **KW)
    for spelling in ("off", "false", "0", "no", False):
        eng = ContinuousBatchingEngine(serving_gpt,
                                       megakernel=spelling, **KW)
        assert eng.megakernel is False
        assert eng._geometry() == base._geometry()
    for spelling in ("on", "true", "1", "yes", True):
        eng = ContinuousBatchingEngine(serving_gpt,
                                       megakernel=spelling, **KW)
        assert eng.megakernel is True
        assert eng._geometry() != base._geometry()


def test_megakernel_flag_and_strict_spelling(serving_gpt):
    """The ``serving_megakernel`` flag sets the default (kwarg still
    wins), and a typo'd spelling raises instead of silently running
    the wrong decode program."""
    old = _state.get_flag("serving_megakernel")
    try:
        _state.set_flags({"serving_megakernel": True})
        assert ContinuousBatchingEngine(
            serving_gpt, **KW).megakernel is True
        assert ContinuousBatchingEngine(
            serving_gpt, megakernel="off", **KW).megakernel is False
    finally:
        _state.set_flags({"serving_megakernel": old})
    with pytest.raises(ValueError, match="megakernel"):
        ContinuousBatchingEngine(serving_gpt, megakernel="fast", **KW)

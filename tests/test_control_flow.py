"""Control flow: cond/while_loop/switch_case/case eagerly and under
to_static capture (lax.cond/switch/while inside the compiled program), plus
the jit fallback retry policy (VERDICT r2 #4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static.nn import case, cond, switch_case, while_loop


def _sf(fn):
    return fn if hasattr(fn, "_fallback_keys") else fn.__wrapped__


def _t(x, **kw):
    return paddle.to_tensor(np.asarray(x), **kw)


# ---------------------------------------------------------------- eager ----

def test_cond_eager_runs_one_branch():
    x = _t([2.0])
    out = cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)
    np.testing.assert_allclose(out.numpy(), [4.0])
    out = cond(x.sum() < 0, lambda: x * 2, lambda: x - 1)
    np.testing.assert_allclose(out.numpy(), [1.0])


def test_cond_eager_grads_through_taken_branch():
    x = _t([3.0], stop_gradient=False)
    out = cond(_t(True), lambda: (x * x).sum(), lambda: x.sum())
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_while_loop_eager_and_grads():
    x = _t([1.0], stop_gradient=False)
    i = _t(0)

    def c(i, v):
        return i < 3

    def b(i, v):
        return i + 1, v * 2

    i_out, v_out = while_loop(c, b, [i, x])
    np.testing.assert_allclose(v_out.numpy(), [8.0])
    v_out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_switch_case_eager():
    x = _t([1.0])
    fns = {1: lambda: x + 1, 3: lambda: x + 3}
    np.testing.assert_allclose(
        switch_case(_t(3), fns).numpy(), [4.0])
    # no match -> default
    np.testing.assert_allclose(
        switch_case(_t(7), fns, default=lambda: x * 10).numpy(), [10.0])
    # no match, no default -> max key
    np.testing.assert_allclose(switch_case(_t(7), fns).numpy(), [4.0])


def test_case_eager_first_true_wins():
    x = _t([1.0])
    out = case([(_t(False), lambda: x + 1), (_t(True), lambda: x + 2),
                (_t(True), lambda: x + 3)])
    np.testing.assert_allclose(out.numpy(), [3.0])
    out = case([(_t(False), lambda: x + 1)], default=lambda: x - 1)
    np.testing.assert_allclose(out.numpy(), [0.0])


# ------------------------------------------------------------ to_static ----

def test_cond_compiles_data_dependent_branch():
    """The r2 gap: data-dependent branching now stays compiled (no eager
    fallback) because cond emits lax.cond instead of bool(tracer)."""

    @paddle.jit.to_static
    def fn(x):
        return cond(x.sum() > 0, lambda: x * 2.0, lambda: x - 1.0)

    xp = _t(np.array([1.0, 2.0], np.float32))
    xn = _t(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(fn(xp).numpy(), [2.0, 4.0])
    # same signature, other branch: MUST reuse the same compiled program
    np.testing.assert_allclose(fn(xn).numpy(), [-2.0, -3.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "cond fell back to eager"
    assert len(sf._cache) == 1


def test_cond_grads_through_closure_weights_under_jit():
    w = _t(np.array([2.0], np.float32), stop_gradient=False)

    @paddle.jit.to_static
    def fn(x):
        w.clear_grad()  # grads are per-call outputs of the program
        loss = cond(x.sum() > 0,
                    lambda: (w * x).sum(),
                    lambda: (w * w * x).sum()).sum()
        loss.backward()
        return loss

    xp = _t(np.array([3.0], np.float32))
    fn(xp)
    np.testing.assert_allclose(w.grad.numpy(), [3.0])  # d(w*x)/dw = x
    xn = _t(np.array([-3.0], np.float32))
    fn(xn)
    # false branch: d(w^2 x)/dw = 2wx = -12
    np.testing.assert_allclose(w.grad.numpy(), [-12.0])
    sf = _sf(fn)
    assert not sf._fallback_keys and not sf._fallback_counts
    assert len(sf._cache) == 1


def test_switch_case_under_jit():
    @paddle.jit.to_static
    def fn(idx, x):
        return switch_case(idx, {0: lambda: x + 10.0, 2: lambda: x * 3.0},
                           default=lambda: x * 0.0)

    x = _t(np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(fn(_t(0), x).numpy(), [11.0, 12.0])
    np.testing.assert_allclose(fn(_t(2), x).numpy(), [3.0, 6.0])
    np.testing.assert_allclose(fn(_t(5), x).numpy(), [0.0, 0.0])
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_while_loop_compiles_without_grads():
    @paddle.jit.to_static
    def fn(x):
        with paddle.no_grad():
            i, y = while_loop(
                lambda i, y: i < 4,
                lambda i, y: (i + 1, y * 2.0),
                [_t(0), x])
        return y

    x = _t(np.array([1.5], np.float32))
    np.testing.assert_allclose(fn(x).numpy(), [24.0])
    np.testing.assert_allclose(fn(x).numpy(), [24.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "while_loop fell back"
    assert len(sf._cache) == 1


def test_while_loop_python_scalar_loop_var_compiles():
    """A plain `0` counter must be promoted to a Tensor carry, not crash
    the structure check during discovery."""

    @paddle.jit.to_static
    def fn(x):
        with paddle.no_grad():
            i, y = while_loop(lambda i, y: i < 3,
                              lambda i, y: (i + 1, y + 1.0), [0, x])
        return y

    x = _t(np.array([1.0], np.float32))
    np.testing.assert_allclose(fn(x).numpy(), [4.0])
    np.testing.assert_allclose(fn(x).numpy(), [4.0])
    sf = _sf(fn)
    assert not sf._fallback_keys and not sf._fallback_counts


def test_while_loop_with_grads_compiles():
    """Grad-requiring while lowers to the bounded masked lax.scan and
    STAYS COMPILED (no eager fallback), with correct gradients through
    the selected iterations."""
    w = _t(np.array([1.0], np.float32), stop_gradient=False)

    @paddle.jit.to_static
    def fn(x):
        w.clear_grad()
        i, y = while_loop(lambda i, y: i < 3,
                          lambda i, y: (i + 1, y * w),
                          [_t(0), x], max_trip_count=8)
        loss = y.sum()
        loss.backward()
        return loss

    x = _t(np.array([2.0], np.float32))
    out = fn(x)
    np.testing.assert_allclose(out.numpy(), 2.0)
    np.testing.assert_allclose(w.grad.numpy(), [6.0])  # d(w^3*2)/dw at w=1
    out = fn(x)  # replay: must hit the compiled cache, not fall back
    np.testing.assert_allclose(out.numpy(), 2.0)
    np.testing.assert_allclose(w.grad.numpy(), [6.0])
    sf = _sf(fn)
    assert not sf._fallback_keys, "while_loop with grads fell back"
    assert len(sf._cache) == 1


def test_while_loop_grad_data_dependent_trip_count():
    """The early-exit mask must zero contributions past the dynamic stop:
    two inputs with different trip counts give different grads from the
    SAME compiled program."""
    w = _t(np.array([2.0], np.float32), stop_gradient=False)

    @paddle.jit.to_static
    def fn(x, n):
        w.clear_grad()
        i, y = while_loop(lambda i, y: i < n,
                          lambda i, y: (i + 1, y * w),
                          [_t(0), x], max_trip_count=8)
        loss = y.sum()
        loss.backward()
        return loss

    x = _t(np.array([1.0], np.float32))
    out2 = fn(x, _t(2))      # y = w^2 -> dy/dw = 2w = 4
    np.testing.assert_allclose(out2.numpy(), 4.0)
    np.testing.assert_allclose(w.grad.numpy(), [4.0])
    out3 = fn(x, _t(3))      # y = w^3 -> dy/dw = 3w^2 = 12
    np.testing.assert_allclose(out3.numpy(), 8.0)
    np.testing.assert_allclose(w.grad.numpy(), [12.0])
    sf = _sf(fn)
    assert not sf._fallback_keys
    assert len(sf._cache) == 1


def test_while_loop_grads_opt_out_falls_back():
    """max_trip_count=0 opts out of the scan lowering: the Python loop
    unrolls and to_static degrades to eager, staying correct."""
    w = _t(np.array([1.0], np.float32), stop_gradient=False)

    @paddle.jit.to_static
    def fn(x):
        i, y = while_loop(lambda i, y: i < 3,
                          lambda i, y: (i + 1, y * w),
                          [_t(0), x], max_trip_count=0)
        loss = y.sum()
        loss.backward()
        return loss

    x = _t(np.array([2.0], np.float32))
    with pytest.warns(UserWarning, match="to_static"):
        out = fn(x)
    np.testing.assert_allclose(out.numpy(), 2.0)
    np.testing.assert_allclose(w.grad.numpy(), [6.0])


def test_branch_structure_mismatch_raises():
    @paddle.jit.to_static(full_graph=True)
    def fn(x):
        return cond(x.sum() > 0, lambda: (x, x), lambda: x)

    with pytest.raises(Exception, match="same structure"):
        fn(_t(np.array([1.0], np.float32)))


def test_branch_outer_write_rejected_under_jit():
    acc = _t(np.array([0.0], np.float32))

    @paddle.jit.to_static(full_graph=True)
    def fn(x):
        def t():
            acc[0] = x[0]  # in-place write to outer state
            return x

        return cond(x.sum() > 0, t, lambda: x)

    with pytest.raises(Exception, match="outside the branch"):
        fn(_t(np.array([1.0], np.float32)))


# -------------------------------------------------------- retry policy ----

def test_fallback_retry_then_recover(monkeypatch):
    """A transient trace failure no longer pins the key to eager forever:
    the next call retries and compiles (VERDICT r2 weak #4)."""
    from paddle_tpu import jit as jit_mod

    calls = {"n": 0}
    orig = jit_mod._Executable.build

    def flaky(self, *a, **kw):
        if calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError("transient trace failure")
        return orig(self, *a, **kw)

    monkeypatch.setattr(jit_mod._Executable, "build", flaky)

    @paddle.jit.to_static
    def fn(x):
        return x * 2.0

    x = _t(np.array([1.0], np.float32))
    with pytest.warns(UserWarning, match="retry 1/"):
        np.testing.assert_allclose(fn(x).numpy(), [2.0])  # eager fallback
    np.testing.assert_allclose(fn(x).numpy(), [2.0])      # retried: compiles
    sf = _sf(fn)
    assert len(sf._cache) == 1 and not sf._fallback_keys
    assert not sf._fallback_counts  # cleared on success


def test_fallback_pins_after_limit(monkeypatch):
    from paddle_tpu import jit as jit_mod

    def always_fail(self, *a, **kw):
        raise RuntimeError("permanent trace failure")

    monkeypatch.setattr(jit_mod._Executable, "build", always_fail)
    monkeypatch.setattr(jit_mod, "_fallback_retry_limit", 2)

    @paddle.jit.to_static
    def fn(x):
        return x + 1.0

    x = _t(np.array([1.0], np.float32))
    with pytest.warns(UserWarning, match="retry 1/2"):
        fn(x)
    with pytest.warns(UserWarning, match="pinning"):
        fn(x)
    sf = _sf(fn)
    assert sf._fallback_keys  # pinned
    # still correct, silently eager now
    np.testing.assert_allclose(fn(x).numpy(), [2.0])

"""Launcher tests (reference pattern: test_launch_coverage / the
fleet elastic watchdog tests)."""
import os
import subprocess
import sys
import textwrap


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_launch_sets_env_contract(tmp_path):
    script = _write(tmp_path, "probe.py", f"""
        import os, pathlib
        r = os.environ["PADDLE_TRAINER_ID"]
        pathlib.Path({str(tmp_path)!r}, "out" + r).write_text(
            " ".join([r, os.environ["PADDLE_TRAINERS_NUM"],
                      os.environ["PADDLE_LOCAL_RANK"]]))
    """)
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", script],
        capture_output=True, text=True, cwd="/root/repo",
        env={**os.environ, "PYTHONPATH": "/root/repo"})
    assert out.returncode == 0, out.stderr
    assert (tmp_path / "out0").read_text() == "0 2 0"
    assert (tmp_path / "out1").read_text() == "1 2 1"


def test_launch_elastic_restart(tmp_path):
    marker = tmp_path / "attempts"
    script = _write(tmp_path, "flaky.py", f"""
        import pathlib, sys
        m = pathlib.Path({str(marker)!r})
        n = int(m.read_text()) if m.exists() else 0
        m.write_text(str(n + 1))
        sys.exit(1 if n == 0 else 0)   # fail once, succeed on restart
    """)
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--max_restart_times", "2", script],
        capture_output=True, text=True, cwd="/root/repo",
        env={**os.environ, "PYTHONPATH": "/root/repo"})
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert marker.read_text() == "2"  # initial failure + 1 restart
    assert "restart 1/2" in out.stderr


def test_launch_propagates_persistent_failure(tmp_path):
    script = _write(tmp_path, "dead.py", "import sys; sys.exit(3)")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch", script],
        capture_output=True, text=True, cwd="/root/repo",
        env={**os.environ, "PYTHONPATH": "/root/repo"})
    assert out.returncode == 3


def test_multinode_env(tmp_path):
    script = _write(tmp_path, "probe.py", """
        import os
        print("R", os.environ["PADDLE_TRAINER_ID"],
              os.environ["JAX_COORDINATOR_ADDRESS"],
              os.environ["JAX_NUM_PROCESSES"],
              os.environ["JAX_PROCESS_ID"])
    """)
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "4", "--node_rank", "2",
         "--master", "10.0.0.1:8476", script],
        capture_output=True, text=True, cwd="/root/repo",
        env={**os.environ, "PYTHONPATH": "/root/repo"})
    assert out.returncode == 0, out.stderr
    assert "R 2 10.0.0.1:8476 4 2" in out.stdout

"""Continuous-batching serving engine (ISSUE 3 tentpole layer 2).

Correctness model: every request routed through the engine — whatever
the admission order, slot contention, prefill chunking, or page-table
shuffling — must produce EXACTLY the greedy sequence that a standalone
``generate(kv_cache='paged')`` call produces for the same prompt.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models import generate
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=64, dropout=0.0))
    m.eval()
    return m


def _refs(model, prompts, new):
    return [generate(model, p[None, :], max_new_tokens=n).numpy()[0]
            for p, n in zip(prompts, new)]


def test_engine_matches_generate_with_slot_contention(gpt):
    """4 ragged requests through 2 slots: later requests are admitted
    MID-STREAM as earlier ones retire; mixed steps run admissions'
    prefill chunks ragged-batched with ongoing decodes; every output
    must equal the sequential generate() row."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (5, 9, 3, 12)]
    new = [6, 4, 7, 5]
    refs = _refs(gpt, prompts, new)
    eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    assert sorted(done) == sorted(rids)
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    # continuous batching actually happened: more requests than slots,
    # and prefill ran ragged-batched with ongoing decodes
    assert eng.stats["admitted"] == 4 and eng.stats["retired"] == 4
    assert eng.stats["mixed_steps"] >= 2


def test_engine_page_reuse_and_free_list_restore(gpt):
    """Retired sequences return pages to the free list and later
    admissions REUSE them: total allocations exceed the peak resident
    count, and the free list is whole after the drain."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 96, (6,)).astype(np.int32)
               for _ in range(4)]
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    refs = _refs(gpt, prompts, [4] * 4)
    rids = [eng.add_request(p, 4) for p in prompts]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    st = eng.stats
    assert st["pages_allocated"] > st["peak_pages_in_use"]  # reuse
    assert len(eng._free_pages) == eng.total_pages - 1      # all freed
    assert st["peak_pages_in_use"] <= 2  # one slot's worst case


def test_engine_eos_early_retire(gpt):
    """eos stops a request early (device stop rule == host replay) and
    frees its slot for the queue."""
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 96, (5,)).astype(np.int32)
    full = generate(gpt, prompt[None, :], max_new_tokens=8).numpy()[0]
    eos = int(full[prompt.size + 1])       # 2nd generated token
    ref = generate(gpt, prompt[None, :], max_new_tokens=8,
                   eos_token_id=eos).numpy()[0]
    eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rid = eng.add_request(prompt, 8, eos_token_id=eos)
    done = eng.run()
    got = done[rid].sequence
    assert got[-1] == eos and got.size < prompt.size + 8  # stopped early
    np.testing.assert_array_equal(got, ref[:got.size])
    assert len(eng._free_pages) == eng.total_pages - 1


def test_engine_llama_gqa():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64))
    m.eval()
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (7, 4, 11)]
    new = [5, 6, 4]
    refs = _refs(m, prompts, new)
    eng = ContinuousBatchingEngine(m, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=3,
                                   prefill_chunk=6, q_block=2,
                                   pages_per_block=1)  # override threads
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)


def test_engine_rejects_oversize_request(gpt):
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.add_request(np.zeros(12, np.int32), 8)

"""Continuous-batching serving engine (ISSUE 3 tentpole layer 2).

Correctness model: every request routed through the engine — whatever
the admission order, slot contention, prefill chunking, or page-table
shuffling — must produce EXACTLY the greedy sequence that a standalone
``generate(kv_cache='paged')`` call produces for the same prompt.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models import generate
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=64, dropout=0.0))
    m.eval()
    return m


def _refs(model, prompts, new):
    return [generate(model, p[None, :], max_new_tokens=n).numpy()[0]
            for p, n in zip(prompts, new)]


def test_engine_matches_generate_with_slot_contention(gpt):
    """4 ragged requests through 2 slots: later requests are admitted
    MID-STREAM as earlier ones retire; mixed steps run admissions'
    prefill chunks ragged-batched with ongoing decodes; every output
    must equal the sequential generate() row."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (5, 9, 3, 12)]
    new = [6, 4, 7, 5]
    refs = _refs(gpt, prompts, new)
    eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    assert sorted(done) == sorted(rids)
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    # continuous batching actually happened: more requests than slots,
    # and prefill ran ragged-batched with ongoing decodes
    assert eng.stats["admitted"] == 4 and eng.stats["retired"] == 4
    assert eng.stats["mixed_steps"] >= 2


def test_engine_page_reuse_and_free_list_restore(gpt):
    """Retired sequences return pages to the free list and later
    admissions REUSE them: total allocations exceed the peak resident
    count, and the free list is whole after the drain."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 96, (6,)).astype(np.int32)
               for _ in range(4)]
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    refs = _refs(gpt, prompts, [4] * 4)
    rids = [eng.add_request(p, 4) for p in prompts]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    st = eng.stats
    assert st["pages_allocated"] > st["peak_pages_in_use"]  # reuse
    assert len(eng._free_pages) == eng.total_pages - 1      # all freed
    assert st["peak_pages_in_use"] <= 2  # one slot's worst case
    # health gauges: a drained engine reads empty
    assert st["pages_in_use"] == 0
    assert st["pages_free"] == eng.total_pages - 1
    assert st["queue_depth"] == 0
    # ... and a loaded engine reads loaded: queue 3 deep behind slot 0
    eng.add_request(prompts[0], 4)
    for p in prompts[1:]:
        eng.add_request(p, 4)
    eng.step()
    st = eng.stats
    assert st["queue_depth"] == 3 and st["pages_in_use"] > 0
    assert st["pages_free"] == eng.total_pages - 1 - st["pages_in_use"]
    eng.run()
    assert eng.stats["pages_in_use"] == 0


def test_engine_eos_early_retire(gpt):
    """eos stops a request early (device stop rule == host replay) and
    frees its slot for the queue."""
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 96, (5,)).astype(np.int32)
    full = generate(gpt, prompt[None, :], max_new_tokens=8).numpy()[0]
    eos = int(full[prompt.size + 1])       # 2nd generated token
    ref = generate(gpt, prompt[None, :], max_new_tokens=8,
                   eos_token_id=eos).numpy()[0]
    eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rid = eng.add_request(prompt, 8, eos_token_id=eos)
    done = eng.run()
    got = done[rid].sequence
    assert got[-1] == eos and got.size < prompt.size + 8  # stopped early
    np.testing.assert_array_equal(got, ref[:got.size])
    assert len(eng._free_pages) == eng.total_pages - 1


def test_engine_llama_gqa():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64))
    m.eval()
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (7, 4, 11)]
    new = [5, 6, 4]
    refs = _refs(m, prompts, new)
    eng = ContinuousBatchingEngine(m, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=3,
                                   prefill_chunk=6, q_block=2,
                                   pages_per_block=1)  # override threads
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)


def test_engine_rejects_oversize_request(gpt):
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.add_request(np.zeros(12, np.int32), 8)


# ----------------------------------------------------------------------
# Overload / resilience (ISSUE 5): the engine must degrade gracefully —
# preempt-and-requeue under page pressure, coded rejections, deadlines,
# cancellation, a per-request decode guard, retried dispatches — while
# every SURVIVING request stays bit-identical to an uncontended
# generate(kv_cache='paged') run and no page ever leaks.
# ----------------------------------------------------------------------

def _paged_refs(model, prompts, new):
    return [generate(model, p[None, :], max_new_tokens=n,
                     kv_cache="paged").numpy()[0]
            for p, n in zip(prompts, new)]


def test_engine_preempt_requeue_bitwise(gpt):
    """Pool sized BELOW the working set: growth preempts the
    latest-admitted victim, which requeues and re-prefills
    prompt + tokens_so_far.  All requests complete, outputs are
    bitwise-identical to the uncontended run, zero pages leak, and the
    old pool-exhaustion RuntimeError is unreachable."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (6, 8, 5, 7)]
    new = [8, 7, 8, 6]
    refs = _paged_refs(gpt, prompts, new)
    # each request needs <= 4 pages (<= 16 tokens, page_size 4); three
    # slots' worst case is 12 pages but the pool only holds 8 usable
    eng = ContinuousBatchingEngine(gpt, max_slots=3, page_size=4,
                                   max_seq_len=16, total_pages=9,
                                   decode_window=4, prefill_chunk=8,
                                   q_block=2)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    assert sorted(done) == sorted(rids)
    for rid, ref in zip(rids, refs):
        assert done[rid].finish_reason == "length"
        np.testing.assert_array_equal(done[rid].sequence, ref)
    st = eng.stats
    assert st["preemptions"] > 0          # contention actually happened
    assert st["pages_in_use"] == 0        # zero leaked
    assert len(eng._free_pages) == eng.total_pages - 1
    assert sorted(set(eng._free_pages)) == list(
        range(1, eng.total_pages))        # free-list cardinality intact


def test_engine_serving_fault_drill(gpt):
    """The deterministic serving drill: oversubscribed pool, an
    injected dispatch transient (absorbed by bounded retry), an
    injected NaN decode (fails exactly one request), one cancel and one
    deadline expiry — survivors bit-identical, free list restored."""
    from paddle_tpu.core import errors
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (6, 7, 5, 8, 6)]
    new = [8, 6, 8, 7, 6]
    refs = _paged_refs(gpt, prompts, new)
    clock = [0.0]
    faults.clear()
    try:
        eng = ContinuousBatchingEngine(gpt, max_slots=3, page_size=4,
                                       max_seq_len=16, total_pages=9,
                                       decode_window=4, prefill_chunk=8,
                                       q_block=2, clock=lambda: clock[0])
        rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
        r_nan, r_cancel = rids[1], rids[2]
        r_dead = eng.add_request(prompts[0], 8, deadline_ms=100.0)
        faults.inject("engine_dispatch", times=2)       # transient x2
        faults.inject("engine_nan_decode", match=str(r_nan))
        assert eng.cancel(r_cancel) and not eng.cancel(10_000)
        done = {c.request_id: c for c in eng.step()}
        clock[0] = 0.2                    # past r_dead's 100ms deadline
        done.update(eng.run())
        assert sorted(done) == sorted(rids + [r_dead])
        # exactly one guard failure, carrying the coded error
        assert done[r_nan].finish_reason == "failed"
        assert isinstance(done[r_nan].error, errors.NonFiniteLogitsError)
        assert done[r_nan].error.error_code == "PDT-E018"
        assert done[r_cancel].finish_reason == "cancelled"
        assert done[r_dead].finish_reason == "timeout"
        # survivors (co-resident with every fault above) are bitwise
        survivors = [r for r in rids if r not in (r_nan, r_cancel)]
        for rid, ref in zip(rids, refs):
            if rid in survivors:
                assert done[rid].finish_reason == "length"
                np.testing.assert_array_equal(done[rid].sequence, ref)
        st = eng.stats
        assert st["retries"] == 2         # transient absorbed, not fatal
        assert st["failed"] == 1 and st["cancelled"] == 1
        assert st["timeouts"] == 1
        assert st["pages_in_use"] == 0 and st["queue_depth"] == 0
        assert sorted(set(eng._free_pages)) == list(
            range(1, eng.total_pages))
    finally:
        faults.clear()


def test_engine_injected_page_pressure(gpt):
    """The engine_page_pressure site forces the preempt path with a
    roomy pool: the grower's victim requeues, recomputes, and both
    outputs stay bitwise."""
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(5)
    p1 = rng.integers(0, 96, (6,)).astype(np.int32)
    p2 = rng.integers(0, 96, (7,)).astype(np.int32)
    ref1, ref2 = _paged_refs(gpt, [p1, p2], [8, 8])
    faults.clear()
    try:
        eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                       max_seq_len=32, decode_window=4,
                                       prefill_chunk=8, q_block=2)
        r1 = eng.add_request(p1, 8)
        r2 = eng.add_request(p2, 8)
        faults.inject("engine_page_pressure", match=str(r1))
        done = eng.run()
        np.testing.assert_array_equal(done[r1].sequence, ref1)
        np.testing.assert_array_equal(done[r2].sequence, ref2)
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["pages_in_use"] == 0
    finally:
        faults.clear()


def test_engine_nan_decode_mid_stream(gpt):
    """Guard fires mid-DECODE (not at prefill): the failed request
    keeps its pre-fault tokens, the co-resident request's stream is
    untouched."""
    from paddle_tpu.core import errors
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(13)
    p1 = rng.integers(0, 96, (6,)).astype(np.int32)
    p2 = rng.integers(0, 96, (7,)).astype(np.int32)
    (ref2,) = _paged_refs(gpt, [p2], [8])
    faults.clear()
    try:
        eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                       max_seq_len=32, decode_window=4,
                                       prefill_chunk=8, q_block=2)
        r1 = eng.add_request(p1, 8)
        r2 = eng.add_request(p2, 8)
        # at=2: first guarded dispatch for r1 is its prefill step; the
        # second poisons a decode window mid-stream
        faults.inject("engine_nan_decode", match=str(r1), at=2)
        done = eng.run()
        assert done[r1].finish_reason == "failed"
        assert isinstance(done[r1].error, errors.NonFiniteLogitsError)
        assert 0 < done[r1].tokens.size < 8   # partial stream survives
        assert done[r2].finish_reason == "length"
        np.testing.assert_array_equal(done[r2].sequence, ref2)
        assert eng.stats["failed"] == 1
    finally:
        faults.clear()


def test_engine_page_budget_eager_reject(gpt):
    """A request that can NEVER fit the pool is rejected at
    add_request with the coded PageBudgetError — not queued to crash
    step() later — and an admissible mix can never reach the step-time
    backstop."""
    from paddle_tpu.core import errors

    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=32, total_pages=3)
    with pytest.raises(errors.PageBudgetError,
                       match="PDT-E016") as ei:
        eng.add_request(np.zeros(12, np.int32), 12)   # 3 pages > 2
    assert ei.value.error_code == "PDT-E016"
    assert eng.stats["rejected"] == 1
    assert not eng.has_work                   # nothing poisoned a queue
    # boundary: exactly the usable pool is admissible
    rid = eng.add_request(np.zeros(10, np.int32), 6)  # 16 tok = 2 pages
    done = eng.run()
    assert done[rid].finish_reason == "length"


def test_engine_queue_policies(gpt):
    """Bounded admission: 'reject' raises the coded QueueFullError,
    'block' steps the engine until the queue drains."""
    from paddle_tpu.core import errors

    rng = np.random.default_rng(17)
    p = rng.integers(0, 96, (5,)).astype(np.int32)
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, max_queue=1,
                                   queue_policy="reject")
    eng.add_request(p, 4)
    with pytest.raises(errors.QueueFullError, match="PDT-E017") as ei:
        eng.add_request(p, 4)             # queue full before any step
    assert ei.value.error_code == "PDT-E017"
    assert eng.stats["rejected"] == 1
    eng.run()

    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, max_queue=1,
                                   queue_policy="block")
    rids = [eng.add_request(p, 4) for _ in range(3)]  # adds 2+ block
    done = eng.run()
    assert sorted(done) == sorted(rids)
    assert all(done[r].ok for r in rids)
    with pytest.raises(ValueError, match="queue_policy"):
        ContinuousBatchingEngine(gpt, queue_policy="drop")


def test_engine_run_budget_warns_and_surfaces_pending(gpt):
    """run(max_steps=...) exhausting its budget with work in flight
    warns (instead of returning silently like success) and
    pending_requests() names the stragglers."""
    rng = np.random.default_rng(19)
    prompts = [rng.integers(0, 96, (6,)).astype(np.int32)
               for _ in range(3)]
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rids = [eng.add_request(p, 4) for p in prompts]
    with pytest.warns(RuntimeWarning, match="pending_requests"):
        done = eng.run(max_steps=2)
    pend = eng.pending_requests()
    assert pend and set(pend) == set(rids) - set(done)
    done.update(eng.run())                # budget off: drains clean
    assert sorted(done) == sorted(rids) and not eng.pending_requests()


def test_engine_cancel_after_final_token_honored(gpt):
    """cancel() racing retirement: the slot has already generated its
    final token (done, awaiting the next step boundary) when cancel()
    returns True — the promised "cancelled" result must surface, not a
    "length" retirement that silently outruns the cancellation."""
    rng = np.random.default_rng(23)
    prompt = rng.integers(0, 96, (6,)).astype(np.int32)
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rid = eng.add_request(prompt, 4)
    done = {}
    for _ in range(50):
        if any(s.req is not None and s.done for s in eng._slots):
            break
        done.update(eng.step())
    else:
        pytest.fail("slot never reached done-awaiting-retirement")
    assert not done                       # nothing surfaced yet
    assert eng.cancel(rid)                # promises a "cancelled" result
    done.update(eng.run())
    assert done[rid].finish_reason == "cancelled"
    assert eng.stats["cancelled"] == 1 and eng.stats["retired"] == 0
    assert eng.stats["pages_in_use"] == 0

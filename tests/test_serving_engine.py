"""Continuous-batching serving engine (ISSUE 3 tentpole layer 2).

Correctness model: every request routed through the engine — whatever
the admission order, slot contention, prefill chunking, or page-table
shuffling — must produce EXACTLY the greedy sequence that a standalone
``generate(kv_cache='paged')`` call produces for the same prompt.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models import generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def gpt(serving_gpt):
    # the session-scoped tiny model (tests/conftest.py): its compiled
    # program caches are shared with test_quant_serving.py
    return serving_gpt


def _refs(model, prompts, new):
    return [generate(model, p[None, :], max_new_tokens=n).numpy()[0]
            for p, n in zip(prompts, new)]


def _assert_pool_conserved(eng, drained=True):
    """Allocator conservation: free, cached and in-use pages are
    disjoint, never include the null page, and sum to the usable pool.
    A DRAINED engine additionally has zero pages in use (retired pages
    may legitimately stay CACHED in the prefix index — the free list
    alone is no longer the whole story)."""
    st = eng.stats
    free = set(eng._free_pages)
    cached = set(eng._cache.cached_page_ids())
    assert len(eng._free_pages) == len(free)          # no duplicates
    assert not (free & cached)
    assert 0 not in free and 0 not in cached
    assert (st["pages_in_use"] + st["pages_free"]
            + st["cached_pages"]) == eng.total_pages - 1
    eng._cache.check()                                # PDT-E019 audit
    if drained:
        assert st["pages_in_use"] == 0
        assert free | cached == set(range(1, eng.total_pages))


def test_engine_matches_generate_with_slot_contention(gpt):
    """4 ragged requests through 2 slots: later requests are admitted
    MID-STREAM as earlier ones retire; mixed steps run admissions'
    prefill chunks ragged-batched with ongoing decodes; every output
    must equal the sequential generate() row."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (5, 9, 3, 12)]
    new = [6, 4, 7, 5]
    refs = _refs(gpt, prompts, new)
    eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    assert sorted(done) == sorted(rids)
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    # continuous batching actually happened: more requests than slots,
    # and prefill ran ragged-batched with ongoing decodes
    assert eng.stats["admitted"] == 4 and eng.stats["retired"] == 4
    assert eng.stats["mixed_steps"] >= 2


def test_engine_page_reuse_and_free_list_restore(gpt):
    """Retired sequences return pages to the free list and later
    admissions REUSE them: total allocations exceed the peak resident
    count, and the free list is whole after the drain."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 96, (6,)).astype(np.int32)
               for _ in range(4)]
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    refs = _refs(gpt, prompts, [4] * 4)
    rids = [eng.add_request(p, 4) for p in prompts]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    st = eng.stats
    assert st["pages_allocated"] > st["peak_pages_in_use"]  # reuse
    _assert_pool_conserved(eng)          # nothing leaked, nothing dup'd
    assert st["peak_pages_in_use"] <= 2  # one slot's worst case
    # health gauges: a drained engine holds no pages in use (retired
    # full pages may stay CACHED in the prefix index by design)
    assert st["pages_in_use"] == 0
    assert st["pages_free"] + st["cached_pages"] == eng.total_pages - 1
    assert st["queue_depth"] == 0
    # ... and a loaded engine reads loaded: queue 3 deep behind slot 0
    eng.add_request(prompts[0], 4)
    for p in prompts[1:]:
        eng.add_request(p, 4)
    eng.step()
    st = eng.stats
    assert st["queue_depth"] == 3 and st["pages_in_use"] > 0
    assert st["pages_free"] == (eng.total_pages - 1
                                - st["pages_in_use"]
                                - st["cached_pages"])
    eng.run()
    assert eng.stats["pages_in_use"] == 0
    _assert_pool_conserved(eng)


def test_engine_eos_early_retire(gpt):
    """eos stops a request early (device stop rule == host replay) and
    frees its slot for the queue."""
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 96, (5,)).astype(np.int32)
    full = generate(gpt, prompt[None, :], max_new_tokens=8).numpy()[0]
    eos = int(full[prompt.size + 1])       # 2nd generated token
    ref = generate(gpt, prompt[None, :], max_new_tokens=8,
                   eos_token_id=eos).numpy()[0]
    eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rid = eng.add_request(prompt, 8, eos_token_id=eos)
    done = eng.run()
    got = done[rid].sequence
    assert got[-1] == eos and got.size < prompt.size + 8  # stopped early
    np.testing.assert_array_equal(got, ref[:got.size])
    _assert_pool_conserved(eng)


def test_engine_llama_gqa():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64))
    m.eval()
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (7, 4, 11)]
    new = [5, 6, 4]
    refs = _refs(m, prompts, new)
    eng = ContinuousBatchingEngine(m, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=3,
                                   prefill_chunk=6, q_block=2,
                                   pages_per_block=1)  # override threads
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)


def test_engine_rejects_oversize_request(gpt):
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.add_request(np.zeros(12, np.int32), 8)


# ----------------------------------------------------------------------
# Overload / resilience (ISSUE 5): the engine must degrade gracefully —
# preempt-and-requeue under page pressure, coded rejections, deadlines,
# cancellation, a per-request decode guard, retried dispatches — while
# every SURVIVING request stays bit-identical to an uncontended
# generate(kv_cache='paged') run and no page ever leaks.
# ----------------------------------------------------------------------

def _paged_refs(model, prompts, new):
    return [generate(model, p[None, :], max_new_tokens=n,
                     kv_cache="paged").numpy()[0]
            for p, n in zip(prompts, new)]


def test_engine_preempt_requeue_bitwise(gpt):
    """Pool sized BELOW the working set: growth preempts the
    latest-admitted victim, which requeues and re-prefills
    prompt + tokens_so_far.  All requests complete, outputs are
    bitwise-identical to the uncontended run, zero pages leak, and the
    old pool-exhaustion RuntimeError is unreachable."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (6, 8, 5, 7)]
    new = [8, 7, 8, 6]
    refs = _paged_refs(gpt, prompts, new)
    # each request needs <= 4 pages (<= 16 tokens, page_size 4); three
    # slots' worst case is 12 pages but the pool only holds 8 usable
    eng = ContinuousBatchingEngine(gpt, max_slots=3, page_size=4,
                                   max_seq_len=16, total_pages=9,
                                   decode_window=4, prefill_chunk=8,
                                   q_block=2)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    assert sorted(done) == sorted(rids)
    for rid, ref in zip(rids, refs):
        assert done[rid].finish_reason == "length"
        np.testing.assert_array_equal(done[rid].sequence, ref)
    st = eng.stats
    assert st["preemptions"] > 0          # contention actually happened
    assert st["pages_in_use"] == 0        # zero leaked
    _assert_pool_conserved(eng)           # free+cached = the whole pool


def test_engine_serving_fault_drill(gpt):
    """The deterministic serving drill: oversubscribed pool, an
    injected dispatch transient (absorbed by bounded retry), an
    injected NaN decode (fails exactly one request), one cancel and one
    deadline expiry — survivors bit-identical, free list restored."""
    from paddle_tpu.core import errors
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (6, 7, 5, 8, 6)]
    new = [8, 6, 8, 7, 6]
    refs = _paged_refs(gpt, prompts, new)
    clock = [0.0]
    faults.clear()
    try:
        eng = ContinuousBatchingEngine(gpt, max_slots=3, page_size=4,
                                       max_seq_len=16, total_pages=9,
                                       decode_window=4, prefill_chunk=8,
                                       q_block=2, clock=lambda: clock[0])
        rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
        r_nan, r_cancel = rids[1], rids[2]
        r_dead = eng.add_request(prompts[0], 8, deadline_ms=100.0)
        faults.inject("engine_dispatch", times=2)       # transient x2
        faults.inject("engine_nan_decode", match=str(r_nan))
        assert eng.cancel(r_cancel) and not eng.cancel(10_000)
        done = {c.request_id: c for c in eng.step()}
        clock[0] = 0.2                    # past r_dead's 100ms deadline
        done.update(eng.run())
        assert sorted(done) == sorted(rids + [r_dead])
        # exactly one guard failure, carrying the coded error
        assert done[r_nan].finish_reason == "failed"
        assert isinstance(done[r_nan].error, errors.NonFiniteLogitsError)
        assert done[r_nan].error.error_code == "PDT-E018"
        assert done[r_cancel].finish_reason == "cancelled"
        assert done[r_dead].finish_reason == "timeout"
        # survivors (co-resident with every fault above) are bitwise
        survivors = [r for r in rids if r not in (r_nan, r_cancel)]
        for rid, ref in zip(rids, refs):
            if rid in survivors:
                assert done[rid].finish_reason == "length"
                np.testing.assert_array_equal(done[rid].sequence, ref)
        st = eng.stats
        assert st["retries"] == 2         # transient absorbed, not fatal
        assert st["failed"] == 1 and st["cancelled"] == 1
        assert st["timeouts"] == 1
        assert st["pages_in_use"] == 0 and st["queue_depth"] == 0
        _assert_pool_conserved(eng)
    finally:
        faults.clear()


def test_engine_injected_page_pressure(gpt):
    """The engine_page_pressure site forces the preempt path with a
    roomy pool: the grower's victim requeues, recomputes, and both
    outputs stay bitwise."""
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(5)
    p1 = rng.integers(0, 96, (6,)).astype(np.int32)
    p2 = rng.integers(0, 96, (7,)).astype(np.int32)
    ref1, ref2 = _paged_refs(gpt, [p1, p2], [8, 8])
    faults.clear()
    try:
        eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                       max_seq_len=32, decode_window=4,
                                       prefill_chunk=8, q_block=2)
        r1 = eng.add_request(p1, 8)
        r2 = eng.add_request(p2, 8)
        faults.inject("engine_page_pressure", match=str(r1))
        done = eng.run()
        np.testing.assert_array_equal(done[r1].sequence, ref1)
        np.testing.assert_array_equal(done[r2].sequence, ref2)
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["pages_in_use"] == 0
    finally:
        faults.clear()


def test_engine_nan_decode_mid_stream(gpt):
    """Guard fires mid-DECODE (not at prefill): the failed request
    keeps its pre-fault tokens, the co-resident request's stream is
    untouched."""
    from paddle_tpu.core import errors
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(13)
    p1 = rng.integers(0, 96, (6,)).astype(np.int32)
    p2 = rng.integers(0, 96, (7,)).astype(np.int32)
    (ref2,) = _paged_refs(gpt, [p2], [8])
    faults.clear()
    try:
        eng = ContinuousBatchingEngine(gpt, max_slots=2, page_size=8,
                                       max_seq_len=32, decode_window=4,
                                       prefill_chunk=8, q_block=2)
        r1 = eng.add_request(p1, 8)
        r2 = eng.add_request(p2, 8)
        # at=2: first guarded dispatch for r1 is its prefill step; the
        # second poisons a decode window mid-stream
        faults.inject("engine_nan_decode", match=str(r1), at=2)
        done = eng.run()
        assert done[r1].finish_reason == "failed"
        assert isinstance(done[r1].error, errors.NonFiniteLogitsError)
        assert 0 < done[r1].tokens.size < 8   # partial stream survives
        assert done[r2].finish_reason == "length"
        np.testing.assert_array_equal(done[r2].sequence, ref2)
        assert eng.stats["failed"] == 1
    finally:
        faults.clear()


def test_engine_page_budget_eager_reject(gpt):
    """A request that can NEVER fit the pool is rejected at
    add_request with the coded PageBudgetError — not queued to crash
    step() later — and an admissible mix can never reach the step-time
    backstop."""
    from paddle_tpu.core import errors

    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=32, total_pages=3)
    with pytest.raises(errors.PageBudgetError,
                       match="PDT-E016") as ei:
        eng.add_request(np.zeros(12, np.int32), 12)   # 3 pages > 2
    assert ei.value.error_code == "PDT-E016"
    assert eng.stats["rejected"] == 1
    assert not eng.has_work                   # nothing poisoned a queue
    # boundary: exactly the usable pool is admissible
    rid = eng.add_request(np.zeros(10, np.int32), 6)  # 16 tok = 2 pages
    done = eng.run()
    assert done[rid].finish_reason == "length"


def test_engine_queue_policies(gpt):
    """Bounded admission: 'reject' raises the coded QueueFullError,
    'block' steps the engine until the queue drains."""
    from paddle_tpu.core import errors

    rng = np.random.default_rng(17)
    p = rng.integers(0, 96, (5,)).astype(np.int32)
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, max_queue=1,
                                   queue_policy="reject")
    eng.add_request(p, 4)
    with pytest.raises(errors.QueueFullError, match="PDT-E017") as ei:
        eng.add_request(p, 4)             # queue full before any step
    assert ei.value.error_code == "PDT-E017"
    assert eng.stats["rejected"] == 1
    eng.run()

    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, max_queue=1,
                                   queue_policy="block")
    rids = [eng.add_request(p, 4) for _ in range(3)]  # adds 2+ block
    done = eng.run()
    assert sorted(done) == sorted(rids)
    assert all(done[r].ok for r in rids)
    with pytest.raises(ValueError, match="queue_policy"):
        ContinuousBatchingEngine(gpt, queue_policy="drop")


def test_engine_run_budget_warns_and_surfaces_pending(gpt):
    """run(max_steps=...) exhausting its budget with work in flight
    warns (instead of returning silently like success) and
    pending_requests() names the stragglers."""
    rng = np.random.default_rng(19)
    prompts = [rng.integers(0, 96, (6,)).astype(np.int32)
               for _ in range(3)]
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rids = [eng.add_request(p, 4) for p in prompts]
    with pytest.warns(RuntimeWarning, match="pending_requests"):
        done = eng.run(max_steps=2)
    pend = eng.pending_requests()
    assert pend and set(pend) == set(rids) - set(done)
    done.update(eng.run())                # budget off: drains clean
    assert sorted(done) == sorted(rids) and not eng.pending_requests()


def test_engine_cancel_after_final_token_honored(gpt):
    """cancel() racing retirement: the slot has already generated its
    final token (done, awaiting the next step boundary) when cancel()
    returns True — the promised "cancelled" result must surface, not a
    "length" retirement that silently outruns the cancellation."""
    rng = np.random.default_rng(23)
    prompt = rng.integers(0, 96, (6,)).astype(np.int32)
    eng = ContinuousBatchingEngine(gpt, max_slots=1, page_size=8,
                                   max_seq_len=16, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    rid = eng.add_request(prompt, 4)
    done = {}
    for _ in range(50):
        if any(s.req is not None and s.done for s in eng._slots):
            break
        done.update(eng.step())
    else:
        pytest.fail("slot never reached done-awaiting-retirement")
    assert not done                       # nothing surfaced yet
    assert eng.cancel(rid)                # promises a "cancelled" result
    done.update(eng.run())
    assert done[rid].finish_reason == "cancelled"
    assert eng.stats["cancelled"] == 1 and eng.stats["retired"] == 0
    assert eng.stats["pages_in_use"] == 0


# ----------------------------------------------------------------------
# Cross-request KV prefix cache (ISSUE 6): a radix index over the page
# pool maps shared prefixes onto already-written pages (block-table
# indirection only), with copy-on-write at the divergence page and LRU
# eviction — bitwise-identical to generate(kv_cache='paged') and to the
# cache-off engine in every mix, including preempt-requeue restore and
# post-eviction re-admission.
# ----------------------------------------------------------------------

def _engine(gpt, **kw):
    args = dict(max_slots=2, page_size=4, max_seq_len=32,
                decode_window=4, prefill_chunk=8, q_block=2)
    args.update(kw)
    return ContinuousBatchingEngine(gpt, **args)


def test_engine_prefix_cache_shared_prefix_bitwise(gpt):
    """Requests sharing a long prompt prefix: later admissions map the
    shared pages from the index (prefill tokens computed drops below
    tokens requested) and every output is bitwise-identical to the
    uncached reference AND to a cache-off engine."""
    rng = np.random.default_rng(29)
    shared = rng.integers(0, 96, (12,)).astype(np.int32)  # 3 full pages
    tails = [rng.integers(0, 96, (n,)).astype(np.int32)
             for n in (3, 2, 5, 1)]
    prompts = [np.concatenate([shared, t]) for t in tails]
    new = [6, 5, 4, 6]
    refs = _paged_refs(gpt, prompts, new)

    outs = {}
    for mode in (True, False):
        eng = _engine(gpt, prefix_cache=mode)
        rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
        done = eng.run()
        outs[mode] = [done[r].sequence for r in rids]
        st = eng.stats
        if mode:
            # the first two admissions run concurrently (2 slots) and
            # prefill the shared prefix independently; both later
            # admissions hit the published pages
            assert st["cache_hits"] >= 2
            assert st["cache_hit_tokens"] >= 2 * 12
            assert (st["prefill_tokens_computed"]
                    < st["prefill_tokens_requested"])
            _assert_pool_conserved(eng)
        else:
            # cache off restores the uncached meter exactly
            assert st["cache_hits"] == 0 and st["cached_pages"] == 0
            assert (st["prefill_tokens_computed"]
                    == st["prefill_tokens_requested"])
            assert len(eng._free_pages) == eng.total_pages - 1
    for got_on, got_off, ref in zip(outs[True], outs[False], refs):
        np.testing.assert_array_equal(got_on, ref)
        np.testing.assert_array_equal(got_off, ref)


def test_engine_prefix_cache_cow_full_prompt(gpt):
    """A fully-cached page-aligned prompt takes the copy-on-write
    path: the divergence page is duplicated, exactly ONE token is
    recomputed for the last position's logits, the shared page is
    never written, and the output stays bitwise."""
    rng = np.random.default_rng(31)
    prompt = rng.integers(0, 96, (8,)).astype(np.int32)  # 2 full pages
    (ref,) = _paged_refs(gpt, [prompt], [6])
    eng = _engine(gpt)
    r1 = eng.add_request(prompt, 6)
    done = eng.run()
    np.testing.assert_array_equal(done[r1].sequence, ref)
    # retirement published the full prompt pages
    assert eng.stats["cached_pages"] >= 2
    base = eng.stats["prefill_tokens_computed"]
    r2 = eng.add_request(prompt, 6)           # identical prompt: full hit
    done = eng.run()
    np.testing.assert_array_equal(done[r2].sequence, ref)
    st = eng.stats
    assert st["cache_hit_tokens"] >= prompt.size - 1   # COW: all but one
    assert st["prefill_tokens_computed"] - base == 1   # 1 recomputed tok
    _assert_pool_conserved(eng)


def test_engine_preempt_requeue_recompute_drop(gpt):
    """The PR5 recompute gap, closed: a preempted victim's pages are
    PUBLISHED to the index (not freed), so its re-admission restores
    from its own just-published pages — prefill-tokens-computed drops
    versus the cache-off engine on the identical forced-preemption
    workload, outputs bitwise both ways.  (In a truly starved pool the
    LRU may reclaim some of the victim's pages for the grower — that
    path is covered by test_engine_preempt_requeue_bitwise; here the
    pool is roomy and the ``engine_page_pressure`` drill forces the
    preemption, so the published pages survive to the re-admission.)"""
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(41)
    p1 = rng.integers(0, 96, (6,)).astype(np.int32)
    p2 = rng.integers(0, 96, (7,)).astype(np.int32)
    refs = _paged_refs(gpt, [p1, p2], [8, 8])
    computed = {}
    faults.clear()
    try:
        for mode in (False, True):
            eng = _engine(gpt, prefix_cache=mode)
            r1 = eng.add_request(p1, 8)
            r2 = eng.add_request(p2, 8)
            # r1's growth hits injected pressure -> r2 (latest) preempts
            faults.inject("engine_page_pressure", match=str(r1))
            done = eng.run()
            np.testing.assert_array_equal(done[r1].sequence, refs[0])
            np.testing.assert_array_equal(done[r2].sequence, refs[1])
            st = eng.stats
            assert st["preemptions"] >= 1
            computed[mode] = st["prefill_tokens_computed"]
            if mode:
                # prompts are DISTINCT, so every hit is the victim's
                # re-admission restoring from its own published pages
                assert st["cache_hits"] >= 1
                assert st["evictions"] == 0    # roomy pool: none lost
                _assert_pool_conserved(eng)
            else:
                assert st["cache_hits"] == 0
    finally:
        faults.clear()
    assert computed[True] < computed[False]


def test_engine_cache_evict_drill_bitwise(gpt):
    """The deterministic engine_cache_evict drill: cached prefix pages
    are evicted under the injected pressure, and a re-admission of the
    evicted prefix transparently re-prefills with bitwise-identical
    output (the cache can only ever cost recompute, never
    correctness)."""
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(37)
    p1 = rng.integers(0, 96, (9,)).astype(np.int32)
    p2 = rng.integers(0, 96, (6,)).astype(np.int32)
    ref1, ref2 = _paged_refs(gpt, [p1, p2], [6, 5])
    faults.clear()
    try:
        eng = _engine(gpt)
        r1 = eng.add_request(p1, 6)
        assert eng.run()[r1].finish_reason == "length"
        assert eng.stats["cached_pages"] >= 2   # p1's prefix published
        # every allocation for p2 forcibly evicts the LRU cached page
        faults.inject("engine_cache_evict", times=0)
        r2 = eng.add_request(p2, 5)
        done = eng.run()
        np.testing.assert_array_equal(done[r2].sequence, ref2)
        faults.clear()
        st = eng.stats
        assert st["evictions"] >= 2             # drill actually evicted
        hits_before = st["cache_hits"]
        # p1 again: its prefix was evicted -> full re-prefill, bitwise
        r3 = eng.add_request(p1, 6)
        done = eng.run()
        np.testing.assert_array_equal(done[r3].sequence, ref1)
        assert eng.stats["cache_hits"] == hits_before  # true miss
        _assert_pool_conserved(eng)
    finally:
        faults.clear()


def test_serving_bench_shared_prefix_accounting(gpt):
    """CPU tiny-model smoke for the serving_bench ``shared_prefix``
    row: the accounting must show prefill tokens computed < tokens
    requested at a high prefix-hit rate, zero leaked pages, and a
    sane saved fraction (absolute times are TPU-only claims)."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "benchmarks", "serving_bench.py")
    spec = importlib.util.spec_from_file_location(
        "serving_bench_smoke", path)
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    row = sb._measure_shared_prefix(
        gpt.cfg, gpt, slots=2, max_seq_len=64, shared_len=12,
        tail_range=(2, 7), new_tokens=4, n_requests=6, hit_every=3,
        page_size=4, decode_window=4, prefill_chunk=8, warm=False)
    assert (row["prefill_tokens_computed"]
            < row["prefill_tokens_requested"])
    assert row["prefill_saved_frac"] > 0
    assert row["cache_hits"] >= 2 and row["cache_hit_tokens"] >= 2 * 12
    assert row["pages_leaked"] == 0
    assert row["ttft_ms_avg"] > 0 and row["ttft_ms_avg_nocache"] > 0

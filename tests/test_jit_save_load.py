"""jit.save/load program export tests (reference ``paddle.jit.save/load``
``python/paddle/jit/api.py:744,1246``; test pattern from
``test/dygraph_to_static/test_save_inference_model.py``: save, reload,
compare outputs — including in a fresh process without the model class)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def test_save_load_same_outputs(tmp_path):
    paddle.seed(0)
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "model")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 16])])
    assert os.path.exists(path + ".pdmodel")
    assert os.path.exists(path + ".pdiparams")

    loaded = paddle.jit.load(path)
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(5, 16)).astype("float32"))
    ref = net(x)
    out = loaded(x)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)
    # dynamic batch: a different batch size runs through the same program
    x2 = paddle.to_tensor(
        np.random.default_rng(1).normal(size=(9, 16)).astype("float32"))
    np.testing.assert_allclose(loaded(x2).numpy(), net(x2).numpy(),
                               atol=1e-5)


def test_save_load_fresh_process(tmp_path):
    paddle.seed(1)
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "fresh")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 16])])
    x = np.random.default_rng(2).normal(size=(3, 16)).astype("float32")
    ref = net(paddle.to_tensor(x)).numpy()
    np.save(str(tmp_path / "x.npy"), x)
    np.save(str(tmp_path / "ref.npy"), ref)
    # a fresh interpreter with no SmallNet definition
    code = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
loaded = paddle.jit.load({path!r})
x = np.load({str(tmp_path / 'x.npy')!r})
out = loaded(paddle.to_tensor(x))
ref = np.load({str(tmp_path / 'ref.npy')!r})
np.testing.assert_allclose(out.numpy(), ref, atol=1e-5)
print("FRESH_OK")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in sys.path if p] + [env.get("PYTHONPATH", "")])
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300, env=env)
    assert "FRESH_OK" in r.stdout, r.stdout + r.stderr


def test_save_static_function(tmp_path):
    paddle.seed(2)
    net = SmallNet()
    net.eval()

    @paddle.jit.to_static(input_spec=[InputSpec([None, 16], name="x")])
    def infer(x):
        return net(x) * 2.0

    path = str(tmp_path / "fn")
    paddle.jit.save(infer, path)
    loaded = paddle.jit.load(path)
    x = paddle.to_tensor(
        np.random.default_rng(3).normal(size=(4, 16)).astype("float32"))
    np.testing.assert_allclose(loaded(x).numpy(), (net(x) * 2.0).numpy(),
                               atol=1e-5)


def test_save_multi_output_structure(tmp_path):
    class TwoHead(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(8, 3)
            self.b = nn.Linear(8, 5)

        def forward(self, x):
            return {"a": self.a(x), "b": [self.b(x), x.sum()]}

    paddle.seed(3)
    net = TwoHead()
    net.eval()
    path = str(tmp_path / "multi")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])
    loaded = paddle.jit.load(path)
    x = paddle.to_tensor(
        np.random.default_rng(4).normal(size=(2, 8)).astype("float32"))
    ref = net(x)
    out = loaded(x)
    np.testing.assert_allclose(out["a"].numpy(), ref["a"].numpy(), atol=1e-5)
    np.testing.assert_allclose(out["b"][0].numpy(), ref["b"][0].numpy(),
                               atol=1e-5)
    np.testing.assert_allclose(out["b"][1].numpy(), ref["b"][1].numpy(),
                               atol=1e-5)


def test_save_requires_spec(tmp_path):
    net = SmallNet()
    with pytest.raises(ValueError, match="input_spec"):
        paddle.jit.save(net, str(tmp_path / "nospec"))


def test_translated_layer_train_raises(tmp_path):
    paddle.seed(4)
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "t")
    paddle.jit.save(net, path, input_spec=[InputSpec([1, 16])])
    loaded = paddle.jit.load(path)
    with pytest.raises(RuntimeError):
        loaded.train()


def test_predictor_output_names_from_export(tmp_path):
    """Dict-returning model: Predictor output names come from the export
    metadata keys, not synthesized out{i} (VERDICT r3 item 8)."""
    from paddle_tpu.inference import Config, create_predictor

    class DictNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            h = self.fc(x)
            return {"logits": h, "probs": nn.functional.softmax(h)}

    paddle.seed(0)
    net = DictNet()
    net.eval()
    path = str(tmp_path / "dictnet")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 4])])
    pred = create_predictor(Config(path))
    x = np.ones((2, 4), np.float32)
    pred.run([x])
    assert pred.get_output_names() == ["logits", "probs"]


def test_predictor_output_names_explicit(tmp_path):
    paddle.seed(0)
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "named")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 16])],
                    output_names=["scores"])
    from paddle_tpu.inference import Config, create_predictor
    pred = create_predictor(Config(path))
    pred.run([np.ones((2, 16), np.float32)])
    assert pred.get_output_names() == ["scores"]

"""Optimizer + LR scheduler + AMP tests."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer as opt


def _quadratic_steps(optimizer_factory, n=50):
    """Minimize ||w - 3||^2 and return final w."""
    w = pt.Parameter(np.zeros(4, dtype="float32"))
    o = optimizer_factory([w])
    for _ in range(n):
        loss = ((w - 3.0) ** 2).sum()
        loss.backward()
        o.step()
        o.clear_grad()
    return w.numpy()


def test_sgd_converges():
    w = _quadratic_steps(lambda ps: opt.SGD(0.1, parameters=ps), 100)
    np.testing.assert_allclose(w, np.full(4, 3.0), atol=1e-3)


def test_momentum_converges():
    w = _quadratic_steps(lambda ps: opt.Momentum(0.05, 0.9, parameters=ps),
                         100)
    np.testing.assert_allclose(w, np.full(4, 3.0), atol=5e-2)


def test_adam_converges():
    w = _quadratic_steps(lambda ps: opt.Adam(0.3, parameters=ps), 100)
    np.testing.assert_allclose(w, np.full(4, 3.0), atol=1e-2)


def test_adamw_decay_shrinks_weights():
    w = pt.Parameter(np.full(4, 5.0, dtype="float32"))
    o = opt.AdamW(learning_rate=0.0, weight_decay=0.1, parameters=[w])
    w.grad = pt.zeros([4])
    o.step()
    # lr=0 -> only decay path, which multiplies by (1 - lr*coeff) = 1
    np.testing.assert_allclose(w.numpy(), np.full(4, 5.0))
    o2 = opt.AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[w])
    w.grad = pt.zeros([4])
    o2.step()
    assert (w.numpy() < 5.0).all()


def test_adam_matches_reference_formula():
    w0 = np.array([1.0, -2.0], dtype="float32")
    g = np.array([0.5, 0.3], dtype="float32")
    w = pt.Parameter(w0.copy())
    o = opt.Adam(learning_rate=0.01, parameters=[w])
    w.grad = pt.to_tensor(g.copy())
    o.step()
    m = 0.1 * g
    v = 0.001 * g * g
    m_hat = m / (1 - 0.9)
    v_hat = v / (1 - 0.999)
    ref = w0 - 0.01 * m_hat / (np.sqrt(v_hat) + 1e-8)
    np.testing.assert_allclose(w.numpy(), ref, rtol=1e-5)


def test_multi_precision_master_weights():
    w = pt.Parameter(np.full(4, 1.0, dtype="float32"))
    w._write(w._read().astype("bfloat16"))
    o = opt.SGD(0.001, parameters=[w], multi_precision=True)
    for _ in range(10):
        w.grad = pt.to_tensor(np.full(4, 0.01, dtype="float32"))
        o.step()
    # 10 tiny steps accumulate exactly in the fp32 master copy
    master = o._master_weights[id(w)]
    np.testing.assert_allclose(np.asarray(master), np.full(4, 0.9999),
                               rtol=1e-5)


def test_optimizer_state_dict_roundtrip():
    w = pt.Parameter(np.ones(3, dtype="float32"), name="w")
    o = opt.Adam(0.1, parameters=[w])
    w.grad = pt.ones([3])
    o.step()
    sd = o.state_dict()
    o2 = opt.Adam(0.1, parameters=[w])
    o2.set_state_dict(sd)
    assert o2._step_count == 1
    np.testing.assert_allclose(
        np.asarray(o2._accumulators["moment1"][id(w)]),
        np.asarray(o._accumulators["moment1"][id(w)]))


def test_lr_schedulers():
    from paddle_tpu.optimizer.lr import (
        CosineAnnealingDecay, LinearWarmup, MultiStepDecay, NoamDecay,
        PiecewiseDecay, PolynomialDecay, StepDecay)
    s = StepDecay(0.1, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(s())
        s.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])
    w = LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    vals = []
    for _ in range(5):
        vals.append(w())
        w.step()
    np.testing.assert_allclose(vals[:4], [0.0, 0.025, 0.05, 0.075])
    c = CosineAnnealingDecay(1.0, T_max=10)
    assert abs(c() - 1.0) < 1e-6
    p = PiecewiseDecay([3, 6], [0.1, 0.01, 0.001])
    assert p() == 0.1


def test_scheduler_drives_optimizer():
    from paddle_tpu.optimizer.lr import StepDecay
    sched = StepDecay(0.1, step_size=1, gamma=0.1)
    w = pt.Parameter(np.zeros(1, dtype="float32"))
    o = opt.SGD(sched, parameters=[w])
    w.grad = pt.ones([1])
    o.step()
    np.testing.assert_allclose(w.numpy(), [-0.1], rtol=1e-6)
    sched.step()
    w.grad = pt.ones([1])
    o.step()
    np.testing.assert_allclose(w.numpy(), [-0.11], rtol=1e-5)


def test_grad_clip_in_optimizer():
    from paddle_tpu.nn import ClipGradByGlobalNorm
    w = pt.Parameter(np.zeros(4, dtype="float32"))
    o = opt.SGD(1.0, parameters=[w], grad_clip=ClipGradByGlobalNorm(1.0))
    w.grad = pt.to_tensor(np.full(4, 100.0, dtype="float32"))
    o.step()
    np.testing.assert_allclose(np.linalg.norm(w.numpy()), 1.0, rtol=1e-4)


def test_amp_auto_cast_o1():
    import paddle_tpu.amp as amp
    x = pt.randn([4, 4])
    y = pt.randn([4, 4])
    with amp.auto_cast(level="O1", dtype="bfloat16"):
        z = pt.matmul(x, y)
        assert str(z.dtype) == "bfloat16"
        s = F.softmax(z)  # black list -> fp32
        assert str(s.dtype) == "float32"
    z2 = pt.matmul(x, y)
    assert str(z2.dtype) == "float32"


def test_amp_grad_scaler_fp16_flow():
    import paddle_tpu.amp as amp
    w = pt.Parameter(np.ones(2, dtype="float32"))
    o = opt.SGD(0.1, parameters=[w])
    scaler = amp.GradScaler(init_loss_scaling=1024.0)
    loss = (w * 2.0).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    # grad should be 2*1024 before unscale
    np.testing.assert_allclose(w.grad.numpy(), [2048.0, 2048.0])
    scaler.step(o)
    np.testing.assert_allclose(w.numpy(), [0.8, 0.8], rtol=1e-6)


def test_grad_scaler_skips_on_inf():
    import paddle_tpu.amp as amp
    w = pt.Parameter(np.ones(2, dtype="float32"))
    o = opt.SGD(0.1, parameters=[w])
    scaler = amp.GradScaler(init_loss_scaling=1024.0)
    w.grad = pt.to_tensor(np.array([np.inf, 1.0], dtype="float32"))
    scaler.step(o)
    np.testing.assert_allclose(w.numpy(), [1.0, 1.0])  # step skipped
    assert scaler._scale == 512.0  # scale halved


def test_amp_decorate_o2():
    import paddle_tpu.amp as amp
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.BatchNorm1D(8))
    o = opt.Adam(0.1, parameters=model.parameters())
    model, o = amp.decorate(model, o, level="O2", dtype="bfloat16")
    assert str(model[0].weight.dtype) == "bfloat16"
    # norm layers stay fp32
    assert str(model[2].weight.dtype) == "float32"
    assert o._multi_precision

"""Real multi-process (multi-host simulation) test: two CPU processes
federate through the JAX coordination service via init_parallel_env
(using the launcher's env contract), and a pod-wide psum must see both
processes' contributions — the ``test_dist_base.py`` pattern of SURVEY
§4 (N local processes standing in for N hosts)."""
import os
import subprocess
import sys
import textwrap


def test_two_process_allreduce(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import paddle_tpu.distributed as dist
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental import multihost_utils

        dist.init_parallel_env()   # federates via JAX_COORDINATOR_ADDRESS
        assert jax.process_count() == 2, jax.process_count()
        assert jax.device_count() == 2
        rank = jax.process_index()

        mesh = Mesh(jax.devices(), ("x",))
        from paddle_tpu.core.meshutil import shard_map
        f = jax.jit(shard_map(lambda a: jax.lax.psum(a, "x"),
                              mesh=mesh, in_specs=P("x"),
                              out_specs=P()))
        garr = multihost_utils.host_local_array_to_global_array(
            np.full((1,), float(rank + 1), np.float32), mesh, P("x"))
        out = f(garr)            # replicated result: read the local shard
        val = float(np.asarray(out.addressable_data(0)))
        assert val == 3.0, val   # 1 + 2 summed across processes
        import pathlib
        pathlib.Path({str(tmp_path)!r}, f"ok{{rank}}").write_text(str(val))
    """))

    def start(rank):
        env = {**os.environ, "PYTHONPATH": "/root/repo",
               "JAX_PLATFORMS": "cpu",
               # the contract paddle_tpu.distributed.launch sets per host
               "JAX_COORDINATOR_ADDRESS": "127.0.0.1:19284",
               "JAX_NUM_PROCESSES": "2",
               "JAX_PROCESS_ID": str(rank),
               "PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": "2"}
        env.pop("XLA_FLAGS", None)  # one real device per process
        return subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    p0, p1 = start(0), start(1)
    out0, _ = p0.communicate(timeout=180)
    out1, _ = p1.communicate(timeout=180)
    assert p0.returncode == 0, out0
    assert p1.returncode == 0, out1
    assert (tmp_path / "ok0").read_text() == "3.0"
    assert (tmp_path / "ok1").read_text() == "3.0"

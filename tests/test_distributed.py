"""Collective communication + DataParallel tests on the 8-device virtual
mesh (the reference's TestDistBase pattern, test/legacy_test/
test_dist_base.py:959, collapsed to single-controller SPMD)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


N = 8  # conftest forces 8 virtual CPU devices


@pytest.fixture(scope="module", autouse=True)
def _env():
    dist.init_parallel_env()


def _rank_tensor(shape=(), base=0.0):
    """Stack of per-rank values: slice r holds value base + r."""
    vals = np.stack([np.full(shape, base + r, dtype=np.float32)
                     for r in range(N)])
    return paddle.to_tensor(vals)


def test_world():
    assert dist.get_world_size() == N
    assert dist.get_rank() == 0
    assert dist.is_initialized()


def test_all_reduce_sum():
    t = _rank_tensor((3,))
    dist.all_reduce(t)
    expect = sum(range(N))  # 0+1+...+7 = 28
    np.testing.assert_allclose(t.numpy(), np.full((N, 3), expect), rtol=1e-6)


def test_all_reduce_ops():
    t = _rank_tensor((2,), base=1.0)  # ranks hold 1..8
    dist.all_reduce(t, op=dist.ReduceOp.MAX)
    np.testing.assert_allclose(t.numpy(), np.full((N, 2), 8.0))
    t = _rank_tensor((2,), base=1.0)
    dist.all_reduce(t, op=dist.ReduceOp.MIN)
    np.testing.assert_allclose(t.numpy(), np.full((N, 2), 1.0))
    t = _rank_tensor((2,), base=1.0)
    dist.all_reduce(t, op=dist.ReduceOp.AVG)
    np.testing.assert_allclose(t.numpy(), np.full((N, 2), 4.5))


def test_all_gather():
    t = _rank_tensor((2,))
    out = []
    dist.all_gather(out, t)
    assert len(out) == N
    for i, o in enumerate(out):
        np.testing.assert_allclose(o.numpy(), np.full((N, 2), float(i)))


def test_broadcast():
    t = _rank_tensor((2,))
    dist.broadcast(t, src=3)
    np.testing.assert_allclose(t.numpy(), np.full((N, 2), 3.0))


def test_reduce():
    t = _rank_tensor((2,))
    dist.reduce(t, dst=2)
    got = t.numpy()
    np.testing.assert_allclose(got[2], np.full((2,), 28.0))
    np.testing.assert_allclose(got[0], np.full((2,), 0.0))
    np.testing.assert_allclose(got[5], np.full((2,), 5.0))


def test_scatter():
    # src rank 1 scatters: rank i receives tensor_list[i] (as held by src)
    tl = [_rank_tensor((2,), base=10.0 * i) for i in range(N)]
    out = paddle.zeros([N, 2])
    dist.scatter(out, tl, src=1)
    got = out.numpy()
    for r in range(N):
        # tensor_list[r] slice at src=1 is 10*r + 1
        np.testing.assert_allclose(got[r], np.full((2,), 10.0 * r + 1.0))


def test_reduce_scatter():
    tl = [_rank_tensor((2,), base=float(i)) for i in range(N)]
    out = paddle.zeros([N, 2])
    dist.reduce_scatter(out, tl)
    got = out.numpy()
    for r in range(N):
        # sum over ranks q of tensor_list[r][q] = sum(r + q) = N*r + 28
        np.testing.assert_allclose(got[r], np.full((2,), N * r + 28.0))


def test_alltoall():
    tl = [_rank_tensor((2,), base=100.0 * i) for i in range(N)]
    out = []
    dist.alltoall(out, tl)
    for i, o in enumerate(out):
        got = o.numpy()
        for r in range(N):
            # out[i][r] = in[r][i] = 100*r + i
            np.testing.assert_allclose(got[r], np.full((2,), 100.0 * r + i))


def test_alltoall_single():
    # per-rank local [N] vector = rank id repeated; after exchange, local
    # chunk j = rank j's chunk for me
    x = np.zeros((N, N), dtype=np.float32)
    for r in range(N):
        x[r] = r * 10 + np.arange(N)
    t = paddle.to_tensor(x)
    out = paddle.zeros([N, N])
    dist.alltoall_single(out, t)
    got = out.numpy()
    for r in range(N):
        np.testing.assert_allclose(got[r], np.arange(N) * 10 + r)


def test_send_recv():
    t = _rank_tensor((2,))
    dist.send(t, dst=5, src=2)
    got = t.numpy()
    np.testing.assert_allclose(got[5], np.full((2,), 2.0))
    np.testing.assert_allclose(got[0], np.full((2,), 0.0))


def test_new_group_subset():
    g = dist.new_group(ranks=[0, 1, 2, 3])
    vals = np.stack([np.full((2,), float(r), np.float32) for r in range(4)])
    t = paddle.to_tensor(vals)
    dist.all_reduce(t, group=g)
    np.testing.assert_allclose(t.numpy(), np.full((4, 2), 6.0))


def test_barrier_and_wait():
    dist.barrier()
    t = _rank_tensor((2,))
    dist.wait(t)


class _MLP(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = paddle.nn.Linear(8, 16)
        self.fc2 = paddle.nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def test_data_parallel_loss_parity():
    """The reference's dist-base test pattern: DataParallel training must
    match single-device training on the same global batch."""
    paddle.seed(7)
    single = _MLP()
    paddle.seed(7)
    wrapped = dist.DataParallel(_MLP())

    opt_s = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=single.parameters())
    opt_d = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=wrapped.parameters())

    rng = np.random.RandomState(0)
    losses_s, losses_d = [], []
    for _ in range(3):
        xb = rng.randn(16, 8).astype(np.float32)
        yb = rng.randn(16, 4).astype(np.float32)

        x = paddle.to_tensor(xb)
        y = paddle.to_tensor(yb)
        loss = ((single(x) - y) ** 2).mean()
        loss.backward()
        opt_s.step()
        opt_s.clear_grad()
        losses_s.append(float(loss))

        x = paddle.to_tensor(xb)
        y = paddle.to_tensor(yb)
        loss = ((wrapped(x) - y) ** 2).mean()
        loss.backward()
        opt_d.step()
        opt_d.clear_grad()
        losses_d.append(float(loss))

    np.testing.assert_allclose(losses_s, losses_d, rtol=1e-5)


def test_data_parallel_actually_shards():
    wrapped = dist.DataParallel(_MLP())
    x = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32))
    out = wrapped(x)
    # forward ran on a batch sharded over dp: verify by re-sharding input
    shx = wrapped._shard_input(x)
    shards = shx._read().sharding
    assert len(shards.device_set) == N


def test_all_reduce_prod_with_negatives():
    vals = np.stack([np.full((2,), float(r - 3), np.float32)
                     for r in range(N)])  # includes negatives and zero
    t = paddle.to_tensor(vals)
    dist.all_reduce(t, op=dist.ReduceOp.PROD)
    expect = np.prod([r - 3 for r in range(N)])  # contains 0 -> 0
    np.testing.assert_allclose(t.numpy(), np.full((N, 2), expect))
    vals = np.stack([np.full((2,), float(r + 1) * (-1) ** r, np.float32)
                     for r in range(4)])
    g = dist.new_group(ranks=[0, 1, 2, 3])
    t = paddle.to_tensor(vals)
    dist.all_reduce(t, op=dist.ReduceOp.PROD, group=g)
    expect = 1 * -2 * 3 * -4  # = 24, sign preserved
    np.testing.assert_allclose(t.numpy(), np.full((4, 2), expect))


def test_out_of_group_rank_rejected():
    g = dist.new_group(ranks=[2, 3])
    vals = np.zeros((2, 2), np.float32)
    t = paddle.to_tensor(vals)
    with pytest.raises(ValueError):
        dist.broadcast(t, src=5, group=g)


def test_axis_group_collectives():
    """HybridCommunicateGroup's AxisGroup works with the comm API."""
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1}
    prev = fleet.get_hybrid_communicate_group()
    hcg = fleet.init(strategy=strategy)
    try:
        mp = hcg.get_model_parallel_group()
        assert mp.nranks == 2
        t = paddle.to_tensor(np.stack([np.full((3,), 1.0, np.float32),
                                       np.full((3,), 5.0, np.float32)]))
        dist.all_reduce(t, group=mp)
        np.testing.assert_allclose(t.numpy(), np.full((2, 3), 6.0))
        dp = hcg.get_data_parallel_group()
        t = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(4, 1))
        dist.broadcast(t, src=2, group=dp)
        np.testing.assert_allclose(t.numpy(), np.full((4, 1), 2.0))
    finally:
        fleet.set_hybrid_communicate_group(prev)


def test_batch_isend_irecv_distinct_tensors():
    """Two sends with different payload buffers both transfer (review fix)."""
    a = _rank_tensor((2,))           # slice r = r
    b = _rank_tensor((2,), base=50.) # slice r = 50 + r
    ops = [
        dist.P2POp(dist.isend, a, peer=1, rank=0),
        dist.P2POp(dist.irecv, a, peer=0, rank=1),
        dist.P2POp(dist.isend, b, peer=3, rank=2),
        dist.P2POp(dist.irecv, b, peer=2, rank=3),
    ]
    dist.batch_isend_irecv(ops)
    got_a, got_b = a.numpy(), b.numpy()
    np.testing.assert_allclose(got_a[1], np.full((2,), 0.0))   # from rank 0
    np.testing.assert_allclose(got_b[3], np.full((2,), 52.0))  # from rank 2
    np.testing.assert_allclose(got_a[0], np.full((2,), 0.0))   # untouched
    np.testing.assert_allclose(got_b[2], np.full((2,), 52.0))

"""TCPStore (SURVEY D3) + paddle.distributed.rpc (D10). The RPC test
spawns three real worker processes — the reference's multi-process RPC
test pattern (test/rpc/)."""
import os
import subprocess
import sys
import textwrap
import threading

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.distributed.store import TCPStore


def test_tcp_store_basics():
    master = TCPStore("127.0.0.1", 0, world_size=2, is_master=True)
    client = TCPStore("127.0.0.1", master.port, world_size=2)
    master.set("k", b"v")
    assert client.get("k") == b"v"
    assert client.add("ctr", 3) == 3
    assert master.add("ctr", 2) == 5
    assert client.delete_key("k") is True
    with pytest.raises(TimeoutError):
        client.get("missing", timeout=0.2)
    # blocking get is released by a later set
    got = []
    t = threading.Thread(
        target=lambda: got.append(master.get("late", timeout=5)))
    t.start()
    client.set("late", b"now")
    t.join(timeout=5)
    assert got == [b"now"]
    client.close()
    master.close()


def test_tcp_store_barrier():
    master = TCPStore("127.0.0.1", 0, world_size=3, is_master=True)
    clients = [TCPStore("127.0.0.1", master.port) for _ in range(2)]
    done = []

    def arrive(s, i):
        s.barrier("b1", 3, timeout=10)
        done.append(i)

    ts = [threading.Thread(target=arrive, args=(s, i))
          for i, s in enumerate(clients)]
    for t in ts:
        t.start()
    assert not done  # blocked until the third participant arrives
    master.barrier("b1", 3, timeout=10)
    for t in ts:
        t.join(timeout=10)
    assert sorted(done) == [0, 1]
    for s in clients + [master]:
        s.close()


WORKER = """
import os
import paddle_tpu.distributed.rpc as rpc

def add(a, b):
    return a + b

def whoami():
    return rpc.get_current_worker_info().name

def boom():
    raise ValueError("remote boom")

rank = int(os.environ["PADDLE_TRAINER_ID"])
me = rpc.init_rpc(f"worker{rank}", rank=rank, world_size=3,
                  master_endpoint=os.environ["MASTER"])
infos = rpc.get_all_worker_infos()
assert len(infos) == 3, infos
assert rpc.get_worker_info("worker0").rank == 0

# every worker calls its right neighbor
peer = f"worker{(rank + 1) % 3}"
assert rpc.rpc_sync(peer, add, args=(rank, 10)) == rank + 10
fut = rpc.rpc_async(peer, whoami)
assert fut.wait(15) == peer

if rank == 0:
    try:
        rpc.rpc_sync("worker1", boom)
        raise SystemExit("expected remote exception")
    except ValueError as e:
        assert "remote boom" in str(e)

rpc.shutdown()
print("RPC_OK", rank)
"""


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_rpc_three_workers(tmp_path):
    script = tmp_path / "rpc_worker.py"
    script.write_text(textwrap.dedent(WORKER))
    port = _free_port()
    procs = []
    try:
        for rank in range(3):
            env = {**os.environ, "PYTHONPATH": _REPO_ROOT,
                   "PADDLE_TRAINER_ID": str(rank),
                   "MASTER": f"127.0.0.1:{port}"}
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env, cwd=str(tmp_path),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = [p.communicate(timeout=120)[0] for p in procs]
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out
            assert "RPC_OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_native_store_backend():
    """The C++ store server (native/store.cc) builds and serves the same
    protocol; full op matrix + barrier against it."""
    from paddle_tpu.distributed import native
    if native._load() is None:
        pytest.skip("no C++ toolchain for the native store")
    master = TCPStore("127.0.0.1", 0, is_master=True)
    assert master.is_native
    client = TCPStore("127.0.0.1", master.port)
    master.set("k", b"v1")
    assert client.get("k") == b"v1"
    client.set("k", b"v2")
    assert master.get("k") == b"v2"
    assert client.add("ctr", 5) == 5
    assert master.add("ctr", -2) == 3
    assert client.delete_key("k") is True
    assert client.delete_key("k") is False
    with pytest.raises(TimeoutError):
        client.get("missing", timeout=0.2)
    got = []
    t = threading.Thread(
        target=lambda: got.append(master.get("late", timeout=5)))
    t.start()
    client.set("late", b"now")
    t.join(timeout=5)
    assert got == [b"now"]
    for it in range(2):  # reusable barrier on the native server
        ts = threading.Thread(
            target=lambda: master.barrier("nb", 2, timeout=10))
        ts.start()
        client.barrier("nb", 2, timeout=10)
        ts.join(5)
        assert not ts.is_alive()
    client.close()
    master.close()


def test_python_fallback_store(monkeypatch):
    monkeypatch.setenv("PDTPU_NATIVE_STORE", "0")
    master = TCPStore("127.0.0.1", 0, is_master=True)
    assert not master.is_native
    client = TCPStore("127.0.0.1", master.port)
    master.set("k", b"v")
    assert client.get("k") == b"v"
    assert client.add("c", 2) == 2
    client.close()
    master.close()

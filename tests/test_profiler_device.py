"""Profiler + device API tests (reference patterns:
``test/legacy_test/test_profiler.py``, ``test_newprofiler.py``,
``test_cuda_max_memory_allocated.py``)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler


def test_scheduler_state_machine():
    sched = profiler.make_scheduler(closed=1, ready=1, record=2, repeat=1,
                                    skip_first=1)
    S = profiler.ProfilerState
    states = [sched(i) for i in range(7)]
    assert states == [S.CLOSED, S.CLOSED, S.READY, S.RECORD,
                      S.RECORD_AND_RETURN, S.CLOSED, S.CLOSED]


def test_profiler_records_ops_and_exports(tmp_path):
    traced = []
    p = profiler.Profiler(
        targets=[profiler.ProfilerTarget.CPU],
        scheduler=profiler.make_scheduler(closed=0, ready=0, record=2,
                                          repeat=1),
        on_trace_ready=lambda prof: traced.append(prof))
    p.reset()
    x = paddle.to_tensor(np.ones((4, 4), "float32"))
    with p:
        for _ in range(2):
            with profiler.RecordEvent("my_span"):
                y = x @ x + x
            p.step(num_samples=4)
    assert traced, "on_trace_ready never fired"
    table = p.summary()
    assert "my_span" in table
    assert "matmul" in table  # per-op dispatch events recorded
    out = p.export(str(tmp_path / "trace.json"))
    data = json.load(open(out))
    names = {e["name"] for e in data["traceEvents"]}
    assert "my_span" in names and "matmul" in names
    bench = p.benchmark()
    assert bench["steps"] == 2 and bench["ips"] > 0


def test_profiler_hook_removed_after_stop():
    from paddle_tpu.core import dispatch
    assert dispatch._profile_hook is None
    p = profiler.Profiler().start()
    assert dispatch._profile_hook is not None
    p.stop()
    assert dispatch._profile_hook is None


def test_device_api():
    dev = paddle.get_device()
    assert ":" in dev
    assert paddle.device.device_count() >= 1
    assert paddle.device.get_all_device_type()
    paddle.device.synchronize()
    # memory stats: zeros on backends without memory_stats, ints otherwise
    assert isinstance(paddle.device.memory_allocated(), int)
    assert paddle.device.max_memory_allocated() >= \
        paddle.device.memory_allocated() or \
        paddle.device.max_memory_allocated() == 0


def test_event_elapsed_time():
    e1 = paddle.device.Event()
    e2 = paddle.device.Event()
    e1.record()
    x = paddle.to_tensor(np.ones((64, 64), "float32"))
    for _ in range(3):
        x = x @ x * 0.01
    e2.record()
    assert e1.elapsed_time(e2) > 0
    s = paddle.device.current_stream()
    s.synchronize()
    assert s.query()


def test_profiler_bracket_survives_raising_step():
    """ISSUE 8 satellite: a step that raises inside a RECORD window
    must not leave the global dispatch hook installed (it would poison
    every later dispatch) nor the device tracer running."""
    from paddle_tpu.core import dispatch

    x = paddle.to_tensor(np.ones((4, 4), "float32"))
    with pytest.raises(RuntimeError, match="step blew up"):
        with profiler.Profiler(targets=[profiler.ProfilerTarget.CPU,
                                        profiler.ProfilerTarget.TPU]):
            _ = x @ x
            raise RuntimeError("step blew up")
    assert dispatch._profile_hook is None
    # dispatch still works and a fresh profiler can open a new window
    _ = x + 1
    with profiler.Profiler(targets=[profiler.ProfilerTarget.CPU]) as p:
        _ = x * 2
        p.step()
    assert dispatch._profile_hook is None


def test_profiler_raising_trace_handler_clears_state():
    """A raising ``on_trace_ready`` handler must still tear the record
    window down: hook cleared, profiler deregistered, state CLOSED."""
    from paddle_tpu.core import dispatch

    def bad_handler(prof):
        raise ValueError("handler blew up")

    p = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                          on_trace_ready=bad_handler)
    p.start()
    assert dispatch._profile_hook is not None
    with pytest.raises(ValueError, match="handler blew up"):
        p.stop()
    assert dispatch._profile_hook is None
    assert profiler._active_profiler is None
    assert p.current_state is profiler.ProfilerState.CLOSED
    # step()-driven handler failures fail safe too: window down, not
    # re-armed for a caller that just saw an exception
    p2 = profiler.Profiler(
        targets=[profiler.ProfilerTarget.CPU],
        scheduler=profiler.make_scheduler(closed=0, ready=0, record=1,
                                          repeat=2),
        on_trace_ready=bad_handler)
    p2.start()
    with pytest.raises(ValueError, match="handler blew up"):
        p2.step()
    assert dispatch._profile_hook is None
    assert p2.current_state is profiler.ProfilerState.CLOSED
    p2.stop()
    assert profiler._active_profiler is None


def test_profiler_spans_feed_event_ring():
    """RecordEvent spans land in the observability event ring (one
    stream for chrome traces and flight records)."""
    from paddle_tpu import observability as obs

    old = paddle.get_flags("metrics")["metrics"]
    paddle.set_flags({"metrics": True})
    try:
        obs.events.clear()
        with profiler.RecordEvent("ring_span"):
            pass
        assert any(e["kind"] == "span" and e["name"] == "ring_span"
                   for e in obs.tail())
    finally:
        paddle.set_flags({"metrics": old})

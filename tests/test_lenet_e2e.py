"""End-to-end LeNet-5 training slice — driver config 1 (SURVEY §6, BASELINE
config "LeNet-5 MNIST dygraph"). Synthetic data; asserts the loss drops,
proving the full stack: DataLoader -> nn -> autograd -> optimizer.
"""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer as opt
from paddle_tpu.io import DataLoader, Dataset


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
        )
        self.fc = nn.Sequential(
            nn.Linear(400, 120),
            nn.Linear(120, 84),
            nn.Linear(84, num_classes),
        )

    def forward(self, x):
        x = self.features(x)
        x = pt.flatten(x, 1)
        return self.fc(x)


class SynthMNIST(Dataset):
    """Deterministic separable synthetic digits."""

    def __init__(self, n=256):
        rng = np.random.RandomState(0)
        self.labels = rng.randint(0, 10, n)
        base = rng.randn(10, 1, 28, 28).astype("float32")
        self.images = (base[self.labels]
                       + 0.1 * rng.randn(n, 1, 28, 28)).astype("float32")

    def __getitem__(self, i):
        return self.images[i], self.labels[i].astype("int64")

    def __len__(self):
        return len(self.labels)


def test_lenet_training_loss_drops():
    pt.seed(42)
    model = LeNet()
    optim = opt.Adam(learning_rate=1e-3, parameters=model.parameters())
    loader = DataLoader(SynthMNIST(), batch_size=64, shuffle=True,
                        drop_last=True)
    losses = []
    for epoch in range(3):
        for img, label in loader:
            logits = model(img)
            loss = F.cross_entropy(logits, label)
            loss.backward()
            optim.step()
            optim.clear_grad()
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_lenet_eval_accuracy_improves():
    pt.seed(7)
    model = LeNet()
    optim = opt.Momentum(0.01, 0.9, parameters=model.parameters())
    ds = SynthMNIST(128)
    loader = DataLoader(ds, batch_size=32, shuffle=True)

    def accuracy():
        model.eval()
        imgs = pt.to_tensor(ds.images)
        preds = np.argmax(model(imgs).numpy(), -1)
        model.train()
        return (preds == ds.labels).mean()

    acc0 = accuracy()
    for _ in range(3):
        for img, label in loader:
            loss = F.cross_entropy(model(img), label)
            loss.backward()
            optim.step()
            optim.clear_grad()
    acc1 = accuracy()
    assert acc1 > max(acc0, 0.5)

"""RNN family tests — torch-parity for cell math (same gate conventions
as the reference), scan-vs-eager consistency, masking, bidirectional,
jit compilation (reference patterns: ``test/rnn/test_rnn_nets.py``,
``test_rnn_cells.py``)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn

R = np.random.default_rng(9)


def _copy_lstm_cell_to_torch(cell, tcell):
    tcell.weight_ih.data = torch.tensor(np.asarray(cell.weight_ih._read()))
    tcell.weight_hh.data = torch.tensor(np.asarray(cell.weight_hh._read()))
    tcell.bias_ih.data = torch.tensor(np.asarray(cell.bias_ih._read()))
    tcell.bias_hh.data = torch.tensor(np.asarray(cell.bias_hh._read()))


def test_lstm_cell_torch_parity():
    paddle.seed(0)
    cell = nn.LSTMCell(8, 16)
    tcell = torch.nn.LSTMCell(8, 16)
    _copy_lstm_cell_to_torch(cell, tcell)
    x = R.normal(size=(4, 8)).astype("float32")
    h0 = R.normal(size=(4, 16)).astype("float32")
    c0 = R.normal(size=(4, 16)).astype("float32")
    out, (h, c) = cell(paddle.to_tensor(x),
                       (paddle.to_tensor(h0), paddle.to_tensor(c0)))
    th, tc = tcell(torch.tensor(x), (torch.tensor(h0), torch.tensor(c0)))
    np.testing.assert_allclose(np.asarray(h._read()), th.detach().numpy(),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(c._read()), tc.detach().numpy(),
                               atol=1e-5)


def test_gru_cell_reference_formula():
    """Paddle GRU differs from torch (candidate uses r*(W_hc h + b_hc));
    verify directly against the documented formula."""
    paddle.seed(1)
    cell = nn.GRUCell(5, 7)
    x = R.normal(size=(3, 5)).astype("float32")
    h = R.normal(size=(3, 7)).astype("float32")
    out, h2 = cell(paddle.to_tensor(x), paddle.to_tensor(h))
    wi = np.asarray(cell.weight_ih._read())
    wh = np.asarray(cell.weight_hh._read())
    bi = np.asarray(cell.bias_ih._read())
    bh = np.asarray(cell.bias_hh._read())

    def sig(v):
        return 1 / (1 + np.exp(-v))

    xg, hg = x @ wi.T + bi, h @ wh.T + bh
    H = 7
    r = sig(xg[:, :H] + hg[:, :H])
    z = sig(xg[:, H:2 * H] + hg[:, H:2 * H])
    cand = np.tanh(xg[:, 2 * H:] + r * hg[:, 2 * H:])
    want = z * h + (1 - z) * cand
    np.testing.assert_allclose(np.asarray(h2._read()), want, atol=1e-5)


def test_rnn_wrapper_matches_manual_loop():
    paddle.seed(2)
    cell = nn.SimpleRNNCell(4, 6)
    rnn = nn.RNN(cell)
    x = R.normal(size=(2, 5, 4)).astype("float32")
    outs, h = rnn(paddle.to_tensor(x))
    # manual eager stepping through the same cell
    hm = paddle.to_tensor(np.zeros((2, 6), "float32"))
    for t in range(5):
        o, hm = cell(paddle.to_tensor(x[:, t]), hm)
        np.testing.assert_allclose(np.asarray(outs._read())[:, t],
                                   np.asarray(o._read()), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h._read()),
                               np.asarray(hm._read()), atol=1e-5)


def test_lstm_multilayer_bidirectional_shapes_and_grad():
    paddle.seed(3)
    lstm = nn.LSTM(8, 16, num_layers=2, direction="bidirect")
    x = paddle.to_tensor(R.normal(size=(4, 10, 8)).astype("float32"))
    x.stop_gradient = False
    out, (h, c) = lstm(x)
    assert tuple(out.shape) == (4, 10, 32)
    assert tuple(h.shape) == (4, 4, 16)  # [layers*dirs, B, H]
    out.sum().backward()
    assert x.grad is not None
    for p in lstm.parameters():
        assert p.grad is not None, "missing grad on RNN weight"


def test_sequence_length_masking():
    paddle.seed(4)
    gru = nn.GRU(3, 5)
    x = R.normal(size=(2, 6, 3)).astype("float32")
    sl = np.array([4, 6], "int32")
    out, h = gru(paddle.to_tensor(x),
                 sequence_length=paddle.to_tensor(sl))
    o = np.asarray(out._read())
    # outputs past each length are zeroed
    assert np.abs(o[0, 4:]).max() == 0.0
    assert np.abs(o[1]).max() > 0.0
    # final state for batch 0 equals output at t=3
    np.testing.assert_allclose(np.asarray(h._read())[0, 0], o[0, 3],
                               atol=1e-6)


def test_lstm_under_jit():
    paddle.seed(5)
    lstm = nn.LSTM(4, 8)
    opt = paddle.optimizer.Adam(parameters=lstm.parameters())

    @paddle.jit.to_static
    def step(x, y):
        out, _ = lstm(x)
        loss = ((out - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(R.normal(size=(2, 6, 4)).astype("float32"))
    y = paddle.to_tensor(np.zeros((2, 6, 8), "float32"))
    losses = [float(step(x, y)) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_birnn_and_custom_cell():
    paddle.seed(6)

    class MyCell(nn.RNNCellBase):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(3, 4)

        @property
        def state_shape(self):
            return (4,)

        def forward(self, x, states=None):
            if states is None:
                states = self.get_initial_states(x)
            h = paddle.tanh(self.lin(x) + states)
            return h, h

    rnn = nn.RNN(MyCell())
    x = paddle.to_tensor(R.normal(size=(2, 5, 3)).astype("float32"))
    out, h = rnn(x)
    assert tuple(out.shape) == (2, 5, 4)

    bi = nn.BiRNN(nn.GRUCell(3, 4), nn.GRUCell(3, 4))
    out, (hf, hb) = bi(x)
    assert tuple(out.shape) == (2, 5, 8)

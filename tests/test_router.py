"""Fleet-scale serving router (ISSUE 17; ``inference/router.py``).

Acceptance model: a :class:`FleetRouter` spreading a workload over N
replicas must produce EXACTLY the greedy token streams of one engine
serving the same requests — placement, tenant fair share, dead-replica
requeue and elastic scale-out are all scheduling, and scheduling may
never move a token (greedy decode is batch-invariant).  On top of the
bitwise bar: affinity must measurably beat round-robin on cache-hit
tokens, a starved tenant must keep its weighted share, a killed
replica's requests must all complete on survivors under exactly one
coded PDT-E024 flight record, and a sustained fleet-SLO burn must
admit the standby.

Shares the session ``serving_gpt`` and the serving-suite geometry, so
the compiled programs come off the session model's cache.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.core import errors
from paddle_tpu.inference import (ContinuousBatchingEngine, DisaggServer,
                                  FleetRouter, TenantSpec)
from paddle_tpu.observability import watchdog as wdog
from paddle_tpu.observability.slo import parse_slo
from paddle_tpu.resilience import faults

from test_serving_engine import _assert_pool_conserved

# ONE geometry for the whole module — matches test_serving_engine's /
# test_distserve's, so every replica engine reuses the session model's
# compiled serving programs
KW = dict(max_slots=2, page_size=8, max_seq_len=32, decode_window=4,
          prefill_chunk=8, q_block=2)


@pytest.fixture(scope="module")
def gpt(serving_gpt):
    return serving_gpt


@pytest.fixture()
def metrics_on():
    """Force the metrics flag on for one test, restoring after."""
    old = paddle.get_flags("metrics")["metrics"]
    paddle.set_flags({"metrics": True})
    yield
    paddle.set_flags({"metrics": old})


def _workload(seed=0, sizes=(5, 9, 3, 12), new=(6, 4, 7, 5)):
    rng = np.random.default_rng(seed)
    return ([rng.integers(0, 96, (n,)).astype(np.int32)
             for n in sizes], list(new))


@pytest.fixture(scope="module")
def refs(gpt):
    """Single-engine streams for the shared workload — the bar every
    fleet variant must hit bitwise."""
    prompts, new = _workload()
    eng = ContinuousBatchingEngine(gpt, **KW)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    _assert_pool_conserved(eng)
    return prompts, new, [done[r].sequence for r in rids]


def _fleet_pool_conserved(router):
    for rep in router._replicas:
        if rep.state != "dead" and hasattr(rep.engine, "_free_pages"):
            _assert_pool_conserved(rep.engine)


# ========================================================== routing ==

def test_fleet_bitwise_vs_single_engine(gpt, refs):
    """The basic spread: N replicas serve the single-engine workload
    token-identically, every replica pool conserved."""
    prompts, new, seqs = refs
    r = FleetRouter(gpt, replicas=3, replica_kwargs=KW)
    rids = [r.add_request(p, n) for p, n in zip(prompts, new)]
    done = r.run()
    assert sorted(done) == sorted(rids)
    for rid, ref in zip(rids, seqs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    st = r.stats
    assert st["placed"] == len(prompts) and st["deaths"] == 0
    assert st["replicas_live"] == 3 and st["queue_depth"] == 0
    _fleet_pool_conserved(r)


def test_eager_admission_errors(gpt):
    """Fleet-level PDT-E016/PDT-E017: an unservable request rejects at
    submission; a full router queue sheds under the reject policy."""
    r = FleetRouter(gpt, replicas=2, replica_kwargs=KW, max_queue=2)
    with pytest.raises(errors.PageBudgetError) as ei:
        r.add_request(np.arange(20, dtype=np.int32), 64)
    assert "PDT-E016" in str(ei.value)
    p = np.arange(4, dtype=np.int32)
    r.add_request(p, 2)
    r.add_request(p, 2)
    with pytest.raises(errors.QueueFullError) as ei:
        r.add_request(p, 2)
    assert "PDT-E017" in str(ei.value)
    assert r.stats["rejected"] == 2
    r.run()


def test_affinity_beats_round_robin(gpt):
    """Shared-prefix storm over 3 replicas, leaders warmed first:
    cache-aware placement routes each group member to the replica
    holding its prefix pages, so the fleet-wide cache-hit tokens beat
    round-robin's scatter — with identical token streams (placement
    is scheduling, not semantics)."""
    rng = np.random.default_rng(7)
    groups = []
    for _ in range(3):
        prefix = rng.integers(0, 96, 8).astype(np.int32)
        groups.append([np.concatenate([
            prefix, rng.integers(0, 96, 6).astype(np.int32)])
            for _ in range(3)])
    leaders = [g[0] for g in groups]
    # group-consecutive storm order: round-robin NECESSARILY scatters
    # each group's members across replicas, affinity concentrates them
    storm = [p for g in groups for p in g[1:]]

    def drive(affinity):
        r = FleetRouter(gpt, replicas=3, replica_kwargs=KW,
                        affinity=affinity)
        for p in leaders:
            r.add_request(p, 4)
        done = r.run()
        pending = list(storm)
        while r.has_work or pending:
            if pending:
                r.add_request(pending.pop(0), 4)
            for c in r.step():
                done[c.request_id] = c
        hits = sum(rep.engine.stats["cache_hit_tokens"]
                   for rep in r._replicas)
        _fleet_pool_conserved(r)
        return r, done, hits

    ra, da, hits_aff = drive(True)
    rr, dr, hits_rr = drive(False)
    assert sorted(da) == sorted(dr)
    for rid in da:
        np.testing.assert_array_equal(da[rid].sequence,
                                      dr[rid].sequence)
    # every storm member's 8-token prefix is cached SOMEWHERE after
    # the warm phase: affinity must collect them all, round-robin
    # lands one only when the rotation happens to line up
    assert hits_aff == 8 * len(storm)
    assert hits_aff > hits_rr
    assert ra.stats["affinity_hits"] >= len(storm)


def test_fair_share_starved_tenant_floor(gpt):
    """Skewed-tenant storm through ONE replica (2 slots): a flooding
    weight-1 tenant vs an equal-weight light tenant.  Stride
    scheduling must interleave the light tenant's requests into the
    early placements instead of parking them behind the flood — the
    starved tenant's completions land within its fair window, not
    after the storm drains."""
    rng = np.random.default_rng(3)
    storm = [rng.integers(0, 96, 6).astype(np.int32) for _ in range(8)]
    light = [rng.integers(0, 96, 6).astype(np.int32) for _ in range(2)]
    r = FleetRouter(
        gpt, replicas=1, replica_kwargs=KW,
        tenants=[TenantSpec("storm", weight=1.0),
                 TenantSpec("light", weight=1.0)])
    storm_rids = [r.add_request(p, 4, tenant="storm") for p in storm]
    light_rids = [r.add_request(p, 4, tenant="light") for p in light]
    order = []
    while r.has_work:
        order.extend(c.request_id for c in r.step())
    assert sorted(order) == sorted(storm_rids + light_rids)
    # equal weights, equal per-request cost: the light tenant's 2
    # requests finish in the first half of the drain even though the
    # storm tenant enqueued 8 requests first
    first_half = set(order[:len(order) // 2])
    assert set(light_rids) <= first_half
    # strict priority dominates weights: a priority-0 tenant admitted
    # into the same storm places before any remaining storm request
    r2 = FleetRouter(
        gpt, replicas=1, replica_kwargs=KW,
        tenants=[TenantSpec("storm", weight=10.0, priority=1),
                 TenantSpec("vip", weight=1.0, priority=0)])
    srids = [r2.add_request(p, 4, tenant="storm") for p in storm]
    vrid = r2.add_request(light[0], 4, tenant="vip")
    order2 = []
    while r2.has_work:
        order2.extend(c.request_id for c in r2.step())
    # the vip request overtakes every storm request still queued at
    # its arrival (the first 4 rode the 2*max_slots admission window)
    assert order2.index(vrid) < len(order2) - 2


# ================================================= replica failure ==

def test_replica_kill_mid_decode_bitwise(gpt, refs, tmp_path,
                                         monkeypatch, metrics_on):
    """THE acceptance drill: 3 replicas, one killed mid-decode.  Every
    affected request completes on a survivor bitwise-identical to the
    unfaulted run, no request is lost, nothing hangs, and exactly one
    coded flight record (PDT-E024) is written."""
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    prompts, new, seqs = refs
    faults.clear()
    obs.events.clear()
    try:
        r = FleetRouter(gpt, replicas=3, replica_kwargs=KW)
        rids = [r.add_request(p, n) for p, n in zip(prompts, new)]
        done, steps = {}, 0
        while r.has_work:
            if steps == 2:       # mid-decode: kill a loaded replica
                victim = max((rep for rep in r._replicas
                              if rep.state == "live"),
                             key=lambda rep: len(rep.rids))
                assert victim.rids, "drill needs in-flight work"
                faults.inject("router_replica_lost", victim.name)
            for c in r.step():
                done[c.request_id] = c
            steps += 1
            assert steps < 2000, "kill drill wedged"
    finally:
        faults.clear()
    assert sorted(done) == sorted(rids)          # no request lost
    for rid, ref in zip(rids, seqs):             # ...and none moved
        np.testing.assert_array_equal(done[rid].sequence, ref)
    st = r.stats
    assert st["deaths"] == 1 and st["replicas_dead"] == 1
    assert st["requeues"] >= 1 and st["generation"] == 1
    _fleet_pool_conserved(r)
    recs = [f for f in sorted(os.listdir(tmp_path))
            if f.endswith(".json") and not f.endswith(".trace.json")]
    assert len(recs) == 1                # exactly one flight record
    rec = json.load(open(os.path.join(tmp_path, recs[0])))
    assert rec["reason"] == "router_replica_lost"
    assert rec["error_code"] == "PDT-E024"
    assert rec["extra"]["replica"] == victim.name
    assert rec["extra"]["requeued"] == st["requeues"]


def test_all_replicas_dead_raises_coded(gpt):
    """Losing the LAST replica with work queued surfaces PDT-E024
    instead of a silent hang (no standby to fail over to)."""
    faults.clear()
    try:
        r = FleetRouter(gpt, replicas=1, replica_kwargs=KW)
        r.add_request(np.arange(5, dtype=np.int32), 4)
        faults.inject("router_replica_lost", "r0")
        with pytest.raises(errors.ReplicaLostError) as ei:
            for _ in range(10):
                r.step()
    finally:
        faults.clear()
    assert "PDT-E024" in str(ei.value)


def test_dispatch_transient_retries(gpt, refs):
    """A transient placement failure retries inside the dispatch
    envelope (counter moves) without killing the replica; the request
    still completes bitwise."""
    prompts, new, seqs = refs
    faults.clear()
    try:
        r = FleetRouter(gpt, replicas=2, replica_kwargs=KW,
                        dispatch_retries=3)
        rids = [r.add_request(p, n) for p, n in zip(prompts, new)]
        faults.inject("router_dispatch_transient", str(rids[0]),
                      times=2)
        done = r.run()
    finally:
        faults.clear()
    assert sorted(done) == sorted(rids)
    for rid, ref in zip(rids, seqs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    assert r.stats["retries"] == 2 and r.stats["deaths"] == 0


def test_dispatch_exhausted_kills_and_requeues(gpt, refs):
    """A placement that fails past the retry budget declares the
    replica dead; the request (and the replica's whole load) requeues
    to the survivor and completes bitwise."""
    prompts, new, seqs = refs
    faults.clear()
    try:
        r = FleetRouter(gpt, replicas=2, replica_kwargs=KW,
                        dispatch_retries=1)
        rids = [r.add_request(p, n) for p, n in zip(prompts, new)]
        # exactly the retry budget (dispatch_retries=1 -> 2 attempts):
        # the replica dies, and the survivor's re-placement is clean
        faults.inject("router_dispatch_transient", str(rids[0]),
                      times=2)
        done = r.run()
    finally:
        faults.clear()
    assert sorted(done) == sorted(rids)
    for rid, ref in zip(rids, seqs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    assert r.stats["deaths"] == 1


# =============================================== elastic scale-out ==

def _breach_specs():
    """A queue-wait objective tiny enough that real traffic breaches
    it immediately, with second-scale windows so the fake clock can
    walk the burn rates over threshold in a few steps."""
    specs = parse_slo("queue_p95_ms=0.001")
    for s in specs:
        s.fast_window_s = 1.0
        s.slow_window_s = 4.0
    return specs


def test_scaleout_on_burn_breach_and_scalein(gpt, metrics_on):
    """Sustained fleet-SLO burn admits the standby (warm model, cold
    cache); holding recovered for scalein_hold_s drains it back to
    standby once idle.  Deterministic clock — no sleeps."""
    t = [0.0]
    r = FleetRouter(gpt, replicas=1, replica_kwargs=KW, standby=1,
                    fleet_slo=_breach_specs(), clock=lambda: t[0],
                    scalein_hold_s=5.0)
    assert r.replica_states() == {"r0": "live", "r1": "standby"}
    rng = np.random.default_rng(5)
    for _ in range(8):
        r.add_request(rng.integers(0, 96, 6).astype(np.int32), 4)
    done = {}
    for _ in range(300):
        t[0] += 0.5
        for c in r.step():
            done[c.request_id] = c
        if not r.has_work:
            break
    assert len(done) == 8
    assert r.stats["scaleouts"] == 1
    assert r.replica_states()["r1"] == "live"
    # recovery: no traffic, SLO recovers, hold elapses -> drain back
    for _ in range(40):
        t[0] += 1.0
        r.step()
        if r.replica_states()["r1"] == "standby":
            break
    assert r.replica_states() == {"r0": "live", "r1": "standby"}
    assert r.stats["scaleins"] == 1


def test_failover_to_standby_without_slo(gpt, refs):
    """Total live-fleet loss admits the standby immediately — failover
    needs no SLO verdict — and the workload completes bitwise."""
    prompts, new, seqs = refs
    faults.clear()
    try:
        r = FleetRouter(gpt, replicas=1, replica_kwargs=KW, standby=1)
        rids = [r.add_request(p, n) for p, n in zip(prompts, new)]
        r.step()
        faults.inject("router_replica_lost", "r0")
        done = r.run()
    finally:
        faults.clear()
    assert sorted(done) == sorted(rids)
    for rid, ref in zip(rids, seqs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    assert r.stats["deaths"] == 1
    assert r.replica_states() == {"r0": "dead", "r1": "live"}


def test_scaleout_stall_degrades_gracefully(gpt, metrics_on):
    """The router_scaleout_stall drill: a wedged standby admission is
    interrupted by the watchdog (coded PDT-E020 flight), counted as a
    scaleout failure, and the fleet keeps serving on the live
    replicas — no hang, no loss."""
    faults.clear()
    t = [0.0]
    try:
        r = FleetRouter(gpt, replicas=1, replica_kwargs=KW, standby=1,
                        fleet_slo=_breach_specs(), clock=lambda: t[0],
                        scaleout_timeout_ms=150.0)
        # EVERY admission attempt wedges (cooldown retries included)
        faults.inject("router_scaleout_stall", "r1", times=1000)
        rng = np.random.default_rng(5)
        rids = [r.add_request(rng.integers(0, 96, 6).astype(np.int32),
                              4) for _ in range(6)]
        done = {}
        for _ in range(300):
            t[0] += 0.5
            for c in r.step():
                done[c.request_id] = c
            if not r.has_work:
                break
    finally:
        faults.clear()
    assert sorted(done) == sorted(rids)       # served on the live rep
    assert r.stats["scaleout_failures"] >= 1
    assert r.stats["scaleouts"] == 0
    assert r.replica_states()["r1"] == "standby"
    assert wdog.armed() == []


# ============================================== metrics-off parity ==

def test_metrics_off_bitwise_noop(gpt, refs):
    """PDTPU_METRICS off: identical routing decisions, identical token
    streams, and the always-on ``stats`` counters still count (the
    engine contract extends to the fleet).  SLO judgment — and with it
    SLO-driven scaling — is off, exactly like the engines'."""
    prompts, new, seqs = refs
    old = paddle.get_flags("metrics")["metrics"]

    def drive():
        r = FleetRouter(gpt, replicas=2, replica_kwargs=KW)
        rids = [r.add_request(p, n) for p, n in zip(prompts, new)]
        return r, rids, r.run()

    try:
        paddle.set_flags({"metrics": True})
        r_on, rids_on, done_on = drive()
        paddle.set_flags({"metrics": False})
        r_off, rids_off, done_off = drive()
    finally:
        paddle.set_flags({"metrics": old})
    for a, b in zip(rids_on, rids_off):
        np.testing.assert_array_equal(done_on[a].sequence,
                                      done_off[b].sequence)
    san = lambda d: {k: v for k, v in d.items()}
    assert san(r_on.stats) == san(r_off.stats)
    for rid, ref in zip(rids_on, seqs):
        np.testing.assert_array_equal(done_on[rid].sequence, ref)


# ============================================= rpc-backed replica ==

def test_rpc_replica_loopback(gpt, refs):
    """One replica fronted by the rpc proxy (loopback worker): the
    fleet surface — placement, cached-prefix queries, stats — crosses
    the wire and the streams stay bitwise."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.inference import RpcReplica, register_replica_worker
    from paddle_tpu.inference.router import _REPLICA_WORKERS
    prompts, new, seqs = refs
    rpc.init_rpc("fleet_w0", rank=0, world_size=1)
    try:
        remote_eng = ContinuousBatchingEngine(gpt, **KW)
        register_replica_worker("fleet_w0", remote_eng)
        local_eng = ContinuousBatchingEngine(gpt, **KW)
        r = FleetRouter(replicas=[local_eng,
                                  RpcReplica(to="fleet_w0")])
        rids = [r.add_request(p, n) for p, n in zip(prompts, new)]
        done = r.run()
        assert sorted(done) == sorted(rids)
        for rid, ref in zip(rids, seqs):
            np.testing.assert_array_equal(done[rid].sequence, ref)
        # both sides actually served (the proxy carried real traffic)
        assert remote_eng.stats["admitted"] >= 1
        assert local_eng.stats["admitted"] >= 1
        assert remote_eng.stats["admitted"] + \
            local_eng.stats["admitted"] == len(prompts)
    finally:
        _REPLICA_WORKERS.clear()
        rpc.shutdown()


# ============================ requeue accounting (ISSUE 17 sat. 2) ==

def test_requeue_accounting_not_double_counted(gpt):
    """Regression: the ``engine_decode_worker_lost`` requeue used to
    re-count ``prefill_tokens_requested`` for the same logical request
    (inflating the prefill_saved_frac denominator).  Pinned counter
    pair on the forced-loss drill: the fault run's REQUESTED total
    equals the clean run's exactly — demand is counted once per
    logical request — while COMPUTED alone grows by the genuine
    recompute; on the clean run computed stays net of prefix-cache
    hits (computed == requested - cache_hit_tokens)."""
    prompts, new = _workload()

    def drive(fault):
        faults.clear()
        if fault:
            faults.inject("engine_decode_worker_lost", "*", times=1)
        try:
            srv = DisaggServer(gpt, prefill_kwargs=dict(KW),
                               decode_kwargs=dict(KW))
            rids = [srv.add_request(p, n)
                    for p, n in zip(prompts, new)]
            done = srv.run()
        finally:
            faults.clear()
        agg = lambda k: sum(e.stats[k] for e in srv.prefill_group)
        return (agg("prefill_tokens_requested"),
                agg("prefill_tokens_computed"),
                agg("cache_hit_tokens"), srv.stats["requeues"],
                rids, done)

    req_c, comp_c, hit_c, rq_c, rids_c, done_c = drive(False)
    req_f, comp_f, hit_f, rq_f, rids_f, done_f = drive(True)
    assert rq_c == 0 and rq_f >= 1          # the drill actually fired
    assert req_f == req_c                   # demand counted ONCE
    assert comp_c == req_c - hit_c          # computed net of hits
    assert comp_f > comp_c                  # recompute is real work
    for a, b in zip(rids_c, rids_f):        # ...and moved no tokens
        np.testing.assert_array_equal(done_c[a].sequence,
                                      done_f[b].sequence)


def test_router_requeue_demand_counted_once(gpt):
    """The same invariant through the ROUTER's requeue path: a killed
    replica's requests re-prefill on a survivor with ``requeue=True``,
    so the fleet-wide requested total matches the unfaulted run."""
    prompts, new = _workload(seed=2)

    def drive(kill):
        faults.clear()
        try:
            r = FleetRouter(gpt, replicas=2, replica_kwargs=KW)
            rids = [r.add_request(p, n)
                    for p, n in zip(prompts, new)]
            done, steps = {}, 0
            while r.has_work:
                if kill and steps == 2:
                    faults.inject("router_replica_lost", "r0")
                for c in r.step():
                    done[c.request_id] = c
                steps += 1
                assert steps < 2000
        finally:
            faults.clear()
        req = sum(rep.engine.stats["prefill_tokens_requested"]
                  for rep in r._replicas)
        return req, rids, done

    req_c, rids_c, done_c = drive(False)
    req_f, rids_f, done_f = drive(True)
    assert req_f == req_c
    for a, b in zip(rids_c, rids_f):
        np.testing.assert_array_equal(done_c[a].sequence,
                                      done_f[b].sequence)


# ======================================================== benches ==

def test_serving_bench_fleet_smoke(gpt):
    """The serving_bench ``fleet`` row on the CPU tiny model: affinity
    measurably beats round-robin on cache-hit tokens, the replica-kill
    recovery is lossless and bitwise, and no survivor leaks pages
    (absolute times are TPU claims)."""
    import sys
    sys.path.insert(0, "/root/repo/benchmarks")
    import serving_bench as sb
    cfg = gpt.cfg
    row = sb._measure_fleet(cfg, gpt, slots=2, prompt_len=16,
                            new_tokens=5, shared_groups=2,
                            group_size=4, n_light=2, light_new=3,
                            page_size=8, decode_window=4,
                            prefill_chunk=8, max_seq_len=32,
                            q_block=2, warm=False)
    assert row["cache_hit_frac_affinity"] > row["cache_hit_frac_rr"]
    assert row["outputs_equal"]
    assert row["pages_leaked"] == 0
    assert row["requeued"] >= 1 and row["deaths"] == 1
    assert row["recover_ms"] > 0.0
    assert row["goodput_fleet4"] == 1.0

"""Coverage for the op-surface completion batch (ops/extra.py) plus the
custom C++ op extension (SURVEY C31)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.testing import OpSpec, run_op_specs

R = np.random.default_rng(23)


def f32(*shape):
    return R.normal(size=shape).astype("float32")


def test_extra_ops_table():
    x = f32(3, 4)
    specs = [
        OpSpec("diff", ops.diff, lambda a: np.diff(a), [x]),
        OpSpec("unflatten", ops.unflatten,
               lambda a, axis, shape: a.reshape(3, 2, 2), [x],
               {"axis": 1, "shape": [2, 2]}),
        OpSpec("hstack", lambda a, b: ops.hstack([a, b]),
               lambda a, b: np.hstack([a, b]), [f32(2, 3), f32(2, 3)]),
        OpSpec("vstack", lambda a, b: ops.vstack([a, b]),
               lambda a, b: np.vstack([a, b]), [f32(2, 3), f32(2, 3)]),
        OpSpec("dstack", lambda a, b: ops.dstack([a, b]),
               lambda a, b: np.dstack([a, b]), [f32(2, 3), f32(2, 3)]),
        OpSpec("column_stack", lambda a, b: ops.column_stack([a, b]),
               lambda a, b: np.column_stack([a, b]), [f32(4), f32(4)]),
        OpSpec("atleast_2d", ops.atleast_2d, np.atleast_2d, [f32(5)]),
        OpSpec("block_diag", lambda a, b: ops.block_diag([a, b]),
               lambda a, b: np.block([
                   [a, np.zeros((2, 3), "float32")],
                   [np.zeros((3, 2), "float32"), b]]),
               [f32(2, 2), f32(3, 3)]),
        OpSpec("signbit", ops.signbit, np.signbit, [x], bf16=False),
        OpSpec("isneginf", ops.isneginf, np.isneginf,
               [np.array([1.0, -np.inf], "float32")], bf16=False),
        OpSpec("isposinf", ops.isposinf, np.isposinf,
               [np.array([1.0, np.inf], "float32")], bf16=False),
        OpSpec("ldexp", ops.ldexp, lambda a, b: np.ldexp(a, b.astype(int)),
               [f32(4), np.array([0, 1, 2, 3], "int32")], bf16=False),
        OpSpec("bucketize", ops.bucketize,
               lambda a, seq: np.searchsorted(seq, a),
               [f32(4), np.sort(f32(6))], bf16=False),
        OpSpec("take", ops.take,
               lambda a, i: np.take(a.ravel(), i),
               [x, np.array([0, 5, 11], "int32")], bf16=False),
        OpSpec("vander", ops.vander, np.vander, [f32(4)], rtol=1e-4),
        OpSpec("trapezoid", ops.trapezoid,
               lambda y: np.trapezoid(y, axis=-1)
               if hasattr(np, "trapezoid") else np.trapz(y, axis=-1),
               [x], rtol=1e-4),
        OpSpec("dist", ops.dist,
               lambda a, b: np.linalg.norm((a - b).ravel()),
               [x, f32(3, 4)], rtol=1e-4),
        OpSpec("renorm", ops.renorm,
               lambda a, p, axis, max_norm: a * np.minimum(
                   1.0, max_norm / (np.abs(a ** p).sum(
                       axis=1, keepdims=True) ** (1 / p) + 1e-7)),
               [np.abs(f32(3, 4)) + 1], {"p": 2.0, "axis": 0,
                                         "max_norm": 1.0}, rtol=1e-3),
        OpSpec("fill_diagonal", ops.fill_diagonal,
               lambda a, value: _fd_ref(a, value), [f32(4, 4)],
               {"value": 7.0}),
        OpSpec("crop", ops.crop,
               lambda a, shape, offsets: a[1:3, 1:4], [f32(4, 5)],
               {"shape": [2, 3], "offsets": [1, 1]}),
        OpSpec("slice_scatter", ops.slice_scatter,
               lambda a, v, axes, starts, ends, strides: _ss_ref(a, v),
               [f32(4, 6), np.ones((4, 2), "float32")],
               {"axes": [1], "starts": [2], "ends": [4], "strides": [1]}),
        OpSpec("index_fill", ops.index_fill,
               lambda a, idx, axis, value: _if_ref(a, idx, value),
               [f32(4, 3), np.array([0, 2], "int64")],
               {"axis": 0, "value": 5.0}, bf16=False),
    ]
    run_op_specs(specs)


def _fd_ref(a, value):
    out = a.copy()
    np.fill_diagonal(out, value)
    return out


def _ss_ref(a, v):
    out = a.copy()
    out[:, 2:4] = v
    return out


def _if_ref(a, idx, value):
    out = a.copy()
    out[idx] = value
    return out


def test_multiplex_and_combinations():
    a = f32(4, 3)
    b = f32(4, 3)
    idx = np.array([[0], [1], [1], [0]], "int32")
    out = ops.multiplex([paddle.to_tensor(a), paddle.to_tensor(b)],
                        paddle.to_tensor(idx))
    want = np.where(idx == 0, a, b)
    np.testing.assert_allclose(np.asarray(out._read()), want)

    c = ops.combinations(paddle.to_tensor(np.arange(4, dtype="float32")))
    np.testing.assert_allclose(
        np.asarray(c._read()),
        [[0, 1], [0, 2], [0, 3], [1, 2], [1, 3], [2, 3]])


def test_frexp_and_cumulative_trapezoid():
    x = np.array([0.5, 4.0, -3.0], "float32")
    m, e = ops.frexp(paddle.to_tensor(x))
    mr, er = np.frexp(x)
    np.testing.assert_allclose(np.asarray(m._read()), mr)
    np.testing.assert_array_equal(np.asarray(e._read()), er)
    y = f32(2, 5)
    got = ops.cumulative_trapezoid(paddle.to_tensor(y))
    import scipy.integrate as si
    np.testing.assert_allclose(np.asarray(got._read()),
                               si.cumulative_trapezoid(y, axis=-1),
                               atol=1e-5)


def test_fill_diagonal_tensor_and_offsets():
    x = np.zeros((3, 5), "float32")
    y = np.array([1.0, 2.0, 3.0], "float32")
    out = ops.fill_diagonal_tensor(paddle.to_tensor(x),
                                   paddle.to_tensor(y), offset=1)
    want = x.copy()
    want[[0, 1, 2], [1, 2, 3]] = y
    np.testing.assert_allclose(np.asarray(out._read()), want)


def test_edit_distance():
    inp = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], "int64")
    lab = np.array([[1, 2, 4, 0], [5, 6, 7, 8]], "int64")
    d, n = ops.edit_distance(paddle.to_tensor(inp), paddle.to_tensor(lab),
                             normalized=False,
                             input_length=paddle.to_tensor(
                                 np.array([4, 4], "int64")),
                             label_length=paddle.to_tensor(
                                 np.array([3, 4], "int64")))
    # [1,2,3,4] vs [1,2,4]: one deletion = 1; identical: 0
    np.testing.assert_allclose(np.asarray(d._read()), [[1.0], [0.0]])
    assert int(np.asarray(n._read())[0]) == 2


def test_cpp_extension_custom_op(tmp_path):
    """SURVEY C31: compile a C++ op with g++, run it through the dispatch
    funnel (jax.pure_callback host execution)."""
    src = tmp_path / "my_ops.cc"
    src.write_text("""
        #include <cstdint>
        extern "C" void my_relu(const float* in, float* out, int64_t n) {
            for (int64_t i = 0; i < n; ++i)
                out[i] = in[i] > 0.f ? in[i] : 0.f;
        }
    """)
    from paddle_tpu.utils import cpp_extension
    mod = cpp_extension.load("my_ops", str(src),
                             build_directory=str(tmp_path))
    my_relu = mod.bind_elementwise("my_relu")
    x = f32(3, 4)
    out = my_relu(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(out._read()),
                               np.maximum(x, 0), atol=1e-6)


def test_group_sharded_namespace():
    import paddle_tpu.distributed as dist
    assert callable(dist.sharding.group_sharded_parallel)
    assert callable(dist.sharding.save_group_sharded_model)
